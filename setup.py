"""Packaging (reference setup.py: op prebuild via DS_BUILD_* envs,
version stamping). Native host ops prebuild with DS_BUILD_OPS=1 (the JIT
builder handles the default path)."""

import os

from setuptools import find_packages, setup

if os.environ.get("DS_BUILD_OPS", "0") == "1":
    from deepspeed_tpu.ops.op_builder.builder import ALL_OPS
    for name, builder in ALL_OPS.items():
        b = builder()
        if b.is_compatible():
            print(f"prebuilding native op {name}...")
            b.build()

setup(
    name="deepspeed_tpu",
    version="0.1.0",
    description="TPU-native large-scale training framework "
                "(DeepSpeed-compatible surface on JAX/XLA/Pallas)",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    include_package_data=True,
    install_requires=["jax", "flax", "numpy"],
    entry_points={
        "console_scripts": [
            "deepspeed=deepspeed_tpu.launcher.runner:main",
            "ds=deepspeed_tpu.launcher.runner:main",
            "ds_report=deepspeed_tpu.env_report:cli_main",
            "ds_elastic=deepspeed_tpu.elasticity.elastic_cli:main",
            "ds_ssh=deepspeed_tpu.launcher.ds_ssh:main",
        ],
    },
    python_requires=">=3.10",
)
