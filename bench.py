"""Benchmark: GPT-2 training throughput through the full engine on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` compares achieved model-FLOPs TFLOPS/chip against the
reference's headline transformer-kernel efficiency claim of 64 TFLOPS/GPU
(docs/_posts/2020-05-28-fastest-bert-training.md:16, BASELINE.md).
"""

import json
import time

import jax
import numpy as np

REFERENCE_TFLOPS_PER_GPU = 64.0  # DeepSpeed's best published per-device claim


def model_flops_per_token(cfg, seq_len):
    """6*N_active + attention term, the standard training-FLOPs model."""
    n = cfg.num_params()
    # 6ND for matmuls + 12*L*E*S for attention scores/values
    return 6 * n + 12 * cfg.n_layer * cfg.n_embd * seq_len


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, GPT2LMHeadModel, PRESETS, synthetic_batch)
    from deepspeed_tpu.utils import groups

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = PRESETS["gpt2"]          # 125M
        batch_size, seq_len, steps = 16, 1024, 20
    else:  # CPU smoke fallback so the bench always emits a line
        cfg = GPT2Config(vocab_size=2048, n_positions=256, n_embd=128,
                         n_layer=2, n_head=4)
        batch_size, seq_len, steps = 2, 128, 3

    groups.destroy()
    groups.initialize()
    ds_config = {
        "train_batch_size": batch_size,
        "train_micro_batch_size_per_gpu": batch_size // max(
            1, groups.get_data_parallel_world_size()),
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 0},
        "bf16": {"enabled": True},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg), config=ds_config,
        sample_batch=synthetic_batch(batch_size, seq_len, cfg.vocab_size))

    batch = synthetic_batch(batch_size, seq_len, cfg.vocab_size, seed=1)
    engine.train_batch(batch=batch)  # compile
    jax.block_until_ready(engine.state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        engine.train_batch(batch=batch)
    jax.block_until_ready(engine.state.params)
    dt = time.perf_counter() - t0

    tokens_per_s = batch_size * seq_len * steps / dt
    tflops = tokens_per_s * model_flops_per_token(cfg, seq_len) / 1e12
    n_chips = jax.device_count()
    tflops_per_chip = tflops / n_chips

    print(json.dumps({
        "metric": f"gpt2-{'125M' if on_tpu else 'toy'} train TFLOPS/chip "
                  f"(bs={batch_size} seq={seq_len} bf16, full engine)",
        "value": round(tflops_per_chip, 2),
        "unit": "TFLOPS/chip",
        "vs_baseline": round(tflops_per_chip / REFERENCE_TFLOPS_PER_GPU, 3),
    }))


if __name__ == "__main__":
    main()
