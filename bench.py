"""Benchmark: GPT-2 training throughput through the full engine on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

``vs_baseline`` compares achieved model-FLOPs TFLOPS/chip against the
reference's headline transformer-kernel efficiency claim of 64 TFLOPS/GPU
(docs/_posts/2020-05-28-fastest-bert-training.md:16, BASELINE.md). ``mfu``
is the same number as a fraction of the chip's advertised bf16 peak.

Hardened against the remote-compile tunnel (round 1 failed on
"remote_compile: read body closed" mid-compile): a persistent compilation
cache is enabled so a retried run re-uses every already-compiled program,
and every compile-triggering call is retried on transient errors.

Config via env:
  BENCH_MODEL  gpt2 (default) | gpt2-medium | gpt2-xl
  BENCH_ZERO   ZeRO stage (default 0 for gpt2, 3 for gpt2-xl)
  BENCH_PEAK_TFLOPS  chip bf16 peak for MFU (default 197, TPU v5e)
  BENCH_HEALTH  1 (default) rides the telemetry.health stats inside the
                timed step and writes HEALTH_BENCH.json; 0 removes the
                stats epilogue from the compiled program entirely
  BENCH_GOODPUT 1 (default) arms the wall-clock goodput ledger (host-side
                only, no ticks inside the timed loop) and writes
                GOODPUT_BENCH.json; 0 disables it
  BENCH_ANATOMY 0 (default) | 1 profiles 3 post-warmup steps OUTSIDE the
                timed loop with jax.profiler, post-processes the trace
                into measured per-category device seconds
                (ANATOMY_BENCH.json, gitignored) and emits the
                measured-vs-predicted drift in the JSON line
  BENCH_PREFETCH 1 (default) feeds the timed loop through the async input
                pipeline (data_prefetch: host collate workers + device
                double-buffering, runtime/prefetch.py) so the H2D copy
                overlaps the step and BENCH_*.json tracks the overlap via
                the ledger's input_wait fraction; 0 restores the fixed
                pre-placed batch path byte-identically
"""

import json
import os
import time

import jax
import numpy as np

REFERENCE_TFLOPS_PER_GPU = 64.0  # DeepSpeed's best published per-device claim
TRANSIENT_MARKERS = (
    "remote_compile", "read body", "response body closed", "UNAVAILABLE",
    "DEADLINE_EXCEEDED", "Connection reset", "Socket closed", "RST_STREAM",
)


def _enable_compile_cache():
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_compilation_cache")
    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # cache is an optimisation, never fatal
        print(f"# compilation cache unavailable: {e}", flush=True)


def _retry(fn, what, attempts=4, sleep_s=10.0):
    """Retry compile-triggering calls on transient tunnel/compile errors."""
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — filter by message below
            msg = str(e)
            transient = any(m in msg for m in TRANSIENT_MARKERS)
            if not transient or i == attempts - 1:
                raise
            print(f"# transient error in {what} (attempt {i + 1}/{attempts}):"
                  f" {msg.splitlines()[0][:200]}", flush=True)
            time.sleep(sleep_s)


_PROBE_FN = None


def _probe_tunnel(n=5):
    """Round-trip a trivial compiled dispatch n times; return median ms.

    Distinguishes "engine slow" from "environment slow": through the remote
    tunnel a dispatch+device_get pair costs ~100 ms when healthy; a degraded
    tunnel (the BENCH_r03 failure mode: identical code measured 62 then 2.2
    TFLOPS hours apart) shows up here as a 10-100x larger round trip.
    """
    global _PROBE_FN
    import jax.numpy as jnp
    x = jnp.ones((8, 128), jnp.float32)
    if _PROBE_FN is None:  # one jitted fn for all probes: compile ONCE
        _PROBE_FN = jax.jit(lambda a: a * 2.0 + 1.0)
        _retry(lambda: jax.device_get(_PROBE_FN(x)), "tunnel-probe compile")
    samples = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.device_get(_PROBE_FN(x))
        samples.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(samples))


def _wait_for_healthy_tunnel(threshold_ms=1000.0, attempts=6, sleep_s=30.0):
    """Probe until the round trip is under threshold.

    Returns (healthy, last_rtt_ms, history). ``healthy=False`` means every
    probe exceeded the threshold — callers must surface that in the output
    rather than publish a silently poisoned number.
    """
    history = []
    for i in range(attempts):
        rtt = _probe_tunnel()
        history.append(round(rtt, 1))
        if rtt < threshold_ms:
            return True, rtt, history
        print(f"# tunnel degraded: trivial round trip {rtt:.0f} ms "
              f"(attempt {i + 1}/{attempts}); sleeping {sleep_s:.0f}s",
              flush=True)
        if i < attempts - 1:
            time.sleep(sleep_s)
    return False, history[-1], history


def _probe_link_bandwidth(mb=32):
    """Measure host<->device bandwidth each way with one bulk array.
    Remote tunnels can be wildly asymmetric (axon: ~830 MB/s H2D,
    ~4 MB/s D2H), which decides whether host-offload training is even
    measurable here."""
    import numpy as _np
    a = _np.ones((mb, 1 << 20), _np.uint8)
    t0 = time.perf_counter()
    x = jax.device_put(a)
    x.block_until_ready()
    t1 = time.perf_counter()
    jax.device_get(x)
    t2 = time.perf_counter()
    return mb / max(t1 - t0, 1e-9), mb / max(t2 - t1, 1e-9)


def main():
    _enable_compile_cache()

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, GPT2LMHeadModel, PRESETS, synthetic_batch)
    from deepspeed_tpu.utils import groups

    # (batch, seq, timed steps, default ZeRO stage) per supported model
    bench_shapes = {
        "gpt2": (16, 1024, 20, 0),          # 125M
        "gpt2-medium": (8, 1024, 10, 1),    # 350M
        "gpt2-xl": (4, 1024, 5, 3),         # 1.5B: needs ZeRO-3 (+offload)
        # the reference's 64-TFLOPS headline config: BERT-large MLM,
        # seq 128, (Fused)Lamb (docs/_tutorials/bert-pretraining.md:387)
        "bert-large": (64, 128, 20, 0),
        # BASELINE config #4 (MoE-GPT recipe): GPT-2 small dims, 8 experts
        # top-1 on alternate layers — single-chip ep=1 (experts vmapped)
        "gpt2-moe": (8, 1024, 10, 0),
        # BASELINE config #3's sparse_attn half: BERT-large with the
        # block-sparse Fixed layout (Pallas SDD/softmax/DSD kernels) at
        # the long-seq regime the reference's 10-16x claim targets;
        # block 64 (not the torch default 16) so tiles half-fill the MXU
        "bert-sparse": (4, 2048, 10, 0),
    }
    on_tpu = jax.default_backend() == "tpu"
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))
    if on_tpu:
        # default: GPT-2 350M ZeRO-1 (BASELINE.json config #2) — the best
        # measured headline on one chip (125M stage-0 underfills the MXU;
        # larger models exceed this chip's compile/memory limits)
        name = os.environ.get("BENCH_MODEL", "gpt2-medium")
        if name not in bench_shapes:
            raise SystemExit(f"BENCH_MODEL must be one of "
                             f"{sorted(bench_shapes)}, got {name!r}")
        batch_size, seq_len, steps, default_zero = bench_shapes[name]
        zero_stage = int(os.environ.get("BENCH_ZERO", str(default_zero)))
        batch_size = int(os.environ.get("BENCH_BS", str(batch_size)))
        # BENCH_SEQ: long-context rows (flash keeps memory O(seq), so a
        # single chip trains seq >> the preset's 1024)
        seq_len = int(os.environ.get("BENCH_SEQ", str(seq_len)))
    else:  # CPU smoke fallback so the bench always emits a line
        name = "gpt2-toy"
        batch_size, seq_len, steps = 2, 128, 3
        zero_stage = 0

    if name in ("bert-large", "bert-sparse"):
        from deepspeed_tpu.models.bert import (PRESETS as BERT_PRESETS,
                                               BertForPreTraining,
                                               synthetic_mlm_batch)
        cfg = BERT_PRESETS["bert-large"]
        import dataclasses as _dc
        if name == "bert-sparse":
            sb = int(os.environ.get("BENCH_SPARSE_BLOCK", "64"))
            # BENCH_SPARSE_WINDOW: local-window tokens (round-5 long-seq
            # rows use window 1024 @ block 128 — the fused kernel's
            # MXU-sized tiling; default 256 keeps the round-4 rows
            # comparable)
            win = int(os.environ.get("BENCH_SPARSE_WINDOW", "256"))
            assert win % sb == 0 and sb <= win, (
                f"BENCH_SPARSE_BLOCK={sb}: must divide the {win}-token "
                "local window (BENCH_SPARSE_WINDOW)")
            cfg = _dc.replace(cfg, sparse_attention_mode="fixed",
                              sparse_block=sb,
                              sparse_num_local_blocks=win // sb,
                              sparse_num_global_blocks=1)
        if seq_len > cfg.max_position_embeddings:
            # widen the position table — otherwise XLA silently clamps
            # out-of-range position gathers and benches a degenerate model
            cfg = _dc.replace(cfg, max_position_embeddings=seq_len)
        if os.environ.get("BENCH_REMAT", "") == "1":
            cfg = _dc.replace(cfg, remat=True)
        model = BertForPreTraining(cfg)
        optimizer = {"type": "Lamb", "params": {"lr": 1e-4, "fused": True}}
        # BENCH_MLM=masked: the reference pretraining data format
        # (max_predictions_per_seq gathered positions) — the MLM head runs
        # on P<<S positions instead of the full sequence
        masked_fmt = os.environ.get("BENCH_MLM", "").lower() == "masked"

        def make_batch(seed):
            return synthetic_mlm_batch(batch_size, seq_len, cfg.vocab_size,
                                       seed=seed,
                                       masked_positions_format=masked_fmt)
    else:
        if name == "gpt2-moe":
            import dataclasses as _dc
            cfg = _dc.replace(PRESETS["gpt2"], moe_num_experts=8,
                              moe_expert_interval=2,
                              moe_k=int(os.environ.get("BENCH_MOE_K", "1")),
                              moe_capacity_factor=float(os.environ.get(
                                  "BENCH_MOE_CF", "1.25")),
                              moe_dispatch_impl=os.environ.get(
                                  "BENCH_MOE_DISPATCH", "scatter"))
        else:
            cfg = (PRESETS[name] if name in PRESETS else
                   GPT2Config(vocab_size=2048, n_positions=256, n_embd=128,
                              n_layer=2, n_head=4))
        import dataclasses as _dc
        if seq_len > cfg.n_positions:
            cfg = _dc.replace(cfg, n_positions=seq_len)
        if os.environ.get("BENCH_REMAT", "") == "1":
            # activation rematerialisation: longest contexts trade ~30%
            # recompute flops for O(layers) less activation HBM
            cfg = _dc.replace(cfg, remat=True)
        if os.environ.get("BENCH_ATTN_MODE"):
            # e.g. BENCH_ATTN_MODE=sparse:1024/128 — causal block-sparse
            # GPT rows (PERF.md round 5)
            cfg = _dc.replace(
                cfg, attention_mode=os.environ["BENCH_ATTN_MODE"])
        model = GPT2LMHeadModel(cfg)
        optimizer = {"type": "Adam", "params": {"lr": 1e-4}}
        if os.environ.get("BENCH_FUSED_OPT", "") == "1":
            optimizer["params"]["fused"] = True  # Pallas fused-Adam path
        if os.environ.get("BENCH_OPT_SWEEP", "") == "1":
            # whole-state one-sweep Adam (clip+update fused over
            # contiguous flat state — ops/adam fused_adam_sweep)
            optimizer["params"]["sweep"] = True

        def make_batch(seed):
            return synthetic_batch(batch_size, seq_len, cfg.vocab_size,
                                   seed=seed)

    n_layer = getattr(cfg, "n_layer", None) or \
        getattr(cfg, "num_hidden_layers", None)
    width = getattr(cfg, "n_embd", None) or getattr(cfg, "hidden_size", None)
    if not n_layer or not width:
        raise SystemExit(
            f"bench: config {type(cfg).__name__} exposes neither "
            "n_layer/n_embd nor num_hidden_layers/hidden_size; the "
            "attention FLOPs term would silently vanish")

    groups.destroy()
    groups.initialize()
    offload_mode = os.environ.get("BENCH_OFFLOAD", "").lower()
    layered = offload_mode == "layered"
    # Telemetry rides along by default (BENCH_TELEMETRY=0 disables): spans
    # + compile watch + metrics cost ~µs against ms-scale steps, and the
    # artifact answers "why was this bench slow" (retraces, stalls)
    # without a rerun. Files land in telemetry/ next to this script; a
    # summary JSON (TELEMETRY_BENCH.json) is written next to BENCH_*.json.
    telemetry_on = os.environ.get("BENCH_TELEMETRY", "1").lower() in (
        "1", "true", "yes")
    # Health stats ride inside the compiled step (norm reductions over the
    # grad/param trees — a few extra HBM sweeps against a matmul-dominated
    # step). Cadence stays 0 -> steps_per_print (pinned to 1e9 here), so
    # the timed loop NEVER pays a stats fetch; health_report() does one
    # on-demand fetch after the rounds for the HEALTH_BENCH.json artifact.
    health_on = telemetry_on and os.environ.get(
        "BENCH_HEALTH", "1").lower() in ("1", "true", "yes")
    # Goodput ledger: pure host-side wall-clock bookkeeping (a few dict
    # adds per step, no device syncs). Cadence 0 -> steps_per_print
    # (pinned to 1e9), so the timed loop never pays a window tick; the
    # report is forced once after the rounds for GOODPUT_BENCH.json —
    # the true end-to-end denominator (compile + stalls + warmup)
    # behind the steady-state headline number. Profiler capture stays
    # off: an escalation mid-round must not perturb the timed loop.
    goodput_on = telemetry_on and os.environ.get(
        "BENCH_GOODPUT", "1").lower() in ("1", "true", "yes")
    # Async input pipeline: the timed loop pulls batches through a
    # prefetched deepspeed_io loader (host collate workers + the device
    # stage's overlapped device_put) instead of re-feeding one pre-placed
    # batch — a real loader's steady state, with the H2D copy off the
    # critical path. The layered engine keeps its own host loop.
    prefetch_on = (not layered) and os.environ.get(
        "BENCH_PREFETCH", "1").lower() in ("1", "true", "yes")
    # Bucketed gradient-collective overlap (comm_overlap): requested by
    # default; the engine arms it only inside its envelope (dp > 1,
    # zero <= 1, dense grads), so the single-chip headline emits
    # comm_overlap=false and multichip rounds track the bucketing.
    comm_overlap_req = (not layered) and os.environ.get(
        "BENCH_COMM_OVERLAP", "1").lower() in ("1", "true", "yes")
    # Fleet flight recorder (telemetry/fleet.py): OFF by default — the
    # shipper's per-step cost is two clock reads, but the bench headline
    # must stay byte-identical to previous rounds unless asked. When on,
    # the fleet cadence stays 0 -> steps_per_print (pinned to 1e9), so
    # the timed loop never ships or fetches a desync checksum; one
    # forced report after the rounds writes FLEET_BENCH.json.
    fleet_on = telemetry_on and os.environ.get(
        "BENCH_FLEET", "0").lower() in ("1", "true", "yes")
    # Step anatomy (telemetry/step_anatomy.py): OFF by default — the
    # profiler capture runs 3 EXTRA steps after the timed loop (outside
    # it, so the headline is untouched) but jax.profiler's one-time init
    # is seconds of host work. When on, ANATOMY_BENCH.json (gitignored —
    # machine-local measured timings, unlike the committed demo
    # artifact) holds the measured per-category device seconds and the
    # JSON line carries the measured-vs-predicted drift.
    anatomy_on = telemetry_on and (not layered) and os.environ.get(
        "BENCH_ANATOMY", "0").lower() in ("1", "true", "yes")
    # HBM residency observatory (telemetry/memory_observatory.py): OFF by
    # default — the memory cadence stays 0 -> steps_per_print (pinned to
    # 1e9), so the timed loop never fetches a device-memory profile; one
    # forced report after the rounds writes MEMORY_BENCH.json (gitignored
    # — machine-local measured bytes; the committed example is the CLI
    # demo's) and the JSON line carries hbm_peak_bytes + watermark_drift.
    memory_on = telemetry_on and os.environ.get(
        "BENCH_MEMORY", "0").lower() in ("1", "true", "yes")
    bench_dir = os.path.dirname(os.path.abspath(__file__))
    telemetry_dir = os.path.join(bench_dir, "telemetry")
    ds_config = {
        "train_batch_size": batch_size,
        "train_micro_batch_size_per_gpu": batch_size // max(
            1, groups.get_data_parallel_world_size()),
        "steps_per_print": 10 ** 9,
        "optimizer": optimizer,
        "zero_optimization": {"stage": zero_stage},
        "bf16": {"enabled": True},
        "data_prefetch": {"enabled": prefetch_on, "depth": 2},
        "comm_overlap": {"enabled": comm_overlap_req},
        # scalar fan-out fires at steps_per_print cadence, which the
        # bench pins to 1e9 — the jsonl/prom sinks would only ever hold
        # empty/partial data, so keep them off and snapshot the registry
        # into TELEMETRY_BENCH.json instead
        "telemetry": {"enabled": telemetry_on,
                      "output_path": telemetry_dir,
                      "job_name": f"bench_{name}",
                      "jsonl": False, "prometheus": False,
                      # own the compiled step artifact (AOT dispatch) so
                      # the post-bench census/MFU cross-check reads the
                      # program that actually ran — zero extra compiles
                      "cost_explorer": {"enabled": True},
                      "health": {"enabled": health_on},
                      "goodput": {"enabled": goodput_on,
                                  "profiler_capture": False},
                      "fleet": {"enabled": fleet_on,
                                "run_dir": os.path.join(telemetry_dir,
                                                        "fleet_run")},
                      "memory": {"enabled": memory_on}},
    }
    if layered:
        # beyond-HBM training: params streamed from host RAM layer by
        # layer (Zero3OffloadEngine) — the only way 1.5B+ params train on
        # this one chip (PERF.md: monolithic gpt2-xl hard-OOMs at 22.8 GB)
        assert name.startswith("gpt2"), "layered offload bench is GPT-2"
        ds_config["zero_optimization"] = {
            "stage": 3, "offload_param": {"device": "cpu"}}
        from deepspeed_tpu.models.gpt2 import gpt2_offload_layers
        model = gpt2_offload_layers(cfg)
        # Layered training is host-link-bound by design (every step moves
        # 2 full param sweeps H2D + one grad sweep D2H). Probe BOTH link
        # directions first: on an asymmetric link (the axon tunnel
        # measures ~830 MB/s H2D but ~4 MB/s D2H) a timed step would take
        # tens of minutes and measure the link, not the engine. In that
        # case emit the probe + transfer-budget roofline as the artifact
        # instead of hanging.
        h2d_MBps, d2h_MBps = _probe_link_bandwidth()
        n_est = int(12 * n_layer * width * width       # blocks
                    + 2 * ((cfg.vocab_size + 127) // 128 * 128) * width
                    + seq_len * width)
        bytes_h2d = 2 * n_est * 2            # bf16 params, fwd+bwd sweeps
        bytes_d2h = 2 * n_est                # bf16 grads
        host_adam_s = 28 * n_est / 10e9      # masters+moments RAM sweep
        flops_step = (6 * n_est + 12 * n_layer * width * seq_len) \
            * batch_size * seq_len
        proj_step_s = (bytes_h2d / (h2d_MBps * 1e6)
                       + bytes_d2h / (d2h_MBps * 1e6)
                       + host_adam_s + flops_step / 100e12)
        max_step_s = float(os.environ.get("BENCH_LAYERED_MAX_STEP_S", 120))
        if proj_step_s > max_step_s:
            tflops = flops_step / proj_step_s / 1e12
            print(json.dumps({
                "metric": f"{name} layered-offload (beyond-HBM) projected "
                          f"TFLOPS/chip — TRANSFER-BOUND ENVIRONMENT, "
                          f"not engine speed",
                "value": round(tflops, 2),
                "unit": "TFLOPS/chip (projected)",
                "vs_baseline": round(tflops / REFERENCE_TFLOPS_PER_GPU, 3),
                "measured": False,
                "probe_h2d_MBps": round(h2d_MBps, 1),
                "probe_d2h_MBps": round(d2h_MBps, 1),
                "projected_step_s": round(proj_step_s, 1),
                "why": "per-step transfer budget (2 param sweeps H2D + "
                       "grad sweep D2H) exceeds BENCH_LAYERED_MAX_STEP_S "
                       "on this link; correctness of the layered engine "
                       "is TPU-verified at small scale "
                       "(tests/unit/test_param_offload.py)",
            }))
            return
    elif offload_mode in ("1", "true", "yes"):
        ds_config["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}

    init_kw = dict(model=model, config=ds_config, sample_batch=make_batch(0))
    if layered:
        init_kw["input_fn"] = lambda b: b["input_ids"]
    engine, _, _, _ = _retry(
        lambda: deepspeed_tpu.initialize(**init_kw), "engine init")
    if layered:
        st = engine.store
        n_params = sum(h.size for i in range(len(engine.layers))
                       for h in st.host_leaves(i))
    else:
        n_params = sum(x.size for x in jax.tree.leaves(engine.state.params))

    batch = make_batch(1)
    if not layered:
        # stage the batch on device once: a real training loop's loader
        # prefetches, so the timed path should not pay the host->device
        # transfer latency per step (through the remote tunnel that is
        # 1-2 x ~100 ms RTT per step — it dominated the step time)
        batch = jax.tree.map(jax.device_put, batch)
        jax.block_until_ready(batch)

    data_iter = None
    if prefetch_on:
        # the real-loader path the staged batch above approximates: per-
        # row synthetic dataset -> deepspeed_io (collate in the host
        # workers) -> device stage device_puts batch N+1 while step N
        # runs. The epoch must outlast EVERY pull the bench can make
        # (compile + warmup + up to max_attempts rounds of `steps`) — a
        # wrap rebuilds the pipeline, a cold start mid-measurement —
        # while the distinct-batch pool stays small (rows index into it
        # modulo, so memory is 8 batches regardless of epoch length).
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader

        class _RowDataset:
            POOL = 8

            def __init__(self, n_batches):
                self._batches = [
                    jax.tree.map(np.asarray, make_batch(100 + i))
                    for i in range(self.POOL)]
                self._rows = batch_size * n_batches

            def __len__(self):
                return self._rows

            def __getitem__(self, i):
                b, r = divmod(i % (batch_size * self.POOL), batch_size)
                return jax.tree.map(lambda a: a[r], self._batches[b])

        # 8 == max_attempts below; +4 covers compile + warmup + slack
        data_iter = RepeatingLoader(engine.deepspeed_io(
            _RowDataset(steps * 8 + 4), num_local_io_workers=2))

    def _feed():
        if data_iter is not None:
            return engine.train_batch(data_iter=data_iter)
        return engine.train_batch(batch=batch)

    # jax.block_until_ready is NOT a reliable barrier through the axon
    # tunnel (it returned immediately in round 3, inflating TFLOPS 5x);
    # transferring a scalar out of the final state forces completion of
    # the whole dispatched chain. The layered engine is a host loop whose
    # train_batch is itself synchronous per layer; its loss transfer is
    # the barrier.
    _last_loss = [None]

    def _sync():
        if layered:
            if _last_loss[0] is not None:
                jax.device_get(_last_loss[0])
        else:
            jax.device_get(engine.state.step)

    def _compile_step():
        _last_loss[0] = _feed()
        _sync()

    _retry(_compile_step, "first train_batch compile")

    # Pre-flight: the r03 driver run recorded 2.2 TFLOPS from the same code
    # that measured 62-106 in-session — a silently degraded tunnel. Probe a
    # trivial round trip, wait for health, and put the evidence in the JSON.
    healthy, rtt_ms, rtt_history = _wait_for_healthy_tunnel()

    def _warmup():
        for _ in range(2):
            _last_loss[0] = _feed()
        _sync()
    _retry(_warmup, "warmup steps")

    # Median-of-N rounds. Each round dispatches `steps` async steps and
    # syncs once (per-step sync would add one tunnel RTT ~100 ms to every
    # step). Stall filtering is against the minimum over ALL rounds seen so
    # far — including earlier ones — so a degraded FIRST round is evicted
    # retroactively the moment a faster round lands (guards the case where
    # the tunnel starts poisoned and recovers mid-bench).
    target_rounds, max_attempts = 3, 8
    all_rounds = []
    for attempt in range(max_attempts):
        t0 = time.perf_counter()
        for _ in range(steps):
            _last_loss[0] = _feed()
        _sync()
        step_ms = (time.perf_counter() - t0) / steps * 1e3
        all_rounds.append(step_ms)
        best = min(all_rounds)
        accepted = [r for r in all_rounds if r <= 2.5 * best]
        if len(accepted) >= target_rounds:
            break
        if step_ms > 2.5 * best:
            print(f"# stall detected: round at {step_ms:.1f} ms/step vs "
                  f"best {best:.1f}; re-probing tunnel", flush=True)
            ok, re_rtt, re_hist = _wait_for_healthy_tunnel()
            rtt_history.extend(re_hist)
            if not ok:
                healthy = False
                print(f"# tunnel still degraded after re-probe "
                      f"({re_rtt:.0f} ms); abandoning further rounds",
                      flush=True)
                break
    best = min(all_rounds)
    round_step_ms = [r for r in all_rounds if r <= 2.5 * best]
    stalled_rounds = [round(r, 1) for r in all_rounds
                      if r > 2.5 * best]

    med_step_ms = float(np.median(round_step_ms))
    dt = med_step_ms * steps / 1e3

    tokens_per_s = batch_size * seq_len * steps / dt
    flops_per_token = 6 * n_params + 12 * n_layer * width * seq_len
    if name == "gpt2-moe":
        # honest MoE accounting: each token routes through k of E experts,
        # so (E - k) expert MLPs per MoE block hold params but do no work
        # for that token (top-1: same per-token flops as the dense model)
        n_moe_blocks = cfg.n_layer // cfg.moe_expert_interval
        expert_mlp = 8 * width * width
        flops_per_token -= 6 * (cfg.moe_num_experts - cfg.moe_k) \
            * expert_mlp * n_moe_blocks
    if name == "bert-sparse":
        # the attention-flops term assumes dense [S, S] scores; scale it
        # by the block layout's density (the whole point of sparse attn)
        from deepspeed_tpu.ops.sparse_attention.sparsity_config import \
            FixedSparsityConfig
        layout = FixedSparsityConfig(
            num_heads=cfg.num_attention_heads, block=cfg.sparse_block,
            num_local_blocks=cfg.sparse_num_local_blocks,
            num_global_blocks=cfg.sparse_num_global_blocks,
        ).make_layout(seq_len)
        density = float(layout.sum()) / layout.size
        flops_per_token -= 12 * n_layer * width * seq_len * (1 - density)
    if (os.environ.get("BENCH_ATTN_MODE", "").startswith("sparse")
            and name not in ("bert-large", "bert-sparse")):
        # causal sparse GPT rows: scale the attention term by the
        # unidirectional layout's density over the FULL [S, S] matrix —
        # conservative vs the dense rows' convention, which counts the
        # full square for causal models too
        from deepspeed_tpu.ops.sparse_attention.fused_kernels import \
            sparse_mode_layout
        layout, _ = sparse_mode_layout(os.environ["BENCH_ATTN_MODE"],
                                       cfg.n_head, seq_len)
        density = float(layout.sum()) / layout.size
        flops_per_token -= 12 * n_layer * width * seq_len * (1 - density)
    if name in ("bert-large", "bert-sparse") and masked_fmt:
        # honest accounting for the gathered-positions MLM head: the tied
        # decoder (V*H) + mlm transform (H*H) only run on P of S tokens,
        # so the 6N-per-token approximation must shed the skipped share
        P = max(1, int(round(seq_len * 0.15)))
        head_params = cfg.padded_vocab * width + width * width
        flops_per_token -= 6 * head_params * (1 - P / seq_len)
    if layered:
        # the layered decomposition UNTIES the LM head from wte, so
        # n_params holds BOTH [V,H] tables — but the wte forward is a
        # gather (~0 flops), not a matmul; shed its 6N share
        flops_per_token -= 6 * cfg.padded_vocab * width
    tflops = tokens_per_s * flops_per_token / 1e12
    n_chips = jax.device_count()
    tflops_per_chip = tflops / n_chips

    # ---- XLA cross-check (telemetry/cost_explorer.py): the analytic
    # flops formula above has per-model adjustments (MoE, sparse, masked
    # MLM) that can silently go stale as models evolve. The compiler's
    # own count of the program that JUST RAN is the ground truth; emit
    # the ratio and warn loudly when they disagree by > 10%.
    mfu_xla = flops_ratio = None
    explain = None
    # telemetry_on gate: without it the engine owns no compiled artifact
    # and explain_step would pay a full duplicate compile of the
    # bench-scale program just for the cross-check
    if not layered and telemetry_on and hasattr(engine, "explain_step"):
        try:
            explain = engine.explain_step(step_time_s=med_step_ms / 1e3)
            xla_flops_per_chip = explain["flops_per_step_per_device"]
            analytic_per_chip = (flops_per_token * batch_size * seq_len
                                 / n_chips)
            if xla_flops_per_chip and analytic_per_chip:
                flops_ratio = xla_flops_per_chip / analytic_per_chip
                if abs(flops_ratio - 1.0) > 0.10:
                    print(f"# WARNING: analytic flops formula disagrees "
                          f"with XLA by {(flops_ratio - 1) * 100:+.1f}% "
                          f"(xla/analytic = {flops_ratio:.3f}) — the "
                          f"per-model adjustments in bench.py may be "
                          f"stale for {name!r}", flush=True)
            if explain.get("mfu") is None and explain.get(
                    "flops_per_step_per_device"):
                # CPU/unknown chip: no peak in the table — derive MFU
                # from the XLA count against BENCH_PEAK_TFLOPS anyway.
                # Significant figures, not fixed decimals: CPU-scale MFU
                # (~1e-5) would round(x, 4) to a flat 0.0
                mfu_xla = float(f"{xla_flops_per_chip / (med_step_ms / 1e3) / 1e12 / peak_tflops:.4g}")
            else:
                mfu_xla = explain.get("mfu")
        except Exception as e:  # the cross-check must never sink a bench
            print(f"# cost-explorer cross-check unavailable: {e}",
                  flush=True)

    # input-pipeline overlap evidence: the whole-run input_wait share of
    # wall time from the goodput ledger. With prefetch on this tracks the
    # overlap (near zero = the H2D copy and collate hid behind compute);
    # with it off (or the fixed-batch path) it is the serialized cost.
    # optimizer sweep time at bench scale: the configured optimizer's
    # update (+ the global-norm clip the way the engine composes it) over
    # the engine's REAL state — the ISSUE-10 gap tracker (round-5
    # measured ≈23 ms vs a ~13 ms Adam HBM bound on the headline config).
    # BENCH_r* rounds watch this close as the one-sweep path lands.
    optimizer_ms = None
    if not layered:
        try:
            import jax.numpy as jnp

            from deepspeed_tpu.runtime import optim as optim_lib
            opt = engine.optimizer
            zgrads = jax.tree.map(jnp.zeros_like, engine.state.params)

            def _opt_step(g, s, p):
                u, s2 = optim_lib.clipped_update(opt, g, s, p, 1e-4)
                return jax.tree.map(jnp.add, p, u), s2

            with engine.mesh:
                f = jax.jit(_opt_step)
                _retry(lambda: jax.block_until_ready(f(
                    zgrads, engine.state.opt_state, engine.state.params)),
                    "optimizer microbench compile")
                t0 = time.perf_counter()
                iters = 10
                for _ in range(iters):
                    out = f(zgrads, engine.state.opt_state,
                            engine.state.params)
                jax.block_until_ready(out)
                optimizer_ms = round(
                    (time.perf_counter() - t0) / iters * 1e3, 2)
        except Exception as e:   # the tracker must never sink a bench
            print(f"# optimizer microbench unavailable: {e}", flush=True)

    # measured step anatomy: 3 profiled steps AFTER (outside) the timed
    # loop, post-processed into per-category device seconds + the
    # measured-vs-predicted drift against the CostExplorer roofline
    anatomy_drift = None
    if anatomy_on and hasattr(engine, "profile_step"):
        try:
            ar = engine.profile_step(3, write=False)
            if ar.get("enabled"):
                with open(os.path.join(bench_dir, "ANATOMY_BENCH.json"),
                          "w") as f:
                    json.dump({
                        "bench": name,
                        "step_time_ms": round(med_step_ms, 1),
                        "anatomy": ar}, f, indent=1, default=repr,
                        allow_nan=False)
                anatomy_drift = {
                    r["category"]: (round(r["drift"], 4)
                                    if r["drift"] is not None else None)
                    for r in ar.get("measured_vs_predicted", [])}
            else:
                print(f"# anatomy capture skipped: {ar.get('reason')}",
                      flush=True)
        except Exception as e:   # forensics must never sink a bench
            print(f"# anatomy profile unavailable: {e}", flush=True)

    input_wait_frac = None
    if goodput_on and hasattr(engine, "goodput_report"):
        try:
            _gp = engine.goodput_report()
            if _gp.get("enabled", True) is not False and _gp["elapsed_s"]:
                input_wait_frac = round(
                    _gp["categories_s"]["input_wait"] / _gp["elapsed_s"], 4)
        except Exception as e:
            print(f"# input_wait fraction unavailable: {e}", flush=True)

    # measured HBM residency: one forced profile fetch AFTER (outside)
    # the timed loop, attributed exactly against the engine inventory;
    # the full report lands in MEMORY_BENCH.json, the headline carries
    # the peak + its drift against the cost-explorer pre-flight
    hbm_peak_bytes = None
    watermark_drift = None
    if memory_on and hasattr(engine, "memory_report"):
        try:
            from deepspeed_tpu.telemetry.health import json_safe
            mb = engine.memory_report()
            if mb.get("enabled", True) is not False:
                hbm_peak_bytes = mb["watermark"]["measured_peak_bytes"]
                watermark_drift = mb["watermark"]["drift"]
                with open(os.path.join(bench_dir, "MEMORY_BENCH.json"),
                          "w") as f:
                    json.dump(json_safe({
                        "bench": name,
                        "step_time_ms": round(med_step_ms, 1),
                        "memory": mb}), f, indent=1, default=repr,
                        allow_nan=False)
        except Exception as e:   # forensics must never sink a bench
            print(f"# memory residency unavailable: {e}", flush=True)

    print(json.dumps({
        "metric": f"{name} train TFLOPS/chip "
                  f"(bs={batch_size} seq={seq_len} bf16 "
                  + ("zero=3+layered-offload (beyond-HBM)"
                     if layered else f"zero={zero_stage}")
                  + ", full engine)",
        "value": round(tflops_per_chip, 2),
        "unit": "TFLOPS/chip",
        "vs_baseline": round(tflops_per_chip / REFERENCE_TFLOPS_PER_GPU, 3),
        "mfu": round(tflops_per_chip / peak_tflops, 4),
        # XLA-census cross-checks (None when the explorer was unavailable):
        # mfu_xla uses the compiler's flop count of the program that ran;
        # flops_xla_vs_analytic near 1.0 validates the analytic formula
        "mfu_xla": mfu_xla,
        "flops_xla_vs_analytic": (round(flops_ratio, 4)
                                  if flops_ratio else None),
        "step_time_ms": round(med_step_ms, 1),
        "tokens_per_s": round(tokens_per_s, 1),
        # evidence that the number is steady state, not a lucky (or poisoned)
        # single loop: per-round per-step times, their spread, the trivial
        # round-trip probe before the timed rounds, and any stalled rounds
        # that were detected and excluded
        "round_step_ms": [round(x, 1) for x in round_step_ms],
        "step_ms_stddev": round(float(np.std(round_step_ms)), 2),
        "tunnel_rtt_ms": round(rtt_ms, 1),
        "tunnel_rtt_history_ms": rtt_history,
        "stalled_rounds_ms": stalled_rounds,
        # False = every health probe exceeded 1 s round-trip: the number
        # above reflects a degraded environment, NOT engine speed
        "tunnel_healthy": healthy,
        # async input pipeline (BENCH_PREFETCH): whether the timed loop
        # fed through the prefetched loader, and the ledger's whole-run
        # input_wait share tracking the overlap (None without goodput)
        "prefetch": prefetch_on,
        "input_wait_frac": input_wait_frac,
        # bucketed gradient-collective overlap: the EFFECTIVE state (the
        # engine arms it only when dp > 1 and the config is in the
        # envelope), and the optimizer-sweep gap tracker (ISSUE-10:
        # measured ≈23 ms vs the ~13 ms Adam HBM bound)
        "comm_overlap": bool(getattr(engine, "_comm_overlap_on", False)),
        "optimizer_ms": optimizer_ms,
        # fleet flight recorder: whether this round shipped rank-tagged
        # window records (BENCH_FLEET=1; FLEET_BENCH.json holds the
        # aggregated report)
        "fleet": fleet_on,
        # measured-vs-predicted per-category drift from the profiled
        # post-loop steps (BENCH_ANATOMY=1; None off / unavailable —
        # predicted sides are None on hosts without chip specs)
        "anatomy_drift": anatomy_drift,
        # measured HBM residency (BENCH_MEMORY=1; MEMORY_BENCH.json holds
        # the full attribution): peak live device bytes over the run and
        # the drift against the cost-explorer pre-flight watermark
        "hbm_peak_bytes": hbm_peak_bytes,
        "watermark_drift": watermark_drift,
    }))

    # telemetry artifact next to BENCH_*.json: where the trace/sink files
    # are + the full metrics snapshot (step-time histogram, compile
    # counts/seconds, retraces, memory) for the perf PRs that follow
    tel = getattr(engine, "telemetry", None)
    if tel is not None and tel.enabled:
        # health forensics artifact BEFORE close (close() finalises the
        # monitor): verdict + last stats sample + overflow counters for
        # the run that produced the headline number above
        if health_on and hasattr(engine, "health_report"):
            try:
                from deepspeed_tpu.telemetry.health import json_safe
                hb = engine.health_report()
                if hb.get("enabled", True) is not False:
                    with open(os.path.join(bench_dir, "HEALTH_BENCH.json"),
                              "w") as f:
                        json.dump(json_safe({
                            "bench": name,
                            "step_time_ms": round(med_step_ms, 1),
                            "health": hb}), f, indent=1, default=repr,
                            allow_nan=False)
            except Exception as e:   # forensics must never sink a bench
                print(f"# health artifact unavailable: {e}", flush=True)
        # goodput ledger artifact: where the run's wall-clock actually
        # went (compile vs input vs compute), the end-to-end complement
        # of the steady-state step_time_ms headline
        if goodput_on and hasattr(engine, "goodput_report"):
            try:
                gb = engine.goodput_report()
                if gb.get("enabled", True) is not False:
                    with open(os.path.join(bench_dir, "GOODPUT_BENCH.json"),
                              "w") as f:
                        json.dump({
                            "bench": name,
                            "step_time_ms": round(med_step_ms, 1),
                            "goodput": gb}, f, indent=1, default=repr,
                            allow_nan=False)
            except Exception as e:   # forensics must never sink a bench
                print(f"# goodput artifact unavailable: {e}", flush=True)
        # fleet flight-recorder artifact: the aggregated cross-rank view
        # (single-rank here, but the record/merge path is the real one)
        if fleet_on and hasattr(engine, "fleet_report"):
            try:
                from deepspeed_tpu.telemetry.health import json_safe
                fb = engine.fleet_report()
                if fb.get("enabled", True) is not False:
                    with open(os.path.join(bench_dir, "FLEET_BENCH.json"),
                              "w") as f:
                        json.dump(json_safe({
                            "bench": name,
                            "step_time_ms": round(med_step_ms, 1),
                            "fleet": fb}), f, indent=1, default=repr,
                            allow_nan=False)
            except Exception as e:   # forensics must never sink a bench
                print(f"# fleet artifact unavailable: {e}", flush=True)
        tel.close()   # forces the final complete trace export
        engine.monitor.close()
        summary = {
            "bench": name,
            "trace_json": tel.trace_path,
            "sinks": {type(m).__name__: getattr(m, "path", None)
                      for m in engine.monitor.monitors},
            "metrics": tel.registry.snapshot(),
            # full cost-explorer report (roofline, bound-ness verdict,
            # per-axis collective bytes, HBM watermark) for this run
            "explain": explain,
        }
        with open(os.path.join(bench_dir, "TELEMETRY_BENCH.json"), "w") as f:
            json.dump(summary, f, indent=2, default=repr)

    if data_iter is not None:
        data_iter.loader.close()    # stop the prefetch pipeline threads


if __name__ == "__main__":
    main()
