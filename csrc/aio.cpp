// Async tensor<->file IO engine for NVMe offload.
//
// TPU-native equivalent of the reference's csrc/aio/ stack
// (deepspeed_aio_common.cpp:69-216 do_aio_operation_sequential/
// _overlap over libaio io_submit, deepspeed_py_aio_handle.cpp
// thread-pooled handle, py_ds_aio.cpp binding surface: aio_handle /
// sync_pread / sync_pwrite / async_pread / async_pwrite / wait).
//
// Primary engine: Linux kernel AIO (raw io_setup/io_submit/io_getevents
// syscalls — exactly what libaio wraps, no userspace lib needed) over an
// O_DIRECT fd with 4 KiB-aligned bounce slots, keeping ``queue_depth``
// blocks in flight per transfer. ``single_submit`` picks one io_submit
// per iocb vs one batched call; ``overlap_events`` reaps completions
// while submission continues vs draining only when the ring is full —
// the reference's two strategies (deepspeed_aio_common.cpp:69/:121).
// A std::thread pool runs each transfer and is also the FALLBACK engine
// (plain pread/pwrite) when O_DIRECT or io_setup is unavailable
// (overlayfs, container aio-max-nr limits) or the transfer is unaligned.
// Set DS_AIO_DISABLE_KERNEL=1 to force the fallback (perf comparisons).
//
// C ABI for ctypes; no torch, no pybind11.
// Build: g++ -O3 -shared -fPIC -pthread aio.cpp
#include <fcntl.h>
#include <linux/aio_abi.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kAlign = 4096;
constexpr int64_t kUseFallback = INT64_MIN;  // sentinel: no IO happened yet

long sys_io_setup(unsigned nr, aio_context_t* ctx) {
  return syscall(__NR_io_setup, nr, ctx);
}
long sys_io_destroy(aio_context_t ctx) { return syscall(__NR_io_destroy, ctx); }
long sys_io_submit(aio_context_t ctx, long n, struct iocb** ios) {
  return syscall(__NR_io_submit, ctx, n, ios);
}
long sys_io_getevents(aio_context_t ctx, long min_nr, long nr,
                      struct io_event* ev) {
  return syscall(__NR_io_getevents, ctx, min_nr, nr, ev, nullptr);
}

bool kernel_aio_disabled() {
  const char* e = getenv("DS_AIO_DISABLE_KERNEL");
  return e && e[0] && e[0] != '0';
}

// high-water mark of simultaneously in-flight kernel-AIO requests since
// the last reset — the enforceable proof that the queue-depth engine
// actually overlaps I/O (bandwidth ratios are hostage to the
// hypervisor's virtio cache; this is not)
std::atomic<long> g_max_inflight{0};

void note_inflight(int inflight) {
  long cur = g_max_inflight.load(std::memory_order_relaxed);
  while (inflight > cur &&
         !g_max_inflight.compare_exchange_weak(cur, inflight)) {
  }
}

int64_t blocked_rw(bool write, const char* path, char* buf, int64_t nbytes,
                   int64_t file_offset, int block_size);

// One transfer through kernel AIO. Returns bytes transferred, -errno, or
// kUseFallback when the environment can't do it (caller then takes the
// thread-pool pread/pwrite path; nothing has been read/written yet).
int64_t kernel_aio_rw(bool write, const char* path, char* buf,
                      int64_t nbytes, int64_t file_offset, int64_t block_size,
                      int queue_depth, bool single_submit,
                      bool overlap_events) {
  if (kernel_aio_disabled() || nbytes < kAlign || (file_offset % kAlign))
    return kUseFallback;
  block_size = (block_size / kAlign) * kAlign;
  if (block_size <= 0) block_size = kAlign;
  if (queue_depth <= 0) queue_depth = 8;

  int flags = write ? (O_WRONLY | O_CREAT | O_DIRECT) : (O_RDONLY | O_DIRECT);
  int fd = ::open(path, flags, 0644);
  if (fd < 0) return kUseFallback;  // O_DIRECT unsupported here

  aio_context_t ctx = 0;
  if (sys_io_setup(queue_depth, &ctx) < 0) {
    ::close(fd);
    return kUseFallback;
  }

  char* bounce = nullptr;
  if (posix_memalign(reinterpret_cast<void**>(&bounce), kAlign,
                     static_cast<size_t>(block_size) * queue_depth) != 0) {
    sys_io_destroy(ctx);
    ::close(fd);
    return kUseFallback;
  }

  const int64_t body = (nbytes / kAlign) * kAlign;  // O_DIRECT-aligned part
  int64_t next_off = 0;   // next body offset to submit
  int64_t completed = 0;  // bytes confirmed done
  int64_t rc = 0;

  std::vector<iocb> cbs(queue_depth);
  std::vector<int64_t> slot_user_off(queue_depth);  // slot -> buf offset
  std::vector<int64_t> slot_len(queue_depth);
  std::vector<int> free_slots;
  for (int i = queue_depth - 1; i >= 0; --i) free_slots.push_back(i);
  std::vector<io_event> events(queue_depth);
  std::vector<iocb*> batch;
  int inflight = 0;

  auto reap = [&](long min_nr) -> int64_t {
    long got = sys_io_getevents(ctx, min_nr, queue_depth, events.data());
    if (got < 0) return -errno;
    for (long i = 0; i < got; ++i) {
      int slot = static_cast<int>(events[i].data);
      int64_t res = events[i].res;
      if (res < 0) return res;
      if (!write)  // copy the landed block out of its bounce slot
        memcpy(buf + slot_user_off[slot], bounce + slot * block_size,
               static_cast<size_t>(res));
      completed += res;
      if (res > 0 && res < slot_len[slot]) {
        // short transfer: the unserved tail of this block would otherwise
        // be silently dropped (round-4 advisory). res need not stay
        // kAlign-aligned, so finish the remainder through the buffered
        // engine (coherent with the O_DIRECT body on Linux, same as the
        // unaligned-tail path below). res == 0 is EOF on a read shorter
        // than the request — partial byte count returned, like the
        // thread-pool fallback.
        int64_t rem_off = slot_user_off[slot] + res;
        int64_t rem_len = slot_len[slot] - res;
        int64_t r2 = blocked_rw(write, path, buf + rem_off, rem_len,
                                file_offset + rem_off,
                                static_cast<int>(block_size));
        if (r2 < 0) return r2;
        completed += r2;
      }
      free_slots.push_back(slot);
      --inflight;
    }
    return 0;
  };

  while (rc == 0 && (next_off < body || inflight > 0)) {
    // fill the ring
    batch.clear();
    while (next_off < body && !free_slots.empty()) {
      int slot = free_slots.back();
      free_slots.pop_back();
      int64_t chunk = std::min<int64_t>(block_size, body - next_off);
      chunk = (chunk / kAlign) * kAlign;  // O_DIRECT length alignment
      slot_user_off[slot] = next_off;
      slot_len[slot] = chunk;
      if (write) memcpy(bounce + slot * block_size, buf + next_off,
                        static_cast<size_t>(chunk));
      iocb* cb = &cbs[slot];
      memset(cb, 0, sizeof(*cb));
      cb->aio_lio_opcode = write ? IOCB_CMD_PWRITE : IOCB_CMD_PREAD;
      cb->aio_fildes = fd;
      cb->aio_buf = reinterpret_cast<uint64_t>(bounce + slot * block_size);
      cb->aio_nbytes = chunk;
      cb->aio_offset = file_offset + next_off;
      cb->aio_data = slot;
      next_off += chunk;
      ++inflight;
      if (single_submit) {
        iocb* one = cb;
        if (sys_io_submit(ctx, 1, &one) < 0) { rc = -errno; break; }
      } else {
        batch.push_back(cb);
      }
    }
    if (rc == 0 && !batch.empty()) {
      if (sys_io_submit(ctx, batch.size(), batch.data()) < 0) rc = -errno;
    }
    note_inflight(inflight);
    if (rc == 0 && inflight > 0) {
      if (overlap_events) {
        // overlap: free at least one slot, then go refill — submission
        // and completion interleave (reference do_aio_operation_overlap)
        int64_t r = reap(1);
        if (r < 0) rc = r;
      } else {
        // sequential: drain the whole wave before the next submit batch
        // (reference do_aio_operation_sequential)
        while (rc == 0 && inflight > 0) {
          int64_t r = reap(1);
          if (r < 0) rc = r;
        }
      }
    }
  }
  while (rc == 0 && inflight > 0) {
    int64_t r = reap(1);
    if (r < 0) rc = r;
  }

  // destroy BEFORE freeing the bounce region: io_destroy waits for any
  // still-in-flight requests, which DMA into these slots (on the error
  // paths inflight can be nonzero here)
  sys_io_destroy(ctx);
  free(bounce);
  ::close(fd);
  if (rc < 0) return rc;

  // unaligned tail through a buffered fd (mixing O_DIRECT body + buffered
  // tail on one file is coherent on Linux)
  int64_t tail = nbytes - body;
  if (tail > 0) {
    int tfd = ::open(path, write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
    if (tfd < 0) return -errno;
    ssize_t r = write
        ? ::pwrite(tfd, buf + body, tail, file_offset + body)
        : ::pread(tfd, buf + body, tail, file_offset + body);
    ::close(tfd);
    if (r < 0) return -errno;
    completed += r;
  }
  return completed;
}

struct Request {
  int64_t id;
  std::function<int64_t()> work;
};

struct Handle {
  int block_size;
  int queue_depth;
  int single_submit;
  int overlap_events;
  int num_threads;
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::map<int64_t, int64_t> results;  // req id -> bytes or -errno
  std::atomic<int64_t> next_id{1};
  bool shutdown = false;

  void worker_loop() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return shutdown || !queue.empty(); });
        if (shutdown && queue.empty()) return;
        req = std::move(queue.front());
        queue.pop_front();
      }
      int64_t res = req.work();
      {
        std::lock_guard<std::mutex> lk(mu);
        results[req.id] = res;
      }
      done_cv.notify_all();
    }
  }
};

std::map<int64_t, Handle*> g_handles;
std::mutex g_handles_mu;
std::atomic<int64_t> g_next_handle{1};

Handle* get_handle(int64_t h) {
  std::lock_guard<std::mutex> lk(g_handles_mu);
  auto it = g_handles.find(h);
  return it == g_handles.end() ? nullptr : it->second;
}

int64_t blocked_rw(bool write, const char* path, char* buf, int64_t nbytes,
                   int64_t file_offset, int block_size) {
  int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
  int fd = ::open(path, flags, 0644);
  if (fd < 0) return -errno;
  int64_t off = 0;
  while (off < nbytes) {
    int64_t chunk = std::min<int64_t>(block_size, nbytes - off);
    ssize_t r = write ? ::pwrite(fd, buf + off, chunk, file_offset + off)
                      : ::pread(fd, buf + off, chunk, file_offset + off);
    if (r < 0) {
      ::close(fd);
      return -errno;
    }
    if (r == 0) break;  // EOF on read
    off += r;
  }
  ::close(fd);
  return off;
}

}  // namespace

extern "C" {

int64_t aio_handle_create(int block_size, int queue_depth, int single_submit,
                          int overlap_events, int num_threads) {
  Handle* h = new Handle();
  h->block_size = block_size > 0 ? block_size : (1 << 20);
  h->queue_depth = queue_depth > 0 ? queue_depth : 8;
  h->single_submit = single_submit;
  h->overlap_events = overlap_events;
  h->num_threads = num_threads > 0 ? num_threads : 1;
  for (int i = 0; i < h->num_threads; ++i)
    h->workers.emplace_back([h] { h->worker_loop(); });
  std::lock_guard<std::mutex> lk(g_handles_mu);
  int64_t id = g_next_handle++;
  g_handles[id] = h;
  return id;
}

int aio_handle_destroy(int64_t handle) {
  Handle* h;
  {
    std::lock_guard<std::mutex> lk(g_handles_mu);
    auto it = g_handles.find(handle);
    if (it == g_handles.end()) return -1;
    h = it->second;
    g_handles.erase(it);
  }
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->shutdown = true;
  }
  h->cv.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
  return 0;
}

// async submit: returns request id (>0) or -errno
int64_t aio_async_pread(int64_t handle, char* buffer, const char* path,
                        int64_t nbytes, int64_t file_offset) {
  Handle* h = get_handle(handle);
  if (!h) return -1;
  int64_t id = h->next_id++;
  std::string p(path);
  int bs = h->block_size, qd = h->queue_depth;
  bool ss = h->single_submit != 0, oe = h->overlap_events != 0;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->queue.push_back({id, [=] {
                          int64_t r = kernel_aio_rw(false, p.c_str(), buffer,
                                                    nbytes, file_offset, bs,
                                                    qd, ss, oe);
                          if (r == kUseFallback)
                            r = blocked_rw(false, p.c_str(), buffer, nbytes,
                                           file_offset, bs);
                          return r;
                        }});
  }
  h->cv.notify_one();
  return id;
}

int64_t aio_async_pwrite(int64_t handle, const char* buffer, const char* path,
                         int64_t nbytes, int64_t file_offset) {
  Handle* h = get_handle(handle);
  if (!h) return -1;
  int64_t id = h->next_id++;
  std::string p(path);
  int bs = h->block_size, qd = h->queue_depth;
  bool ss = h->single_submit != 0, oe = h->overlap_events != 0;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->queue.push_back({id, [=] {
                          char* b = const_cast<char*>(buffer);
                          int64_t r = kernel_aio_rw(true, p.c_str(), b,
                                                    nbytes, file_offset, bs,
                                                    qd, ss, oe);
                          if (r == kUseFallback)
                            r = blocked_rw(true, p.c_str(), b, nbytes,
                                           file_offset, bs);
                          return r;
                        }});
  }
  h->cv.notify_one();
  return id;
}

// wait for one request; returns bytes transferred or -errno
int64_t aio_wait(int64_t handle, int64_t request_id) {
  Handle* h = get_handle(handle);
  if (!h) return -1;
  std::unique_lock<std::mutex> lk(h->mu);
  h->done_cv.wait(lk, [&] { return h->results.count(request_id) > 0; });
  int64_t res = h->results[request_id];
  h->results.erase(request_id);
  return res;
}

// count of completed-but-unwaited requests (reference wait/poll surface)
int64_t aio_pending(int64_t handle) {
  Handle* h = get_handle(handle);
  if (!h) return -1;
  std::lock_guard<std::mutex> lk(h->mu);
  return (int64_t)(h->queue.size());
}

int64_t aio_sync_pread(int64_t handle, char* buffer, const char* path,
                       int64_t nbytes, int64_t file_offset) {
  int64_t id = aio_async_pread(handle, buffer, path, nbytes, file_offset);
  if (id < 0) return id;
  return aio_wait(handle, id);
}

int64_t aio_sync_pwrite(int64_t handle, const char* buffer, const char* path,
                        int64_t nbytes, int64_t file_offset) {
  int64_t id = aio_async_pwrite(handle, buffer, path, nbytes, file_offset);
  if (id < 0) return id;
  return aio_wait(handle, id);
}

// observability: high-water mark of in-flight kernel-AIO requests since
// the last reset (0 = everything went through the fallback)
int64_t aio_max_inflight() { return g_max_inflight.load(); }
void aio_reset_max_inflight() { g_max_inflight.store(0); }

// 1 when the kernel io_submit engine can run for files under probe_dir:
// io_setup permitted AND O_DIRECT opens there (tmpfs/overlayfs reject it,
// in which case every transfer takes the thread-pool fallback). A null
// probe_dir checks io_setup only.
int aio_kernel_available(const char* probe_dir) {
  if (kernel_aio_disabled()) return 0;
  aio_context_t ctx = 0;
  if (sys_io_setup(1, &ctx) < 0) return 0;
  sys_io_destroy(ctx);
  if (probe_dir && probe_dir[0]) {
    std::string p(probe_dir);
    p += "/.ds_aio_probe";
    int fd = ::open(p.c_str(), O_WRONLY | O_CREAT | O_DIRECT, 0644);
    if (fd < 0) return 0;
    ::close(fd);
    ::unlink(p.c_str());
  }
  return 1;
}

}  // extern "C"
