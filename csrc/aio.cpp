// Async tensor<->file IO engine for NVMe offload.
//
// TPU-native equivalent of the reference's csrc/aio/ stack
// (deepspeed_aio_common.cpp libaio paths, deepspeed_py_aio_handle.cpp
// thread-pooled handle, py_ds_aio.cpp binding surface: aio_handle /
// sync_pread / sync_pwrite / async_pread / async_pwrite / wait). The
// reference drives libaio io_submit with pinned bounce buffers; here a
// std::thread pool issues pread/pwrite (optionally O_DIRECT) — the
// host-side concurrency model is the same (queue depth × worker threads,
// overlapped with compute), without requiring libaio/liburing at runtime.
//
// C ABI for ctypes; no torch, no pybind11.
// Build: g++ -O3 -shared -fPIC -pthread aio.cpp
#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Request {
  int64_t id;
  std::function<int64_t()> work;
};

struct Handle {
  int block_size;
  int queue_depth;
  int single_submit;
  int overlap_events;
  int num_threads;
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::map<int64_t, int64_t> results;  // req id -> bytes or -errno
  std::atomic<int64_t> next_id{1};
  bool shutdown = false;

  void worker_loop() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return shutdown || !queue.empty(); });
        if (shutdown && queue.empty()) return;
        req = std::move(queue.front());
        queue.pop_front();
      }
      int64_t res = req.work();
      {
        std::lock_guard<std::mutex> lk(mu);
        results[req.id] = res;
      }
      done_cv.notify_all();
    }
  }
};

std::map<int64_t, Handle*> g_handles;
std::mutex g_handles_mu;
std::atomic<int64_t> g_next_handle{1};

Handle* get_handle(int64_t h) {
  std::lock_guard<std::mutex> lk(g_handles_mu);
  auto it = g_handles.find(h);
  return it == g_handles.end() ? nullptr : it->second;
}

int64_t blocked_rw(bool write, const char* path, char* buf, int64_t nbytes,
                   int64_t file_offset, int block_size) {
  int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
  int fd = ::open(path, flags, 0644);
  if (fd < 0) return -errno;
  int64_t off = 0;
  while (off < nbytes) {
    int64_t chunk = std::min<int64_t>(block_size, nbytes - off);
    ssize_t r = write ? ::pwrite(fd, buf + off, chunk, file_offset + off)
                      : ::pread(fd, buf + off, chunk, file_offset + off);
    if (r < 0) {
      ::close(fd);
      return -errno;
    }
    if (r == 0) break;  // EOF on read
    off += r;
  }
  ::close(fd);
  return off;
}

}  // namespace

extern "C" {

int64_t aio_handle_create(int block_size, int queue_depth, int single_submit,
                          int overlap_events, int num_threads) {
  Handle* h = new Handle();
  h->block_size = block_size > 0 ? block_size : (1 << 20);
  h->queue_depth = queue_depth > 0 ? queue_depth : 8;
  h->single_submit = single_submit;
  h->overlap_events = overlap_events;
  h->num_threads = num_threads > 0 ? num_threads : 1;
  for (int i = 0; i < h->num_threads; ++i)
    h->workers.emplace_back([h] { h->worker_loop(); });
  std::lock_guard<std::mutex> lk(g_handles_mu);
  int64_t id = g_next_handle++;
  g_handles[id] = h;
  return id;
}

int aio_handle_destroy(int64_t handle) {
  Handle* h;
  {
    std::lock_guard<std::mutex> lk(g_handles_mu);
    auto it = g_handles.find(handle);
    if (it == g_handles.end()) return -1;
    h = it->second;
    g_handles.erase(it);
  }
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->shutdown = true;
  }
  h->cv.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
  return 0;
}

// async submit: returns request id (>0) or -errno
int64_t aio_async_pread(int64_t handle, char* buffer, const char* path,
                        int64_t nbytes, int64_t file_offset) {
  Handle* h = get_handle(handle);
  if (!h) return -1;
  int64_t id = h->next_id++;
  std::string p(path);
  int bs = h->block_size;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->queue.push_back({id, [=] {
                          return blocked_rw(false, p.c_str(), buffer, nbytes,
                                            file_offset, bs);
                        }});
  }
  h->cv.notify_one();
  return id;
}

int64_t aio_async_pwrite(int64_t handle, const char* buffer, const char* path,
                         int64_t nbytes, int64_t file_offset) {
  Handle* h = get_handle(handle);
  if (!h) return -1;
  int64_t id = h->next_id++;
  std::string p(path);
  int bs = h->block_size;
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->queue.push_back({id, [=] {
                          return blocked_rw(true, p.c_str(),
                                            const_cast<char*>(buffer), nbytes,
                                            file_offset, bs);
                        }});
  }
  h->cv.notify_one();
  return id;
}

// wait for one request; returns bytes transferred or -errno
int64_t aio_wait(int64_t handle, int64_t request_id) {
  Handle* h = get_handle(handle);
  if (!h) return -1;
  std::unique_lock<std::mutex> lk(h->mu);
  h->done_cv.wait(lk, [&] { return h->results.count(request_id) > 0; });
  int64_t res = h->results[request_id];
  h->results.erase(request_id);
  return res;
}

// count of completed-but-unwaited requests (reference wait/poll surface)
int64_t aio_pending(int64_t handle) {
  Handle* h = get_handle(handle);
  if (!h) return -1;
  std::lock_guard<std::mutex> lk(h->mu);
  return (int64_t)(h->queue.size());
}

int64_t aio_sync_pread(int64_t handle, char* buffer, const char* path,
                       int64_t nbytes, int64_t file_offset) {
  int64_t id = aio_async_pread(handle, buffer, path, nbytes, file_offset);
  if (id < 0) return id;
  return aio_wait(handle, id);
}

int64_t aio_sync_pwrite(int64_t handle, const char* buffer, const char* path,
                        int64_t nbytes, int64_t file_offset) {
  int64_t id = aio_async_pwrite(handle, buffer, path, nbytes, file_offset);
  if (id < 0) return id;
  return aio_wait(handle, id);
}

}  // extern "C"
