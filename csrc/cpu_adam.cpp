// CPU Adam/Adagrad — AVX-vectorized host optimizer for ZeRO-Offload.
//
// TPU-native equivalent of the reference's csrc/adam/cpu_adam.cpp (AVX
// intrinsics in csrc/includes/simd.h, pybind surface
// create_adam/adam_update) and csrc/adagrad/cpu_adagrad.cpp. Exposed as a
// plain C ABI consumed via ctypes (no torch, no pybind11): the Python
// wrapper (deepspeed_tpu/ops/adam/cpu_adam.py) drives it on pinned host
// buffers that swap against TPU HBM.
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC cpu_adam.cpp
#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

struct AdamConfig {
  float betta1;
  float betta2;
  float eps;
  float weight_decay;
  int adamw_mode;
};

static std::map<int, AdamConfig> g_adam_optimizers;
static std::mutex g_mu;

int ds_adam_create(int optimizer_id, float betta1, float betta2, float eps,
                   float weight_decay, int adamw_mode) {
  std::lock_guard<std::mutex> lk(g_mu);
  g_adam_optimizers[optimizer_id] = {betta1, betta2, eps, weight_decay,
                                     adamw_mode};
  return 0;
}

int ds_adam_destroy(int optimizer_id) {
  std::lock_guard<std::mutex> lk(g_mu);
  g_adam_optimizers.erase(optimizer_id);
  return 0;
}

// One fused Adam step over a contiguous shard. Matches the reference
// kernel's math order: bias correction folded into step size; AdamW
// decoupled decay vs L2 fold-in (cpu_adam.h Step_1).
int ds_adam_step(int optimizer_id, int64_t step, float lr, float* params,
                 const float* grads, float* exp_avg, float* exp_avg_sq,
                 int64_t n) {
  AdamConfig cfg;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_adam_optimizers.find(optimizer_id);
    if (it == g_adam_optimizers.end()) return -1;
    cfg = it->second;
  }
  const float b1 = cfg.betta1, b2 = cfg.betta2, eps = cfg.eps;
  const float wd = cfg.weight_decay;
  const float bc1 = 1.0f - std::pow(b1, (float)step);
  const float bc2 = 1.0f - std::pow(b2, (float)step);
  const float step_size = lr / bc1;
  const float bc2_sqrt = std::sqrt(bc2);
  const bool adamw = cfg.adamw_mode != 0;

  int64_t i = 0;
#if defined(__AVX2__) && defined(__FMA__)
  const __m256 vb1 = _mm256_set1_ps(b1);
  const __m256 vb2 = _mm256_set1_ps(b2);
  const __m256 v1mb1 = _mm256_set1_ps(1.0f - b1);
  const __m256 v1mb2 = _mm256_set1_ps(1.0f - b2);
  const __m256 veps = _mm256_set1_ps(eps);
  const __m256 vstep = _mm256_set1_ps(-step_size);
  const __m256 vbc2s = _mm256_set1_ps(1.0f / bc2_sqrt);
  const __m256 vwd = _mm256_set1_ps(wd);
  const __m256 vlrwd = _mm256_set1_ps(-lr * wd);
#pragma omp parallel for
  for (int64_t blk = 0; blk < n / 8; ++blk) {
    int64_t j = blk * 8;
    __m256 g = _mm256_loadu_ps(grads + j);
    __m256 p = _mm256_loadu_ps(params + j);
    if (wd > 0.0f && !adamw) g = _mm256_fmadd_ps(vwd, p, g);
    __m256 m = _mm256_loadu_ps(exp_avg + j);
    __m256 v = _mm256_loadu_ps(exp_avg_sq + j);
    m = _mm256_fmadd_ps(vb1, m, _mm256_mul_ps(v1mb1, g));
    v = _mm256_fmadd_ps(vb2, v, _mm256_mul_ps(v1mb2, _mm256_mul_ps(g, g)));
    // denom = sqrt(v)/sqrt(bc2) + eps
    __m256 denom =
        _mm256_add_ps(_mm256_mul_ps(_mm256_sqrt_ps(v), vbc2s), veps);
    __m256 upd = _mm256_div_ps(m, denom);
    __m256 p_orig = p;  // decoupled decay uses the pre-update param
    p = _mm256_fmadd_ps(vstep, upd, p);
    if (wd > 0.0f && adamw) p = _mm256_fmadd_ps(vlrwd, p_orig, p);
    _mm256_storeu_ps(params + j, p);
    _mm256_storeu_ps(exp_avg + j, m);
    _mm256_storeu_ps(exp_avg_sq + j, v);
  }
  i = (n / 8) * 8;
#endif
  for (; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (wd > 0.0f && !adamw) g += wd * p;
    float m = exp_avg[i] = b1 * exp_avg[i] + (1.0f - b1) * g;
    float v = exp_avg_sq[i] = b2 * exp_avg_sq[i] + (1.0f - b2) * g * g;
    float denom = std::sqrt(v) / bc2_sqrt + eps;
    float p_orig = p;
    p -= step_size * (m / denom);
    if (wd > 0.0f && adamw) p -= lr * wd * p_orig;
    params[i] = p;
  }
  return 0;
}

// ---------------------------------------------------------------- adagrad
int ds_adagrad_step(float lr, float eps, float weight_decay, float* params,
                    const float* grads, float* exp_avg_sq, int64_t n) {
#pragma omp parallel for
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    if (weight_decay > 0.0f) g += weight_decay * params[i];
    exp_avg_sq[i] += g * g;
    params[i] -= lr * g / (std::sqrt(exp_avg_sq[i]) + eps);
  }
  return 0;
}

int ds_has_avx2() {
#if defined(__AVX2__) && defined(__FMA__)
  return 1;
#else
  return 0;
#endif
}

}  // extern "C"
