"""Training telemetry — TensorBoard + CSV writers.

Rebuild of the reference's rank-0 TensorBoard wiring
(engine.get_summary_writer engine.py:510; scalar writes :1686/:1911-1939/
_write_tensorboard :2011). A CSV fallback keeps telemetry alive on hosts
without the tensorboard package.
"""

import csv
import os
from typing import Optional


class TensorBoardMonitor:
    def __init__(self, output_path="runs/", job_name="DeepSpeedJobName"):
        from torch.utils.tensorboard import SummaryWriter
        os.makedirs(output_path, exist_ok=True)
        self.writer = SummaryWriter(log_dir=os.path.join(output_path,
                                                         job_name))

    def write_scalar(self, name, value, step):
        self.writer.add_scalar(name, value, step)

    def flush(self):
        self.writer.flush()


class CSVMonitor:
    def __init__(self, output_path="runs/", job_name="DeepSpeedJobName"):
        os.makedirs(output_path, exist_ok=True)
        self.path = os.path.join(output_path, f"{job_name}.csv")
        self._file = open(self.path, "a", newline="")
        self._writer = csv.writer(self._file)
        if self._file.tell() == 0:
            self._writer.writerow(["step", "name", "value"])

    def write_scalar(self, name, value, step):
        self._writer.writerow([step, name, float(value)])

    def flush(self):
        self._file.flush()


class MonitorMaster:
    """Fans scalars out to every enabled backend (rank 0 only)."""

    def __init__(self, tensorboard_config=None, rank=0):
        self.monitors = []
        self.enabled = rank == 0
        if not self.enabled:
            return
        if tensorboard_config is not None and tensorboard_config.enabled:
            path = tensorboard_config.output_path or "runs/"
            job = tensorboard_config.job_name or "DeepSpeedJobName"
            try:
                self.monitors.append(TensorBoardMonitor(path, job))
            except Exception:
                self.monitors.append(CSVMonitor(path, job))

    def write_events(self, event_list, flush=True):
        """event_list: [(name, value, step), ...] — reference signature."""
        if not self.enabled:
            return
        for name, value, step in event_list:
            for m in self.monitors:
                m.write_scalar(name, value, step)
        if flush:
            for m in self.monitors:
                m.flush()
