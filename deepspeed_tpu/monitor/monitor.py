"""Training telemetry — TensorBoard + CSV + JSONL + Prometheus writers.

Rebuild of the reference's rank-0 TensorBoard wiring
(engine.get_summary_writer engine.py:510; scalar writes :1686/:1911-1939/
_write_tensorboard :2011). A CSV fallback keeps telemetry alive on hosts
without the tensorboard package. The ``telemetry`` config block adds the
structured sinks (telemetry/sinks.py) as extra backends, so every
existing ``write_events`` call site fans out to them unchanged.

All backends share the ``write_scalar``/``flush``/``close`` protocol;
``MonitorMaster.close()`` (or using it as a context manager) releases the
file handles — backends hold open files, so teardown matters for anything
longer-lived than the process.
"""

import csv
import os


class TensorBoardMonitor:
    def __init__(self, output_path="runs/", job_name="DeepSpeedJobName"):
        from torch.utils.tensorboard import SummaryWriter
        os.makedirs(output_path, exist_ok=True)
        self.writer = SummaryWriter(log_dir=os.path.join(output_path,
                                                         job_name))

    def write_scalar(self, name, value, step):
        self.writer.add_scalar(name, value, step)

    def flush(self):
        self.writer.flush()

    def close(self):
        self.writer.close()


class CSVMonitor:
    def __init__(self, output_path="runs/", job_name="DeepSpeedJobName"):
        os.makedirs(output_path, exist_ok=True)
        self.path = os.path.join(output_path, f"{job_name}.csv")
        self._file = open(self.path, "a", newline="")
        self._writer = csv.writer(self._file)
        if self._file.tell() == 0:
            self._writer.writerow(["step", "name", "value"])

    def write_scalar(self, name, value, step):
        self._writer.writerow([step, name, float(value)])

    def flush(self):
        if not self._file.closed:
            self._file.flush()

    def close(self):
        if not self._file.closed:
            self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class MonitorMaster:
    """Fans scalars out to every enabled backend (rank 0 only)."""

    def __init__(self, tensorboard_config=None, rank=0,
                 telemetry_config=None, metrics_registry=None):
        self.monitors = []
        self.enabled = rank == 0
        if not self.enabled:
            return
        if tensorboard_config is not None and tensorboard_config.enabled:
            path = tensorboard_config.output_path or "runs/"
            job = tensorboard_config.job_name or "DeepSpeedJobName"
            try:
                self.monitors.append(TensorBoardMonitor(path, job))
            except Exception:
                self.monitors.append(CSVMonitor(path, job))
        if telemetry_config is not None and telemetry_config.enabled:
            from deepspeed_tpu.telemetry.sinks import (JSONLMonitor,
                                                       PrometheusMonitor)
            path = telemetry_config.output_path or "telemetry/"
            job = telemetry_config.job_name or "DeepSpeedJobName"
            if telemetry_config.jsonl:
                self.monitors.append(JSONLMonitor(path, job))
            if telemetry_config.prometheus:
                # shares the TelemetryManager's registry so engine metrics
                # (step times, compile counts, ...) land in the same .prom
                self.monitors.append(PrometheusMonitor(
                    path, job, registry=metrics_registry))
        if self.monitors:
            # backends hold open file handles; a run that never tears the
            # engine down still flushes + closes at interpreter exit
            import atexit
            atexit.register(self.close)

    def write_events(self, event_list, flush=True):
        """event_list: [(name, value, step), ...] — reference signature."""
        if not self.enabled:
            return
        for name, value, step in event_list:
            for m in self.monitors:
                m.write_scalar(name, value, step)
        if flush:
            for m in self.monitors:
                m.flush()

    def close(self):
        """Flush and release every backend (idempotent)."""
        if not self.enabled:
            return
        for m in self.monitors:
            try:
                m.close()
            except Exception:
                pass
        # drop the exit hook so long-lived processes constructing many
        # masters (sweeps, test suites) don't pin closed instances
        import atexit
        try:
            atexit.unregister(self.close)
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
