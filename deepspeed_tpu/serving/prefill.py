"""Chunked prefill — fill a prompt's KV in fixed-size slices.

A synchronous full-prompt prefill stalls every running request for the
whole prompt forward (hundreds of tokens of compute between two decode
steps). Chunking bounds that stall: each scheduler iteration advances the
prefilling request by at most ``chunk_size`` tokens, interleaved with the
decode batch (Sarathi-style chunked prefill; the scheduler picks at most
one chunk per iteration).

One compiled program serves every chunk: chunks are always ``chunk_size``
wide, the final partial chunk is padded, and the pad positions write to
the null block (``n_valid`` masks them). The planner covers
``prompt[:-1]`` only — the last prompt token is the request's first
decode input, so its KV is written by the decode step that samples the
first generated token (TTFT therefore includes exactly one decode step
after the last chunk).

Prefix-cache composition: admission may pre-set ``cached_len`` past 0
when whole prompt blocks were matched read-only from the prefix index
(scheduler ``_admit``). ``remaining`` then naturally plans chunks from
the first uncached token — a fully-cached prefix needs ZERO chunk
dispatches here, just the block-table copy the scheduler already did.
"""

import numpy as np


class ChunkedPrefill:
    def __init__(self, prefill_fn, chunk_size: int):
        """``prefill_fn``: the runner's ``prefill_chunk`` (the server
        passes its compile-watch-wrapped form so chunk signatures are
        tracked)."""
        assert chunk_size >= 1
        self.prefill_fn = prefill_fn
        self.chunk_size = int(chunk_size)

    def remaining(self, req) -> int:
        """Prompt tokens still to cache (prefill target is P-1)."""
        return max(0, len(req.full_prompt) - 1 - req.cached_len)

    def next_chunk(self, req):
        """Plan the next chunk: ``(tokens[C] int32, start, n_valid)``,
        tokens null-padded to the fixed chunk width."""
        start = req.cached_len
        todo = self.remaining(req)
        n_valid = min(self.chunk_size, todo)
        assert n_valid > 0, "next_chunk on a fully prefilled request"
        tokens = np.zeros((self.chunk_size,), np.int32)
        tokens[:n_valid] = req.full_prompt[start:start + n_valid]
        return tokens, start, n_valid

    def run(self, params, scales, pools, req, max_blocks: int):
        """Execute one chunk for *req*; returns ``(pools, n_valid,
        n_recompute, done)`` where ``done`` means the prompt KV is
        complete and the request is decode-ready. ``n_recompute`` counts
        the chunk's tokens below the request's eviction high-water mark
        — positions whose KV existed before a preemption threw it away,
        i.e. compute this chunk is paying a SECOND time (the slot-step
        ledger and ``serving_recompute_tokens_total`` book preemption
        cost from it)."""
        tokens, start, n_valid = self.next_chunk(req)
        bt_row = np.zeros((max_blocks,), np.int32)
        bt_row[:len(req.block_table)] = req.block_table
        pools = self.prefill_fn(
            params, scales, pools, bt_row, tokens,
            np.int32(start), np.int32(n_valid))
        req.cached_len += n_valid
        n_recompute = max(0, min(start + n_valid,
                                 getattr(req, "max_cached_len", 0)) - start)
        return pools, n_valid, n_recompute, self.remaining(req) == 0
