"""Paged KV cache — fixed-size blocks + a block allocator + block tables.

The production-serving memory model (vLLM/PagedAttention, SOSP '23): the
decode KV cache is ONE pool of fixed-size blocks shared by every request,
and each request owns an ordered *block table* mapping its logical token
positions onto pool blocks. Heterogeneous prompt/generation lengths then
share a single static-shaped compiled decode step — the per-step program
always sees ``[num_blocks, H, block_size, D]`` pools plus small int32
tables, and only the *values* change as requests come and go, so XLA
compiles the decode step exactly once for the whole serving lifetime.

Split of responsibilities:

* ``BlockAllocator`` — host-side free-list over block ids. Block 0 is
  reserved as the *null* block: inactive batch slots (and the padded tail
  of a prefill chunk) route their writes there, which keeps the compiled
  step branch-free. ``free``/``allocate`` are guarded against leaks and
  double-frees — the scheduler tests pin those invariants.
* ``PagedKVCache`` — owns the device pools (per layer: K, V, and for the
  int8 KV layout the per-row fp32 scales, riding the same lane-dim
  convention as ops/transformer/decode.py) plus the scatter/gather
  helpers the runner traces into the compiled step: ``write_decode``
  (one token per slot), ``write_chunk`` (a prefill chunk for one slot)
  and ``gather`` (block table -> contiguous ``[B, H, T, D]`` view that
  composes with ``decode_attention``'s per-sequence lengths).

The gather materialises each slot's logical cache contiguously per step.
Attention has to stream those bytes anyway — decode is KV-bandwidth
bound — so paging costs one extra copy of the *live* window while buying
the capacity sharing that makes continuous batching admissible.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.transformer.decode import quantize_kv


class BlockAllocatorError(RuntimeError):
    pass


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` pool blocks.

    Block 0 is reserved (the null/trash block) and never handed out.
    ``allocate`` is all-or-nothing; ``free`` rejects double-frees and
    foreign ids so an accounting bug fails loudly instead of silently
    corrupting another request's cache.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 usable + the reserved null block), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool pages are hot)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._allocated = set()

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    def occupancy(self) -> float:
        """Fraction of usable blocks currently owned by requests."""
        return len(self._allocated) / max(1, self.num_usable)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int):
        """Return ``n`` block ids, or ``None`` when the pool can't cover
        the request (all-or-nothing; no partial grants)."""
        if n < 0:
            raise ValueError(f"allocate({n})")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._allocated.update(blocks)
        return blocks

    def free(self, blocks):
        for b in blocks:
            if b not in self._allocated:
                raise BlockAllocatorError(
                    f"free of block {b} which is not allocated "
                    f"(double-free or foreign id)")
            self._allocated.remove(b)
            self._free.append(b)

    def check_consistency(self):
        """Invariant check used by the tests: free ∪ allocated is exactly
        the usable id space and the two sets are disjoint."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise BlockAllocatorError("duplicate ids on the free list")
        if free & self._allocated:
            raise BlockAllocatorError(
                f"ids both free and allocated: {free & self._allocated}")
        universe = set(range(1, self.num_blocks))
        if free | self._allocated != universe:
            raise BlockAllocatorError(
                f"leaked ids: {universe - (free | self._allocated)}")
        return True


class PagedKVCache:
    """Device block pools + the traced scatter/gather helpers.

    Pools are layer-STACKED arrays (one pytree leaf each, one scatter
    per step via :meth:`write_all_layers`):

    * ``k``/``v``: ``[n_layer, num_blocks, H, block_size, D]`` in the
      activation dtype, or int8 when ``int8_kv`` (the lane-dim int8 KV
      layout that measured 1.33x on the decode bench);
    * ``k_scale``/``v_scale`` (int8 only): ``[n_layer, num_blocks, H,
      block_size]`` fp32 per-row absmax scales.
    """

    def __init__(self, n_layer, n_head, head_dim, block_size, num_blocks,
                 dtype=jnp.float32, int8_kv=False):
        self.n_layer = n_layer
        self.n_head = n_head
        self.head_dim = head_dim
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.int8_kv = bool(int8_kv)
        self.dtype = jnp.int8 if int8_kv else dtype
        self.allocator = BlockAllocator(num_blocks)

    # -------------------------------------------------- pool construction
    def init_pools(self, sharding=None):
        """Zeroed device pools; pass through the jitted step and thread
        the returned (donated) pools back in. Layer-STACKED arrays
        (``[L, N, H, BS, D]``): all layers of a step's K/V land in ONE
        scatter (XLA scatter dispatch is the dominant per-step host cost
        once attention streams only live blocks — 2 scatters/step beats
        2-per-layer by the layer count)."""
        L, N, H, BS, D = (self.n_layer, self.num_blocks, self.n_head,
                          self.block_size, self.head_dim)
        pools = {
            "k": jnp.zeros((L, N, H, BS, D), self.dtype),
            "v": jnp.zeros((L, N, H, BS, D), self.dtype),
        }
        if self.int8_kv:
            pools["k_scale"] = jnp.zeros((L, N, H, BS), jnp.float32)
            pools["v_scale"] = jnp.zeros((L, N, H, BS), jnp.float32)
        # COMMIT the arrays (to the caller's sharding — the server passes
        # a mesh-replicated one matching the engine params): a donated
        # program's outputs are committed, and feeding a committed pool
        # to a program first traced on uncommitted inputs is a silent
        # (and large) recompile on the second step
        return jax.device_put(
            pools, sharding if sharding is not None
            else jax.local_devices()[0])

    def pool_bytes(self) -> int:
        """Total HBM the pools occupy (for the serving metrics)."""
        N, H, BS, D = (self.num_blocks, self.n_head, self.block_size,
                       self.head_dim)
        per_layer = 2 * N * H * BS * D * jnp.dtype(self.dtype).itemsize
        if self.int8_kv:
            per_layer += 2 * N * H * BS * 4
        return per_layer * self.n_layer

    # ------------------------------------------------------ traced writes
    def write_decode(self, pools, layer, k_new, v_new, block_ids, offsets):
        """Write one token's (or one chunk's) K/V into ONE layer's pages.

        k_new/v_new: ``[B, H, D]``; block_ids/offsets: ``[B]`` int32 (the
        scheduler routes inactive slots / pad positions to the null block
        0). Used by the ``gather`` attention impl, whose kernel needs the
        current token in the pool before it reads. The ``paged`` impl
        batches all layers through :meth:`write_all_layers` instead.
        """
        out = dict(pools)
        if self.int8_kv:
            kq, ks = quantize_kv(k_new)                 # scales [B, H]
            vq, vs = quantize_kv(v_new)
            out["k"] = pools["k"].at[layer, block_ids, :, offsets, :].set(kq)
            out["v"] = pools["v"].at[layer, block_ids, :, offsets, :].set(vq)
            out["k_scale"] = pools["k_scale"].at[
                layer, block_ids, :, offsets].set(ks)
            out["v_scale"] = pools["v_scale"].at[
                layer, block_ids, :, offsets].set(vs)
        else:
            dt = pools["k"].dtype
            out["k"] = pools["k"].at[layer, block_ids, :, offsets, :].set(
                k_new.astype(dt))
            out["v"] = pools["v"].at[layer, block_ids, :, offsets, :].set(
                v_new.astype(dt))
        return out

    write_chunk = write_decode      # [C, H, D]: C plays B's role

    def write_all_layers(self, pools, k_all, v_all, block_ids, offsets):
        """Write EVERY layer's K/V for this step in one scatter apiece.

        k_all/v_all: ``[L, B, H, D]`` (decode) or ``[L, C, H, D]``
        (prefill chunk); block_ids/offsets: ``[B]``/``[C]`` int32. The
        advanced indices land on pool dims 1 and 3, so the update tensor
        is expected batch-major — ``[B, L, H, D]``."""
        out = dict(pools)
        if self.int8_kv:
            kq, ks = quantize_kv(k_all)        # scales [L, B, H]
            vq, vs = quantize_kv(v_all)
            out["k"] = pools["k"].at[:, block_ids, :, offsets, :].set(
                kq.transpose(1, 0, 2, 3))
            out["v"] = pools["v"].at[:, block_ids, :, offsets, :].set(
                vq.transpose(1, 0, 2, 3))
            out["k_scale"] = pools["k_scale"].at[
                :, block_ids, :, offsets].set(ks.transpose(1, 0, 2))
            out["v_scale"] = pools["v_scale"].at[
                :, block_ids, :, offsets].set(vs.transpose(1, 0, 2))
        else:
            dt = pools["k"].dtype
            out["k"] = pools["k"].at[:, block_ids, :, offsets, :].set(
                k_all.transpose(1, 0, 2, 3).astype(dt))
            out["v"] = pools["v"].at[:, block_ids, :, offsets, :].set(
                v_all.transpose(1, 0, 2, 3).astype(dt))
        return out

    # ------------------------------------------------------ traced gather
    def gather(self, pools, layer, block_tables):
        """Block table -> contiguous per-slot cache views.

        block_tables: ``[B, MB]`` int32 (or ``[MB]`` for one slot).
        Returns ``(k, v, k_scale, v_scale)`` with k/v shaped
        ``[B, H, MB*block_size, D]`` (scales ``[B, H, MB*block_size]`` or
        ``None``) — exactly what ``decode_attention`` /
        ``decode_attention_quantized`` read, with per-sequence lengths
        masking the tail.
        """
        squeeze = block_tables.ndim == 1
        bt = block_tables[None] if squeeze else block_tables
        B, MB = bt.shape
        T = MB * self.block_size

        def _g4(pool):   # [N,H,BS,D] -> [B,H,T,D]
            g = pool[bt]                      # [B, MB, H, BS, D]
            g = g.transpose(0, 2, 1, 3, 4)    # [B, H, MB, BS, D]
            return g.reshape(B, self.n_head, T, self.head_dim)

        def _g3(pool):   # [N,H,BS] -> [B,H,T]
            g = pool[bt].transpose(0, 2, 1, 3)
            return g.reshape(B, self.n_head, T)

        k = _g4(pools["k"][layer])
        v = _g4(pools["v"][layer])
        ks = vs = None
        if self.int8_kv:
            ks = _g3(pools["k_scale"][layer])
            vs = _g3(pools["v_scale"][layer])
        if squeeze:
            k, v = k[0], v[0]
            if ks is not None:
                ks, vs = ks[0], vs[0]
        return k, v, ks, vs

    # ------------------------------------------------------- host helpers
    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        return max(1, -(-int(n_tokens) // self.block_size))

    def table_array(self, block_tables, max_blocks, n_rows=None):
        """Host block tables (lists of ids) -> padded ``[B, MB]`` int32
        np array, null-block padded; ``None`` rows (empty slots) are all
        null."""
        if n_rows is None:
            n_rows = len(block_tables)
        out = np.zeros((n_rows, max_blocks), np.int32)
        for i, tbl in enumerate(block_tables):
            if tbl:
                out[i, :len(tbl)] = tbl
        return out
