"""Paged KV cache — fixed-size blocks + a block allocator + block tables.

The production-serving memory model (vLLM/PagedAttention, SOSP '23): the
decode KV cache is ONE pool of fixed-size blocks shared by every request,
and each request owns an ordered *block table* mapping its logical token
positions onto pool blocks. Heterogeneous prompt/generation lengths then
share a single static-shaped compiled decode step — the per-step program
always sees ``[num_blocks, H, block_size, D]`` pools plus small int32
tables, and only the *values* change as requests come and go, so XLA
compiles the decode step exactly once for the whole serving lifetime.

Split of responsibilities:

* ``BlockAllocator`` — host-side free-list over block ids, REFCOUNTED:
  a block may be mapped read-only into several requests' tables at once
  (shared-prefix reuse) and returns to the free list only when its last
  holder releases it. Block 0 is reserved as the *null* block: inactive
  batch slots (and the padded tail of a prefill chunk) route their
  writes there, which keeps the compiled step branch-free, and it is
  never refcounted or handed out. ``free``/``allocate``/``share`` are
  guarded against leaks, double-frees and foreign frees — the guard
  names the holding request and the refcount at failure so an
  accounting bug fails loudly instead of silently corrupting another
  request's cache.
* ``PrefixCache`` — content-addressed index over FULL blocks: each full
  block is keyed by a chain digest of ``(parent_digest, token_ids,
  position_base)`` salted with the attention impl + KV dtype, in a
  bounded LRU. Admission walks a prompt against it and maps every hit
  read-only (prefill then starts at the first uncached token); the
  index holds one reference per resident block, so a block whose last
  *request* finished stays reusable until LRU eviction or
  ``reclaim()`` — which the scheduler calls before any preemption
  fires.
* ``PagedKVCache`` — owns the device pools (per layer: K, V, and for the
  int8 KV layout the per-row fp32 scales, riding the same lane-dim
  convention as ops/transformer/decode.py) plus the scatter/gather
  helpers the runner traces into the compiled step: ``write_decode``
  (one token per slot), ``write_chunk`` (a prefill chunk for one slot)
  and ``gather`` (block table -> contiguous ``[B, H, T, D]`` view that
  composes with ``decode_attention``'s per-sequence lengths).

The gather materialises each slot's logical cache contiguously per step.
Attention has to stream those bytes anyway — decode is KV-bandwidth
bound — so paging costs one extra copy of the *live* window while buying
the capacity sharing that makes continuous batching admissible.
"""

import hashlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.transformer.decode import quantize_kv


class BlockAllocatorError(RuntimeError):
    pass


class BlockAllocator:
    """Refcounted free-list allocator over ``num_blocks`` pool blocks.

    Block 0 is reserved (the null/trash block) and never handed out.
    ``allocate`` is all-or-nothing; ``share`` adds a reference to an
    already-live block (shared-prefix mapping); ``free`` drops one
    reference and recycles the block at zero. Double-frees and foreign
    frees raise with the holding request and the refcount at failure
    named, so an accounting bug fails loudly instead of silently
    corrupting another request's cache.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 usable + the reserved null block), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool pages are hot)
        self._free = list(range(num_blocks - 1, 0, -1))
        # block id -> live reference count (the historical name is kept:
        # the membership/len reads the tests pin still hold)
        self._allocated = {}
        # block id -> one owner label per reference (len == refcount);
        # labels are request ids / "prefix-cache" / None, purely for the
        # failure messages — policy never reads them
        self._owners = {}
        # block id -> label that dropped the LAST reference (what a
        # double-free names as the probable culprit)
        self._last_freed_by = {}

    @staticmethod
    def _label(owner):
        return "<anonymous>" if owner is None else f"request {owner!r}"

    @property
    def num_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    def occupancy(self) -> float:
        """Fraction of usable blocks currently holding live references
        (request tables AND prefix-cache residency)."""
        return len(self._allocated) / max(1, self.num_usable)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def refcount(self, block: int) -> int:
        return self._allocated.get(block, 0)

    def allocate(self, n: int, owner=None):
        """Return ``n`` block ids (each with refcount 1), or ``None``
        when the pool can't cover the request (all-or-nothing; no
        partial grants)."""
        if n < 0:
            raise ValueError(f"allocate({n})")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._allocated[b] = 1
            self._owners[b] = [owner]
        return blocks

    def share(self, blocks, owner=None):
        """Add one reference apiece to already-live blocks (a read-only
        shared-prefix mapping). The null block and free blocks are
        rejected — sharing dead storage is an indexing bug."""
        for b in blocks:
            if b == 0:
                raise BlockAllocatorError(
                    "share of the reserved null block 0 — the null block "
                    "is never refcounted")
            if b not in self._allocated:
                raise BlockAllocatorError(
                    f"share of block {b} which is not allocated "
                    f"(refcount 0) — stale prefix-index entry?")
            self._allocated[b] += 1
            self._owners[b].append(owner)

    def free(self, blocks, owner=None):
        """Drop one reference per block; a block returns to the free
        list when its last reference goes. With ``owner`` given, the
        reference released must actually be held by that owner."""
        for b in blocks:
            rc = self._allocated.get(b, 0)
            if rc == 0:
                culprit = self._last_freed_by.get(b)
                hint = (f"; last released by {self._label(culprit)}"
                        if b in self._last_freed_by else "")
                raise BlockAllocatorError(
                    f"free of block {b} which is not allocated "
                    f"(refcount 0{hint}) — double-free or foreign id")
            owners = self._owners[b]
            if owner is not None and owner not in owners:
                holders = ", ".join(self._label(o) for o in owners)
                raise BlockAllocatorError(
                    f"free of block {b} by {self._label(owner)} which "
                    f"holds no reference to it (refcount {rc}, held by "
                    f"{holders}) — foreign id")
            owners.remove(owner if owner in owners else owners[-1])
            if rc == 1:
                del self._allocated[b]
                del self._owners[b]
                self._last_freed_by[b] = owner
                self._free.append(b)
            else:
                self._allocated[b] = rc - 1

    def check_consistency(self):
        """Invariant check used by the tests: free ∪ allocated is exactly
        the usable id space, the two sets are disjoint, and every live
        block carries one owner label per reference."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise BlockAllocatorError("duplicate ids on the free list")
        live = set(self._allocated)
        if free & live:
            raise BlockAllocatorError(
                f"ids both free and allocated: {free & live}")
        universe = set(range(1, self.num_blocks))
        if free | live != universe:
            raise BlockAllocatorError(
                f"leaked ids: {universe - (free | live)}")
        if 0 in live:
            raise BlockAllocatorError("null block 0 acquired a refcount")
        for b, rc in self._allocated.items():
            if rc < 1 or len(self._owners.get(b, ())) != rc:
                raise BlockAllocatorError(
                    f"block {b}: refcount {rc} != {len(self._owners[b])} "
                    f"owner labels")
        return True


class PrefixCache:
    """Content-addressed shared-prefix index over FULL KV blocks.

    Every full block a request writes is registered under a *chain
    digest* — ``H(parent_digest, token_ids, position_base)`` with the
    cache salt (attention impl, KV dtype, block size) folded into the
    root — so a hit certifies the ENTIRE prefix up to and including the
    block, not just its own tokens (position_base makes the digest
    absolute-position-aware; learned position embeddings mean the same
    tokens at a different offset are different KV). Admission walks a
    prompt block-by-block against the index and maps every hit
    read-only; the index holds ONE allocator reference per resident
    block, so finished requests' prefixes stay warm until LRU eviction
    (capacity bound) or :meth:`reclaim` — the scheduler's
    cheaper-than-preemption block source.

    int8-KV pools share bit-exactly: quantize-on-write makes a block's
    stored bytes a deterministic function of (tokens, positions,
    params), so a reader cannot tell a shared block from one it wrote
    itself.
    """

    OWNER = "prefix-cache"

    def __init__(self, allocator, block_size, capacity_blocks=0, salt=""):
        self.allocator = allocator
        self.block_size = int(block_size)
        # 0 = bounded only by the pool itself
        self.capacity_blocks = int(capacity_blocks)
        self._root = hashlib.blake2b(
            f"prefix/{salt}/{block_size}".encode(),
            digest_size=16).digest()
        # digest -> block id, insertion/touch-ordered (last = hottest)
        self._index = OrderedDict()
        self._digest_of = {}            # block id -> digest (evict path)
        self.hits = 0                   # full prompt blocks mapped from
        self.misses = 0                 # ... / not found at admission
        self.insertions = 0
        self.evictions = 0
        self.cow_forks = 0

    # ----------------------------------------------------------- hashing
    @property
    def root_digest(self):
        return self._root

    def chain_digest(self, parent, tokens, position_base):
        h = hashlib.blake2b(digest_size=16)
        h.update(self._root if parent is None else parent)
        h.update(np.asarray(tokens, np.int64).tobytes())
        h.update(int(position_base).to_bytes(8, "little", signed=False))
        return h.digest()

    # ------------------------------------------------------------ lookup
    def _walk(self, tokens, touch):
        """Longest chain of FULL blocks of ``tokens`` present in the
        index: ``(block_ids, digests)``. ``touch`` refreshes LRU."""
        bs = self.block_size
        blocks, digests = [], []
        parent = self._root
        for j in range(len(tokens) // bs):
            d = self.chain_digest(parent, tokens[j * bs:(j + 1) * bs],
                                  j * bs)
            b = self._index.get(d)
            if b is None:
                break
            if touch:
                self._index.move_to_end(d)
            blocks.append(b)
            digests.append(d)
            parent = d
        return blocks, digests

    def lookup(self, tokens):
        """Admission walk (LRU-touching). Returns the matched leading
        ``(block_ids, digests)`` — counters are booked separately via
        :meth:`record_lookup` once the admission actually lands, so a
        blocked FCFS head retrying every iteration doesn't inflate the
        hit rate."""
        return self._walk(tokens, touch=True)

    def match_blocks(self, tokens) -> int:
        """Pure peek (no LRU touch, no counters): how many leading full
        blocks of ``tokens`` this cache holds. The router's
        prefix-affinity signal."""
        return len(self._walk(tokens, touch=False)[0])

    def record_lookup(self, hit_blocks, full_blocks):
        self.hits += hit_blocks
        self.misses += max(0, full_blocks - hit_blocks)

    # ------------------------------------------------------------ insert
    def insert(self, parent, tokens, position_base, block) -> bytes:
        """Register one FULL block under its chain digest and take the
        index's reference. Returns the digest (the caller threads it as
        the next block's parent). A digest already resident keeps its
        existing block (first writer wins — later identical blocks are
        NOT swapped in, so live sharers never see a remap); over
        capacity the LRU tail is reclaimed first, and when nothing is
        reclaimable the insert is skipped (never steals live blocks)."""
        d = self.chain_digest(parent, tokens, position_base)
        if d in self._index:
            self._index.move_to_end(d)
            return d
        if block == 0:
            raise BlockAllocatorError(
                "prefix-index insert of the reserved null block 0")
        if self.capacity_blocks and len(self._index) >= self.capacity_blocks:
            if self.reclaim(
                    len(self._index) - self.capacity_blocks + 1) == 0:
                return d        # bound holds; chain digest still valid
        self.allocator.share([block], owner=self.OWNER)
        self._index[d] = block
        self._digest_of[block] = d
        self.insertions += 1
        return d

    # ---------------------------------------------------------- eviction
    def resident_blocks(self) -> int:
        return len(self._index)

    def reclaimable_blocks(self) -> int:
        """Resident blocks whose ONLY reference is the index's own."""
        rc = self.allocator.refcount
        return sum(1 for b in self._index.values() if rc(b) == 1)

    def shared_blocks(self) -> int:
        """Resident blocks currently mapped by at least one request —
        the ``serving_prefix_blocks_shared`` gauge."""
        rc = self.allocator.refcount
        return sum(1 for b in self._index.values() if rc(b) > 1)

    def reclaim(self, n: int) -> int:
        """Drop up to ``n`` cold cache-only entries (LRU first),
        returning their blocks to the free list. Entries still mapped by
        a request are skipped — reclaim never breaks a live table. The
        scheduler calls this BEFORE preempting anyone: a cold cached
        block is free capacity, a preemption is recompute debt."""
        if n <= 0:
            return 0
        freed = 0
        for d in list(self._index):
            if freed >= n:
                break
            b = self._index[d]
            if self.allocator.refcount(b) != 1:
                continue        # a request still maps it
            del self._index[d]
            del self._digest_of[b]
            self.allocator.free([b], owner=self.OWNER)
            self.evictions += 1
            freed += 1
        return freed

    def drop_all(self) -> int:
        """Release every cache-only entry (teardown / leak checks)."""
        return self.reclaim(len(self._index))

    def stats(self):
        total = self.hits + self.misses
        return {
            "resident_blocks": len(self._index),
            "reclaimable_blocks": self.reclaimable_blocks(),
            "shared_blocks": self.shared_blocks(),
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 6) if total else 0.0,
            "insertions": self.insertions,
            "evictions": self.evictions,
            "cow_forks": self.cow_forks,
            "capacity_blocks": self.capacity_blocks,
        }


class PagedKVCache:
    """Device block pools + the traced scatter/gather helpers.

    Pools are layer-STACKED arrays (one pytree leaf each, one scatter
    per step via :meth:`write_all_layers`):

    * ``k``/``v``: ``[n_layer, num_blocks, H, block_size, D]`` in the
      activation dtype, or int8 when ``int8_kv`` (the lane-dim int8 KV
      layout that measured 1.33x on the decode bench);
    * ``k_scale``/``v_scale`` (int8 only): ``[n_layer, num_blocks, H,
      block_size]`` fp32 per-row absmax scales.
    """

    def __init__(self, n_layer, n_head, head_dim, block_size, num_blocks,
                 dtype=jnp.float32, int8_kv=False):
        self.n_layer = n_layer
        self.n_head = n_head
        self.head_dim = head_dim
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.int8_kv = bool(int8_kv)
        self.dtype = jnp.int8 if int8_kv else dtype
        self.allocator = BlockAllocator(num_blocks)
        # shared-prefix index (None = prefix caching off). The scheduler
        # reads this attribute; the server attaches it from the
        # serving.prefix_cache config block.
        self.prefix_cache = None

    def attach_prefix_cache(self, capacity_blocks=0, attention_impl=""):
        """Arm shared-prefix reuse: the salt folds in everything that
        makes two bit-identical token prefixes produce different block
        BYTES (attention impl, KV dtype, block size), so a cache can
        never serve a block written under a different layout."""
        self.prefix_cache = PrefixCache(
            self.allocator, self.block_size,
            capacity_blocks=capacity_blocks,
            salt=f"{attention_impl}|{jnp.dtype(self.dtype).name}")
        return self.prefix_cache

    # -------------------------------------------------- pool construction
    def init_pools(self, sharding=None):
        """Zeroed device pools; pass through the jitted step and thread
        the returned (donated) pools back in. Layer-STACKED arrays
        (``[L, N, H, BS, D]``): all layers of a step's K/V land in ONE
        scatter (XLA scatter dispatch is the dominant per-step host cost
        once attention streams only live blocks — 2 scatters/step beats
        2-per-layer by the layer count)."""
        L, N, H, BS, D = (self.n_layer, self.num_blocks, self.n_head,
                          self.block_size, self.head_dim)
        pools = {
            "k": jnp.zeros((L, N, H, BS, D), self.dtype),
            "v": jnp.zeros((L, N, H, BS, D), self.dtype),
        }
        if self.int8_kv:
            pools["k_scale"] = jnp.zeros((L, N, H, BS), jnp.float32)
            pools["v_scale"] = jnp.zeros((L, N, H, BS), jnp.float32)
        # COMMIT the arrays (to the caller's sharding — the server passes
        # a mesh-replicated one matching the engine params): a donated
        # program's outputs are committed, and feeding a committed pool
        # to a program first traced on uncommitted inputs is a silent
        # (and large) recompile on the second step
        return jax.device_put(
            pools, sharding if sharding is not None
            else jax.local_devices()[0])

    def pool_bytes(self) -> int:
        """Total HBM the pools occupy (for the serving metrics)."""
        N, H, BS, D = (self.num_blocks, self.n_head, self.block_size,
                       self.head_dim)
        per_layer = 2 * N * H * BS * D * jnp.dtype(self.dtype).itemsize
        if self.int8_kv:
            per_layer += 2 * N * H * BS * 4
        return per_layer * self.n_layer

    # ------------------------------------------------------ traced writes
    def write_decode(self, pools, layer, k_new, v_new, block_ids, offsets):
        """Write one token's (or one chunk's) K/V into ONE layer's pages.

        k_new/v_new: ``[B, H, D]``; block_ids/offsets: ``[B]`` int32 (the
        scheduler routes inactive slots / pad positions to the null block
        0). Used by the ``gather`` attention impl, whose kernel needs the
        current token in the pool before it reads. The ``paged`` impl
        batches all layers through :meth:`write_all_layers` instead.
        """
        out = dict(pools)
        if self.int8_kv:
            kq, ks = quantize_kv(k_new)                 # scales [B, H]
            vq, vs = quantize_kv(v_new)
            out["k"] = pools["k"].at[layer, block_ids, :, offsets, :].set(kq)
            out["v"] = pools["v"].at[layer, block_ids, :, offsets, :].set(vq)
            out["k_scale"] = pools["k_scale"].at[
                layer, block_ids, :, offsets].set(ks)
            out["v_scale"] = pools["v_scale"].at[
                layer, block_ids, :, offsets].set(vs)
        else:
            dt = pools["k"].dtype
            out["k"] = pools["k"].at[layer, block_ids, :, offsets, :].set(
                k_new.astype(dt))
            out["v"] = pools["v"].at[layer, block_ids, :, offsets, :].set(
                v_new.astype(dt))
        return out

    write_chunk = write_decode      # [C, H, D]: C plays B's role

    def write_all_layers(self, pools, k_all, v_all, block_ids, offsets):
        """Write EVERY layer's K/V for this step in one scatter apiece.

        k_all/v_all: ``[L, B, H, D]`` (decode) or ``[L, C, H, D]``
        (prefill chunk); block_ids/offsets: ``[B]``/``[C]`` int32. The
        advanced indices land on pool dims 1 and 3, so the update tensor
        is expected batch-major — ``[B, L, H, D]``."""
        out = dict(pools)
        if self.int8_kv:
            kq, ks = quantize_kv(k_all)        # scales [L, B, H]
            vq, vs = quantize_kv(v_all)
            out["k"] = pools["k"].at[:, block_ids, :, offsets, :].set(
                kq.transpose(1, 0, 2, 3))
            out["v"] = pools["v"].at[:, block_ids, :, offsets, :].set(
                vq.transpose(1, 0, 2, 3))
            out["k_scale"] = pools["k_scale"].at[
                :, block_ids, :, offsets].set(ks.transpose(1, 0, 2))
            out["v_scale"] = pools["v_scale"].at[
                :, block_ids, :, offsets].set(vs.transpose(1, 0, 2))
        else:
            dt = pools["k"].dtype
            out["k"] = pools["k"].at[:, block_ids, :, offsets, :].set(
                k_all.transpose(1, 0, 2, 3).astype(dt))
            out["v"] = pools["v"].at[:, block_ids, :, offsets, :].set(
                v_all.transpose(1, 0, 2, 3).astype(dt))
        return out

    def write_first_layers(self, pools, k_all, v_all, block_ids, offsets,
                           n_layers):
        """Write the FIRST ``n_layers`` layers' K/V in one scatter apiece
        — the truncated-layer self-draft's write (serving/speculative.py):
        a draft that is the target's first ``n_layers`` layers produces
        bit-identical K/V for those layers, so its speculative positions
        land in the SAME pools and the verify pass simply overwrites all
        layers at the accepted positions.

        k_all/v_all: ``[n_layers, B, H, D]``; block_ids/offsets: ``[B]``
        int32; ``n_layers`` is a static Python int (the static slice
        keeps this the same one-scatter shape as
        :meth:`write_all_layers`, just over a layer prefix)."""
        n = int(n_layers)
        if n == self.n_layer:
            return self.write_all_layers(pools, k_all, v_all, block_ids,
                                         offsets)
        out = dict(pools)
        if self.int8_kv:
            kq, ks = quantize_kv(k_all)        # scales [n, B, H]
            vq, vs = quantize_kv(v_all)
            out["k"] = pools["k"].at[:n, block_ids, :, offsets, :].set(
                kq.transpose(1, 0, 2, 3))
            out["v"] = pools["v"].at[:n, block_ids, :, offsets, :].set(
                vq.transpose(1, 0, 2, 3))
            out["k_scale"] = pools["k_scale"].at[
                :n, block_ids, :, offsets].set(ks.transpose(1, 0, 2))
            out["v_scale"] = pools["v_scale"].at[
                :n, block_ids, :, offsets].set(vs.transpose(1, 0, 2))
        else:
            dt = pools["k"].dtype
            out["k"] = pools["k"].at[:n, block_ids, :, offsets, :].set(
                k_all.transpose(1, 0, 2, 3).astype(dt))
            out["v"] = pools["v"].at[:n, block_ids, :, offsets, :].set(
                v_all.transpose(1, 0, 2, 3).astype(dt))
        return out

    # ------------------------------------------------------ traced gather
    def gather(self, pools, layer, block_tables):
        """Block table -> contiguous per-slot cache views.

        block_tables: ``[B, MB]`` int32 (or ``[MB]`` for one slot).
        Returns ``(k, v, k_scale, v_scale)`` with k/v shaped
        ``[B, H, MB*block_size, D]`` (scales ``[B, H, MB*block_size]`` or
        ``None``) — exactly what ``decode_attention`` /
        ``decode_attention_quantized`` read, with per-sequence lengths
        masking the tail.
        """
        squeeze = block_tables.ndim == 1
        bt = block_tables[None] if squeeze else block_tables
        B, MB = bt.shape
        T = MB * self.block_size

        def _g4(pool):   # [N,H,BS,D] -> [B,H,T,D]
            g = pool[bt]                      # [B, MB, H, BS, D]
            g = g.transpose(0, 2, 1, 3, 4)    # [B, H, MB, BS, D]
            return g.reshape(B, self.n_head, T, self.head_dim)

        def _g3(pool):   # [N,H,BS] -> [B,H,T]
            g = pool[bt].transpose(0, 2, 1, 3)
            return g.reshape(B, self.n_head, T)

        k = _g4(pools["k"][layer])
        v = _g4(pools["v"][layer])
        ks = vs = None
        if self.int8_kv:
            ks = _g3(pools["k_scale"][layer])
            vs = _g3(pools["v_scale"][layer])
        if squeeze:
            k, v = k[0], v[0]
            if ks is not None:
                ks, vs = ks[0], vs[0]
        return k, v, ks, vs

    # ------------------------------------------------------- host helpers
    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        return max(1, -(-int(n_tokens) // self.block_size))

    def table_array(self, block_tables, max_blocks, n_rows=None):
        """Host block tables (lists of ids) -> padded ``[B, MB]`` int32
        np array, null-block padded; ``None`` rows (empty slots) are all
        null."""
        if n_rows is None:
            n_rows = len(block_tables)
        out = np.zeros((n_rows, max_blocks), np.int32)
        for i, tbl in enumerate(block_tables):
            if tbl:
                out[i, :len(tbl)] = tbl
        return out
