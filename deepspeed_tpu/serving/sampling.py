"""Per-request sampling for the compiled decode step.

The batch-synchronous engine samples with ONE temperature baked into the
compiled loop (a new temperature = a new program). Serving inverts that:
temperature/top-p/seed are *per-request tensors* ``[B]`` flowing through
one compiled program, so any mix of greedy and sampled requests shares
the same decode step.

RNG: every request owns a PRNG key lane (``[B, 2]`` uint32, built host-
side from its seed). Each step folds the slot's current position into its
lane — sampling is deterministic per (seed, position) and independent of
which batch slot or step the token happened to land in, which is what
makes continuous batching reproducible under preemption/resume.

Top-p (nucleus): sort descending, keep the smallest prefix whose
*exclusive* cumulative probability is < p (the top-1 token always
survives), then threshold the unsorted logits — no scatter back through
the sort permutation needed.
"""

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def fold_position_lanes(rng_lanes, positions):
    """Fold each slot's POSITION into its key lane: ``[B, 2]`` uint32
    lanes + ``[B]`` int32 positions -> ``[B, 2]`` folded keys.

    This is THE randomness schedule of the serving engine: a token's draw
    depends only on (request seed, absolute position), never on which
    batch slot, decode_steps grouping, or draft/verify path produced it.
    The decode scan and the speculative verify program both call this
    helper, so speculative acceptance under sampling compares the SAME
    draw sequential decoding would have made at that position.
    """
    return jax.vmap(jax.random.fold_in)(rng_lanes, positions)


def top_p_filter(logits, top_p):
    """Nucleus filter. logits ``[B, V]`` fp32, top_p ``[B]`` in (0, 1];
    p >= 1 keeps everything. Returns filtered logits with non-nucleus
    entries at NEG_INF."""
    sorted_desc = -jnp.sort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]       # exclusive cumsum: top-1 stays
    threshold = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1)
    return jnp.where(logits >= threshold[:, None], logits, NEG_INF)


def sample_tokens(logits, temperature, top_p, rng_lanes, positions,
                  vocab_size=None):
    """One sampled token per slot, all policies in one traced program.

    logits ``[B, Vpad]`` fp32; temperature/top_p ``[B]`` fp32 (temperature
    <= 0 means greedy for that slot); rng_lanes ``[B, 2]`` uint32 per-
    request key lanes; positions ``[B]`` int32 (folded into the lane so
    each step draws fresh randomness). ``vocab_size`` masks Megatron-style
    padded vocab rows, which must never be sampled. Returns ``[B]`` int32.
    """
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        logits = logits[:, :vocab_size]
    logits = logits.astype(jnp.float32)
    greedy = temperature <= 0.0
    argmax = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def mixed(_):
        safe_t = jnp.where(greedy, 1.0, temperature)
        scaled = logits / safe_t[:, None]
        filtered = top_p_filter(scaled, top_p)
        folded = fold_position_lanes(rng_lanes, positions)
        sampled = jax.vmap(jax.random.categorical)(folded, filtered)
        return jnp.where(greedy, argmax, sampled).astype(jnp.int32)

    # all-greedy batches skip the sort/top-p/categorical work at RUNTIME
    # (lax.cond executes one branch) while staying one compiled program —
    # the decode step is hot enough that the dead sampling machinery was
    # a measurable tax on greedy traffic
    return jax.lax.cond(jnp.all(greedy), lambda _: argmax, mixed,
                        operand=None)


def make_rng_lane(seed: int):
    """Host-side: one request's key lane (uint32[2]) from its seed."""
    import numpy as np
    key = jax.random.PRNGKey(int(seed))
    return np.asarray(jax.device_get(key), np.uint32)
