"""Speculative decoding over the paged + prefix-cached KV.

Decode at small batch is weight-bandwidth-bound (PERF.md, the step
anatomy profiler): every generated token streams the full parameter set
for ONE matmul row. Speculative decoding converts that waste into
parallelism — a cheap draft proposes K tokens, then the target model
scores all K+1 positions in ONE forward (near-batch cost in the
bandwidth-bound regime) and keeps the longest prefix it agrees with.

Two compiled programs, both static-shaped for the serving lifetime:

* ``draft_step`` — K greedy steps through the DRAFT. The default draft
  is the truncated-layer self-draft (LayerSkip-style early exit): the
  first ``draft_layers`` of the target's own params pytree plus the
  shared ``ln_f``/tied head — zero extra weights to load, and its layer
  K/V are bit-identical to the target's, so draft writes land in the
  same pools (``write_first_layers``) at the speculative positions.
  An explicitly configured small model (``draft_params``) rides the same
  program; draft quality only moves the ACCEPTANCE RATE, never
  correctness — the verify pass decides every delivered token.
* ``verify_step`` — the target forward over ``K+1`` positions per slot
  (the slot's last accepted token + K drafted), the batched cross of the
  decode and prefill-chunk programs: past pages stream through
  ``paged_verify_attention`` while the candidate chunk stays in
  registers (causal), then ONE stacked scatter writes all layers at all
  candidate positions. Target tokens come from the SAME
  ``sample_tokens`` + position-fold the decode scan uses, so greedy
  verification is argmax-for-argmax the sequential program and sampled
  verification draws the exact (seed, position) stream sequential
  decoding would have drawn.

Rejection is a STATE EDIT, not a recompute: the host simply does not
advance ``cached_len`` past the accepted prefix. Rejected positions keep
stale pool bytes — attention masks every column ``>= past_len``, so they
are invisible until the correct tokens overwrite them. Writes are
budget-masked to the slot's allocated blocks and always land at
positions ``>= cached_len``, which the scheduler keeps strictly outside
prefix-cache-shared (always-full) blocks — speculation can never dirty a
shared or indexed block. The slot-step ledger books the rejected
positions into the ``drafted_rejected`` category so speculation cost is
measured, not hidden (telemetry/serving_observatory.py).

Acceptance rules: ``"exact"`` (default) accepts a drafted token iff it
equals the target's own token for that position — bit-exact parity with
the non-speculative engine for greedy AND sampled requests.
``"typical"`` relaxes sampled slots to accept any draft whose target
probability clears ``typical_threshold`` × the modal probability
(greedy slots stay exact) — higher acceptance, no parity guarantee.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.serving.paged_attention import paged_verify_attention
from deepspeed_tpu.serving.runner import NEG_INF, _dense, _ln, _sub
from deepspeed_tpu.serving.sampling import sample_tokens


def default_draft_layers(n_layer: int) -> int:
    """Self-draft depth when the config leaves ``draft_layers`` at 0:
    a quarter of the stack (floor 1) — the shallowest exit that keeps
    acceptance useful on well-trained models."""
    return max(1, int(n_layer) // 4)


def validate_draft_params(params, target_params, n_layers: int):
    """An explicit draft must be pool- and head-compatible with the
    target: same embedding width (its K/V land in the target's pools),
    same vocab rows (its argmax is compared against target tokens), and
    at least ``n_layers`` transformer blocks plus the exit pieces."""
    for key in ("wte", "wpe", "ln_f"):
        if key not in params:
            raise ValueError(f"draft params missing {key!r}")
    if params["wte"].shape != target_params["wte"].shape:
        raise ValueError(
            f"draft wte {params['wte'].shape} != target "
            f"{target_params['wte'].shape}: the draft must share the "
            f"target's vocab and embedding width")
    for layer in range(n_layers):
        if f"h_{layer}" not in params:
            raise ValueError(
                f"draft params has no h_{layer} but draft_layers="
                f"{n_layers}")


class SpeculativeDecoder:
    """The two jitted programs + acceptance logic behind the server's
    speculative decode path. Holds NO per-request state — the server
    threads pools/positions exactly as it does for the plain decode
    program, and rollback is the server not advancing ``cached_len``."""

    def __init__(self, runner, *, k, draft_layers=0, acceptance="exact",
                 typical_threshold=0.3, draft_params=None,
                 draft_scales=None):
        assert k >= 1, f"speculative k must be >= 1, got {k}"
        assert acceptance in ("exact", "typical"), acceptance
        self.runner = runner
        self.k = int(k)
        L = runner.cfg.n_layer
        self.draft_layers = (int(draft_layers) if draft_layers
                             else default_draft_layers(L))
        if draft_params is None:
            assert 1 <= self.draft_layers <= L, (
                f"self-draft draft_layers={self.draft_layers} must be in "
                f"[1, n_layer={L}]")
        self.acceptance = acceptance
        self.typical_threshold = float(typical_threshold)
        self.draft_params = draft_params
        self.draft_scales = draft_scales or {}
        # donated pools for the same reason as the runner's programs:
        # the scatters stay in-place and the server re-threads the result
        self._draft = jax.jit(self._draft_impl, donate_argnums=(2,))
        self._verify = jax.jit(self._verify_impl, donate_argnums=(2,))

    # ----------------------------------------------------------- draft
    def _draft_impl(self, params, scales, pools, bt, pos, active, tok,
                    budget):
        """K greedy steps through the first ``draft_layers`` of
        ``params`` (the scan body is the runner's own ``_stack_decode``
        over a layer prefix). Writes ride ``write_first_layers`` at the
        speculative positions, budget-masked to the null block beyond
        each slot's allocation. Returns ``(pools, drafted [K, B])``."""
        r = self.runner
        vocab = r.cfg.vocab_size

        def body(carry, i):
            pools, cur = carry
            step_pos = pos + jnp.minimum(i, jnp.maximum(budget - 1, 0))
            live = active & (i < budget)
            pools, logits = r._stack_decode(
                params, scales, pools, bt, step_pos, live, cur,
                n_layers=self.draft_layers)
            nxt = jnp.argmax(logits[:, :vocab], axis=-1).astype(jnp.int32)
            cur = jnp.where(live, nxt, cur)
            return (pools, cur), nxt

        (pools, _), drafted = jax.lax.scan(
            body, (pools, tok), jnp.arange(self.k, dtype=jnp.int32))
        return pools, drafted

    # ---------------------------------------------------------- verify
    def _attn_verify(self, p, s, layer, x, pools, bt, pos, poss, live_w):
        """One layer's attention for the K+1 candidate chunk of every
        slot. Paged impl: past pages + the chunk from registers (write
        deferred to the stacked scatter). Gather impl: eager write, then
        dense per-query-masked attention over the contiguous view — the
        batched form of the prefill chunk's gather branch."""
        r = self.runner
        cache = r.cache
        B, C = poss.shape
        H, D = r.n_head, r.head_dim
        N, E = x.shape
        int8 = cache.int8_kv
        q, k, v = r._qkv(p, s, x)                       # [B*C, H, D]

        def heads(t):                                   # -> [B, H, C, D]
            return t.reshape(B, C, H, D).transpose(0, 2, 1, 3)

        if r.attention_impl == "paged":
            out = paged_verify_attention(
                heads(q), heads(r._requant(k)), heads(r._requant(v)),
                layer, pools["k"], pools["v"], bt, pos,
                k_scale_pool=pools["k_scale"] if int8 else None,
                v_scale_pool=pools["v_scale"] if int8 else None)
            out = out.transpose(0, 2, 1, 3).reshape(N, E).astype(x.dtype)
            proj = _dense(out, p["attn"]["proj"], _sub(s, "attn", "proj"))
            return pools, proj, (k, v)
        bs = cache.block_size
        MB = bt.shape[1]
        row = jnp.take_along_axis(bt, jnp.minimum(poss // bs, MB - 1),
                                  axis=1)                # [B, C]
        blk = jnp.where(live_w, row, 0).reshape(-1)
        pools = cache.write_decode(pools, layer, k, v, blk,
                                   (poss % bs).reshape(-1))
        kg, vg, ksg, vsg = cache.gather(pools, layer, bt)  # [B, H, T, D]
        if int8:
            kg = (kg.astype(jnp.float32) * ksg[..., None]).astype(x.dtype)
            vg = (vg.astype(jnp.float32) * vsg[..., None]).astype(x.dtype)
        T = kg.shape[2]
        scores = jnp.einsum("bhcd,bhtd->bhct", heads(q).astype(jnp.float32),
                            kg.astype(jnp.float32)) * (D ** -0.5)
        mask = jnp.arange(T)[None, None, :] <= poss[:, :, None]  # [B, C, T]
        scores = jnp.where(mask[:, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhct,bhtd->bhcd", probs.astype(vg.dtype), vg)
        out = out.transpose(0, 2, 1, 3).reshape(N, E).astype(x.dtype)
        proj = _dense(out, p["attn"]["proj"], _sub(s, "attn", "proj"))
        return pools, proj, None

    def _verify_impl(self, params, scales, pools, bt, pos, active,
                     drafted, tok, temp, top_p, lanes, budget):
        """ONE target forward over K+1 positions per slot; returns
        ``(pools, accepted [B], tokens [K+1, B])`` where ``tokens`` row
        ``j`` is the j-th delivered token (accepted drafts, then the
        target's own token at the first disagreement — the bonus
        token). Only ``min(accepted+1, budget)`` rows are meaningful per
        slot; the host caps delivery."""
        r = self.runner
        cache = r.cache
        cfg = r.cfg
        bs = cache.block_size
        K = self.k
        C = K + 1
        B = tok.shape[0]
        toks_in = jnp.concatenate([tok[None], drafted], axis=0).T  # [B, C]
        poss = pos[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
        live_w = active[:, None] \
            & (jnp.arange(C, dtype=jnp.int32)[None, :] < budget[:, None])
        # the tail candidates of a budget-capped slot can step past
        # n_positions; their rows are write-masked, clamp keeps the
        # embedding gather legal (same move as the prefill pad tail)
        pos_emb = jnp.minimum(poss, cfg.n_positions - 1)
        x = (params["wte"][toks_in]
             + params["wpe"][pos_emb].astype(params["wte"].dtype))
        x = x.reshape(B * C, cfg.n_embd)
        kv_stack = []
        for layer in range(cfg.n_layer):
            p = params[f"h_{layer}"]
            s = _sub(scales, f"h_{layer}")
            pools, a, kv = self._attn_verify(p, s, layer, x, pools, bt,
                                             pos, poss, live_w)
            if kv is not None:
                kv_stack.append(kv)
            x = x + a
            x = x + r._mlp(p, s, x)
        if kv_stack:
            # ONE stacked scatter for all layers × all K+1 positions —
            # accepted positions become real target KV, rejected ones
            # become stale bytes the past_lens mask never reads
            MB = bt.shape[1]
            row = jnp.take_along_axis(bt, jnp.minimum(poss // bs, MB - 1),
                                      axis=1)
            blk = jnp.where(live_w, row, 0).reshape(-1)
            pools = cache.write_all_layers(
                pools, jnp.stack([k for k, _ in kv_stack]),
                jnp.stack([v for _, v in kv_stack]), blk,
                (poss % bs).reshape(-1))
        x = _ln(x, params["ln_f"])
        logits = jnp.einsum("be,ve->bv", x, params["wte"],
                            preferred_element_type=jnp.float32)
        # the target's OWN token at every position: same sampler, same
        # position fold as the decode scan -> path-invariant draws
        flat_pos = poss.reshape(-1)
        tgt = sample_tokens(
            logits, jnp.repeat(temp, C), jnp.repeat(top_p, C),
            jnp.repeat(lanes, C, axis=0), flat_pos,
            vocab_size=cfg.vocab_size).reshape(B, C)
        dT = drafted.T                                   # [B, K]
        match = dT == tgt[:, :K]
        if self.acceptance == "typical":
            probs = jax.nn.softmax(
                logits[:, :cfg.vocab_size].reshape(B, C, -1)
                [:, :K], axis=-1)
            p_draft = jnp.take_along_axis(
                probs, dT[..., None], axis=-1)[..., 0]   # [B, K]
            typical = p_draft >= self.typical_threshold \
                * jnp.max(probs, axis=-1)
            match = jnp.where((temp > 0.0)[:, None], typical, match)
        accepted = jnp.sum(
            jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        cols = jnp.arange(C, dtype=jnp.int32)[None, :]
        out = jnp.where(cols < accepted[:, None],
                        jnp.pad(dT, ((0, 0), (0, 1))), tgt)
        return pools, accepted, out.T

    # ------------------------------------------------------- public API
    def draft_step(self, params, scales, pools, bt, pos, active, tok,
                   budget):
        """One draft DISPATCH: K greedy candidates per slot; returns
        ``(pools, drafted [K, B] int32 device array)``. Pass the draft's
        own params (``draft_params``) or the target's (self-draft)."""
        return self._draft(params, scales or {}, pools, bt, pos, active,
                           tok, budget)

    def verify_step(self, params, scales, pools, bt, pos, active,
                    drafted, tok, temp, top_p, lanes, budget):
        """One verify DISPATCH; returns ``(pools, accepted [B],
        tokens [K+1, B])`` device arrays (ONE host sync for both)."""
        return self._verify(params, scales or {}, pools, bt, pos, active,
                            drafted, tok, temp, top_p, lanes, budget)
