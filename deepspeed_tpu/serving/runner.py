"""Paged model runner — the two compiled programs behind the server.

The flax decode path (models/gpt2.py ``decode=True``) owns a per-batch
contiguous cache with ONE shared ``cache_index`` — every sequence in the
batch must sit at the same position, which is exactly what continuous
batching breaks. This runner re-expresses the same GPT-2 math directly
over the model's *params pytree* with per-slot positions and the paged
pool from serving/kv_cache.py:

* ``decode_step`` — the one static-shaped program the server calls every
  iteration: embeds each slot's last token at its own position, writes
  its K/V through the slot's block table, gathers pages into the
  contiguous view ``decode_attention`` reads (per-sequence lengths), and
  samples the next token per request (serving/sampling.py). Compiled
  once for the whole serving lifetime — request churn only changes
  tensor *values*.
* ``prefill_chunk`` — fills one slot's prompt KV ``chunk`` tokens at a
  time (serving/prefill.py plans the chunks) so a long prompt never
  stalls the decode batch. Also compiled once: the final short chunk is
  padded and its tail writes are routed to the null block.

Weight formats: float kernels and the engine's TRUE int8 weight storage
(module_quantize ``quant_scales`` collection) both work — the dequant
folds into the matmul exactly like QuantDense. The int8 *KV* layout is
the cache's concern and composes transparently.

Scope guards (asserted at construction): GPT2LMHeadModel-family param
trees, learned position embeddings, no MoE / pipeline / sequence
parallelism, mp_size 1.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer.int8_linear import int8_matmul
from deepspeed_tpu.ops.transformer.decode import (decode_attention,
                                                  decode_attention_quantized,
                                                  quantize_kv)
from deepspeed_tpu.serving.paged_attention import (paged_decode_attention,
                                                   paged_prefill_attention)
from deepspeed_tpu.serving.sampling import NEG_INF, sample_tokens

_LN_EPS = 1e-5


def _ln(x, p):
    """nn.LayerNorm(epsilon=1e-5) parity (fast-variance form)."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.maximum(jnp.mean(x * x, axis=-1, keepdims=True) - mu * mu, 0.0)
    y = (x - mu) * jax.lax.rsqrt(var + _LN_EPS)
    return y * p["scale"] + p["bias"]


def _dense(x, p, scales=None):
    """QuantDense parity: float kernels matmul directly; int8 kernels
    fold the per-column scale into the matmul."""
    kernel = p["kernel"]
    bias = p.get("bias")
    if kernel.dtype == jnp.int8:
        return int8_matmul(x, kernel, scales["kernel_scale"], bias)
    y = x @ kernel
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def _sub(scales, *path):
    """Descend the quant_scales mirror (may be absent)."""
    node = scales
    for seg in path:
        if not isinstance(node, dict) or seg not in node:
            return None
        node = node[seg]
    return node


class PagedGPT2Runner:
    def __init__(self, model, cache, use_flash=None,
                 attention_impl="paged", decode_steps=1):
        """``attention_impl``: ``"paged"`` (default) streams attention
        over LIVE KV blocks with a dynamic-trip-count loop — per-step
        traffic scales with how many tokens actually exist
        (serving/paged_attention.py). ``"gather"`` materialises each
        slot's pages into the contiguous view the
        ops/transformer/decode.py Pallas kernel reads — fixed
        ``T_max``-window traffic, but the decode GEMMs run in the tuned
        TPU kernel."""
        assert attention_impl in ("paged", "gather"), attention_impl
        assert decode_steps >= 1
        self.attention_impl = attention_impl
        self.decode_steps = int(decode_steps)
        cfg = model.config
        for attr in ("n_layer", "n_head", "n_embd", "n_positions",
                     "vocab_size"):
            assert hasattr(cfg, attr), (
                f"serving needs a GPT2Config-like model config (missing "
                f"{attr!r}); got {type(cfg).__name__}")
        assert getattr(cfg, "position_embedding", "learned") == "learned", \
            "serving: rope per-slot offsets not wired yet; use 'learned'"
        assert getattr(cfg, "moe_num_experts", 0) == 0, \
            "serving: MoE decode not supported"
        assert getattr(cfg, "pp_stages", 1) == 1, \
            "serving: pipeline-parallel models not supported"
        mode = getattr(cfg, "attention_mode", "auto")
        assert not str(mode).startswith(("ring:", "ulysses:", "sparse")), (
            f"serving decode is dense KV-cache attention; "
            f"attention_mode={mode!r} models must serve with 'auto'")
        self.cfg = cfg
        self.cache = cache
        self.use_flash = use_flash
        self.n_head = cfg.n_head
        self.head_dim = cfg.n_embd // cfg.n_head
        # donating the pools makes every KV scatter a true in-place
        # update instead of a whole-pool copy per layer per step
        # (measured 14x on the CPU backend, which aliases fine too); the
        # server re-threads the returned pools so the stale buffers are
        # never touched
        self._decode = jax.jit(self._decode_impl, donate_argnums=(2,))
        self._prefill = jax.jit(self._prefill_impl, donate_argnums=(2,))
        # copy-on-write block fork (prefix cache): ONE device block copy
        # across every pool leaf (all layers in one update apiece, the
        # same stacked layout the write scatters ride). A third tiny
        # program — deliberately NOT part of decode/prefill, whose
        # signatures the one-program acceptance pins.
        self._copy_block = jax.jit(self._copy_block_impl,
                                   donate_argnums=(0,))

    # -------------------------------------------------------- block copy
    @staticmethod
    def _copy_block_impl(pools, src, dst):
        """``pools[leaf][:, dst] = pools[leaf][:, src]`` for every leaf
        (K, V and the int8 scales ride the same ``[L, N, ...]`` block
        dim). src/dst are traced int32 scalars, so every fork reuses one
        compiled program."""
        return {name: p.at[:, dst].set(
            jax.lax.dynamic_index_in_dim(p, src, axis=1, keepdims=False))
            for name, p in pools.items()}

    def copy_block(self, pools, src, dst):
        """Fork one block's bytes: the COW path's single device op."""
        return self._copy_block(pools, jnp.int32(src), jnp.int32(dst))

    # ------------------------------------------------------------ layers
    def _qkv(self, p, s, x):
        B_or_C = x.shape[0]
        H, D = self.n_head, self.head_dim
        qkv = _dense(_ln(x, p["ln_1"]), p["attn"]["qkv"],
                     _sub(s, "attn", "qkv"))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        return (q.reshape(B_or_C, H, D), k.reshape(B_or_C, H, D),
                v.reshape(B_or_C, H, D))

    def _requant(self, kv):
        """What the pool will hold for these rows: int8-round-tripped
        values, so the current token's self-attention matches what every
        later step reads (the flax decode path quantises on write too)."""
        if not self.cache.int8_kv:
            return kv
        kq, ks = quantize_kv(kv)
        return kq.astype(jnp.float32) * ks[..., None]

    def _attn_decode(self, p, s, layer, x, pools, bt, pos, active):
        """Paged impl: attend over PAST pool + current token from
        registers; returns the layer's (k, v) so the caller scatters all
        layers at once. Gather impl: eager per-layer write, then the
        ops/transformer/decode.py kernel over the contiguous view."""
        B, E = x.shape
        int8 = self.cache.int8_kv
        q, k, v = self._qkv(p, s, x)
        if self.attention_impl == "paged":
            out = paged_decode_attention(
                q, self._requant(k), self._requant(v),
                layer, pools["k"], pools["v"], bt, pos,
                k_scale_pool=pools["k_scale"] if int8 else None,
                v_scale_pool=pools["v_scale"] if int8 else None)
            out = out.reshape(B, E).astype(x.dtype)
            proj = _dense(out, p["attn"]["proj"], _sub(s, "attn", "proj"))
            return pools, proj, (k, v)
        bs = self.cache.block_size
        row = jnp.take_along_axis(bt, (pos // bs)[:, None], axis=1)[:, 0]
        blk = jnp.where(active, row, 0)
        pools = self.cache.write_decode(pools, layer, k, v, blk, pos % bs)
        lens = pos + 1
        kg, vg, ksg, vsg = self.cache.gather(pools, layer, bt)
        q4 = q[:, :, None, :]
        if int8:
            out = decode_attention_quantized(
                q4, kg, ksg, vg, vsg, lens, use_flash=self.use_flash)
        else:
            out = decode_attention(q4, kg, vg, lens,
                                   use_flash=self.use_flash)
        out = out[:, :, 0, :].reshape(B, E).astype(x.dtype)
        proj = _dense(out, p["attn"]["proj"], _sub(s, "attn", "proj"))
        return pools, proj, None

    def _attn_prefill(self, p, s, layer, x, pools, bt_row, pos, start,
                      n_valid):
        """Chunk attention for one slot. Paged impl: past pages + the
        chunk from registers (write deferred to one stacked scatter).
        Gather impl: eager write, dense masked attention over the
        contiguous view."""
        C, E = x.shape
        D = self.head_dim
        int8 = self.cache.int8_kv
        q, k, v = self._qkv(p, s, x)                    # [C, H, D]
        qh = q.transpose(1, 0, 2)                       # [H, C, D]
        if self.attention_impl == "paged":
            out = paged_prefill_attention(
                qh, self._requant(k).transpose(1, 0, 2),
                self._requant(v).transpose(1, 0, 2),
                layer, pools["k"], pools["v"], bt_row, pos, start,
                k_scale_pool=pools["k_scale"] if int8 else None,
                v_scale_pool=pools["v_scale"] if int8 else None)
            out = out.transpose(1, 0, 2).reshape(C, E).astype(x.dtype)
            proj = _dense(out, p["attn"]["proj"], _sub(s, "attn", "proj"))
            return pools, proj, (k, v)
        bs = self.cache.block_size
        MB = bt_row.shape[0]
        valid = jnp.arange(C) < n_valid
        blk = jnp.where(valid,
                        bt_row[jnp.minimum(pos // bs, MB - 1)], 0)
        pools = self.cache.write_chunk(pools, layer, k, v, blk, pos % bs)
        kg, vg, ksg, vsg = self.cache.gather(pools, layer, bt_row)
        if int8:
            kg = (kg.astype(jnp.float32) * ksg[..., None]).astype(x.dtype)
            vg = (vg.astype(jnp.float32) * vsg[..., None]).astype(x.dtype)
        scores = jnp.einsum("hcd,htd->hct", qh, kg.astype(qh.dtype),
                            preferred_element_type=jnp.float32)
        scores = scores * (D ** -0.5)
        T = kg.shape[1]
        mask = jnp.arange(T)[None, :] <= pos[:, None]   # [C, T]
        scores = jnp.where(mask[None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("hct,htd->hcd", probs.astype(vg.dtype), vg)
        out = out.transpose(1, 0, 2).reshape(C, E).astype(x.dtype)
        proj = _dense(out, p["attn"]["proj"], _sub(s, "attn", "proj"))
        return pools, proj, None

    def _mlp(self, p, s, x):
        h = jax.nn.gelu(_dense(_ln(x, p["ln_2"]), p["mlp"]["fc"],
                               _sub(s, "mlp", "fc")), approximate=True)
        return _dense(h, p["mlp"]["proj"], _sub(s, "mlp", "proj"))

    # ---------------------------------------------------------- programs
    def _stack_decode(self, params, scales, pools, bt, pos, live, tok,
                      n_layers=None):
        """Embed each live slot's token at its own position, run the
        first ``n_layers`` of the stack (default: all), write those
        layers' K/V, and return ``(pools, logits)``.

        ``n_layers < cfg.n_layer`` is the truncated-layer self-draft of
        serving/speculative.py: the SAME params pytree traced over a
        layer prefix (plus the shared ln_f and tied head) — zero extra
        weights, and the prefix layers' K/V are bit-identical to the
        target's, so draft writes land in the same pools."""
        cfg = self.cfg
        bs = self.cache.block_size
        L = cfg.n_layer if n_layers is None else int(n_layers)
        x = params["wte"][tok] + params["wpe"][pos].astype(
            params["wte"].dtype)
        kv_stack = []
        for layer in range(L):
            p = params[f"h_{layer}"]
            s = _sub(scales, f"h_{layer}")
            pools, a, kv = self._attn_decode(p, s, layer, x, pools, bt,
                                             pos, live)
            if kv is not None:
                kv_stack.append(kv)
            x = x + a
            x = x + self._mlp(p, s, x)
        if kv_stack:
            # paged impl: ONE stacked scatter for all layers; non-live
            # slots land in the null block
            row = jnp.take_along_axis(bt, (pos // bs)[:, None],
                                      axis=1)[:, 0]
            blk = jnp.where(live, row, 0)
            pools = self.cache.write_first_layers(
                pools, jnp.stack([k for k, _ in kv_stack]),
                jnp.stack([v for _, v in kv_stack]), blk, pos % bs, L)
        x = _ln(x, params["ln_f"])
        logits = jnp.einsum("be,ve->bv", x, params["wte"],
                            preferred_element_type=jnp.float32)
        return pools, logits

    def _decode_one(self, params, scales, pools, bt, pos, live, tok,
                    temp, top_p, lanes):
        """One decode iteration over the slot batch: embed each live
        slot's token at its own position, run the stack, write all
        layers' K/V, sample."""
        pools, logits = self._stack_decode(params, scales, pools, bt,
                                           pos, live, tok)
        nxt = sample_tokens(logits, temp, top_p, lanes, pos,
                            vocab_size=self.cfg.vocab_size)
        return pools, nxt

    def _decode_impl(self, params, scales, pools, bt, pos, active, tok,
                     temp, top_p, lanes, budget):
        """``decode_steps`` iterations in one dispatch (lax.scan).

        ``budget`` [B]: tokens this dispatch may produce per slot (the
        scheduler caps it by remaining generation / model length /
        allocated blocks). A slot past its budget FREEZES — its writes
        route to the null block, its position stops advancing, and its
        sampled tokens are discarded host-side. K=1 reduces to classic
        per-token continuous batching. Returns (pools, tokens [K, B]).
        """
        K = self.decode_steps

        def body(carry, i):
            pools, cur = carry
            step_pos = pos + jnp.minimum(i, budget)
            live = active & (i < budget)
            pools, nxt = self._decode_one(params, scales, pools, bt,
                                          step_pos, live, cur, temp,
                                          top_p, lanes)
            cur = jnp.where(live, nxt, cur)
            return (pools, cur), nxt

        if K == 1:
            live = active & (budget > 0)
            pools, nxt = self._decode_one(params, scales, pools, bt, pos,
                                          live, tok, temp, top_p, lanes)
            return pools, nxt[None]
        (pools, _), toks = jax.lax.scan(
            body, (pools, tok), jnp.arange(K, dtype=jnp.int32))
        return pools, toks

    def _prefill_impl(self, params, scales, pools, bt_row, tokens, start,
                      n_valid):
        cfg = self.cfg
        bs = self.cache.block_size
        MB = bt_row.shape[0]
        C = tokens.shape[0]
        pos = start + jnp.arange(C, dtype=jnp.int32)
        # the padded tail of the final chunk can step past n_positions;
        # its embedding rows are discarded, clamp keeps the gather legal
        pos_emb = jnp.minimum(pos, cfg.n_positions - 1)
        x = params["wte"][tokens] + params["wpe"][pos_emb].astype(
            params["wte"].dtype)
        kv_stack = []
        for layer in range(cfg.n_layer):
            p = params[f"h_{layer}"]
            s = _sub(scales, f"h_{layer}")
            pools, a, kv = self._attn_prefill(p, s, layer, x, pools,
                                              bt_row, pos, start, n_valid)
            if kv is not None:
                kv_stack.append(kv)
            x = x + a
            x = x + self._mlp(p, s, x)
        if kv_stack:
            valid = jnp.arange(C) < n_valid
            blk = jnp.where(valid,
                            bt_row[jnp.minimum(pos // bs, MB - 1)], 0)
            pools = self.cache.write_all_layers(
                pools, jnp.stack([k for k, _ in kv_stack]),
                jnp.stack([v for _, v in kv_stack]), blk, pos % bs)
        return pools

    # -------------------------------------------------------- public API
    def decode_step(self, params, scales, pools, bt, pos, active, tok,
                    temp, top_p, lanes, budget):
        """One decode DISPATCH (``decode_steps`` tokens per slot, budget-
        capped); returns ``(pools, tokens [K, B] int32 device array)``."""
        return self._decode(params, scales or {}, pools, bt, pos, active,
                            tok, temp, top_p, lanes, budget)

    def prefill_chunk(self, params, scales, pools, bt_row, tokens, start,
                      n_valid):
        """Fill ``n_valid`` prompt tokens of one slot's KV; returns
        updated pools."""
        return self._prefill(params, scales or {}, pools, bt_row, tokens,
                             start, n_valid)
