"""SLO-aware multi-replica request router.

One :class:`~deepspeed_tpu.serving.server.ServingEngine` saturates at
its KV pool and static batch; scaling past that is N replicas behind a
router. Placement is a pure host-side argmax over per-replica scores —
no device work, no shared state between replicas, no change to any
replica's compiled programs:

    score = affinity_weight  * matched_prefix_blocks
          - queue_weight     * (queue_depth + active)
          - occupancy_weight * kv_occupancy
          - breach_penalty   * recent_slo_breach

``matched_prefix_blocks`` is the replica prefix cache's pure peek
(:meth:`PrefixCache.match_blocks` — no LRU touch, no counters), so
routing concentrates a shared-prefix flow onto the replica that already
holds its KV instead of re-prefilling it N times (the cache-aware
routing move from the SGLang playbook). The load terms come from
:meth:`ServingEngine.router_signals`; ``recent_slo_breach`` is true when
the replica's PR-9 observatory fired ``ttft_slo_breach`` or
``queue_growth`` within its last two windows. ``breach_penalty``
dominates the other terms by construction, so a breaching replica only
receives traffic when EVERY replica is breaching — failover, not a
permanent blacklist (ties broken by replica index for determinism).

The router owns the global request-id space: ``submit`` returns a router
id and ``collect`` re-stamps each replica's outputs with it, so callers
never see (or collide on) per-replica local ids.
"""

import dataclasses

from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass
class RouteDecision:
    """Why a request landed where it did (returned by ``explain``,
    recorded for the last ``submit``)."""
    replica: int
    score: float
    affinity_blocks: int
    scores: list          # every replica's score, index-aligned


class ServingRouter:
    def __init__(self, engines, config=None):
        """``engines``: the replica :class:`ServingEngine` instances
        (the caller builds them — replicas may run different tuned
        configs, see ``autotuning.tune.tune_serving``); ``config``: a
        ``DeepSpeedServingRouterConfig``, a ``{"router": {...}}``-style
        dict, or None for defaults."""
        if not engines:
            raise ValueError("ServingRouter needs at least one engine")
        from deepspeed_tpu.runtime.config import \
            DeepSpeedServingRouterConfig
        if config is None or isinstance(config, dict):
            config = DeepSpeedServingRouterConfig(config or {})
        self.engines = list(engines)
        self.config = config
        self._next_id = 0
        # router id -> (replica index, replica-local req id)
        self._placement = {}
        self.last_decision = None
        self.routed_by_replica = [0] * len(self.engines)
        log_dist(f"ServingRouter ready: {len(self.engines)} replica(s) "
                 f"affinity={config.affinity_weight} "
                 f"queue={config.queue_weight} "
                 f"occupancy={config.occupancy_weight} "
                 f"breach={config.breach_penalty}", ranks=[0])

    # ---------------------------------------------------------- placement
    def _affinity(self, engine, prompt) -> int:
        pc = engine.cache.prefix_cache
        return pc.match_blocks(prompt) if pc is not None else 0

    def explain(self, prompt) -> RouteDecision:
        """Score every replica for ``prompt`` (no side effects)."""
        c = self.config
        scores, affinities = [], []
        for eng in self.engines:
            sig = eng.router_signals()
            aff = self._affinity(eng, prompt)
            breach = sig["ttft_slo_breach"] or sig["queue_growth"]
            scores.append(c.affinity_weight * aff
                          - c.queue_weight * (sig["queue_depth"]
                                              + sig["active"])
                          - c.occupancy_weight * sig["kv_occupancy"]
                          - c.breach_penalty * bool(breach))
            affinities.append(aff)
        best = max(range(len(scores)), key=lambda i: (scores[i], -i))
        return RouteDecision(replica=best, score=scores[best],
                             affinity_blocks=affinities[best],
                             scores=scores)

    def submit(self, prompt, **kwargs) -> int:
        """Route one request; returns the ROUTER-global request id."""
        prompt = [int(t) for t in list(prompt)]
        decision = self.explain(prompt)
        self.last_decision = decision
        local = self.engines[decision.replica].submit(prompt, **kwargs)
        rid = self._next_id
        self._next_id += 1
        self._placement[rid] = (decision.replica, local)
        self.routed_by_replica[decision.replica] += 1
        # chronicle the placement so the (federated) timeline explains
        # WHY traffic moved, not just that latency followed; one cheap
        # attribute check when the chronicle is disabled
        from deepspeed_tpu.telemetry.chronicle import get_chronicle
        get_chronicle().emit(
            "serving", "router", request_id=rid,
            replica=decision.replica,
            score=round(decision.score, 6),
            affinity_blocks=decision.affinity_blocks)
        return rid

    # --------------------------------------------------------------- loop
    def step(self) -> bool:
        progress = False
        for eng in self.engines:
            if eng.scheduler.has_work():
                progress |= eng.step()
        return progress

    def collect(self):
        """Drain every replica, re-stamped with router ids (finish order
        within a replica, replicas in index order)."""
        by_local = {(ri, local): rid
                    for rid, (ri, local) in self._placement.items()}
        outs = []
        for ri, eng in enumerate(self.engines):
            for o in eng.collect():
                rid = by_local.get((ri, o.req_id))
                if rid is None:
                    continue          # submitted directly to the engine
                del self._placement[rid]
                outs.append(dataclasses.replace(o, req_id=rid))
        return outs

    def serve_forever(self, max_steps=None):
        """Step every replica until all are drained; returns collected
        outputs. Each replica's own livelock guard still applies."""
        outputs = []
        steps = 0
        while any(e.scheduler.has_work() for e in self.engines):
            self.step()
            outputs.extend(self.collect())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        outputs.extend(self.collect())
        return outputs

    # ---------------------------------------------------------- telemetry
    def stats(self):
        reps = []
        for ri, eng in enumerate(self.engines):
            pc = eng.cache.prefix_cache
            reps.append({
                "routed": self.routed_by_replica[ri],
                "signals": eng.router_signals(),
                "prefix_cache": None if pc is None else pc.stats(),
            })
        return {"replicas": reps, "pending": len(self._placement)}

    def close(self):
        for eng in self.engines:
            eng.close()
