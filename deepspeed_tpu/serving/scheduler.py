"""Continuous-batching scheduler — admit/evict between decode steps.

Orca-style iteration-level scheduling (OSDI '22) over a slot-based static
batch: the compiled decode step always runs ``max_batch`` slots; the
scheduler decides *which request occupies which slot* between steps and
hands the server an active mask. Policy:

* **FCFS admission**: requests are admitted strictly in submit order. A
  head request whose prompt doesn't fit the free block pool blocks the
  tail (no out-of-order admission — the tests pin this).
* **Preemption by eviction**: when a running request needs one more KV
  block and the pool is dry, the LATEST-admitted running request is
  evicted — its blocks return to the pool and it re-queues at the FRONT
  of the waiting line (it still outranks everything submitted after it).
  Eviction is recompute-style (vLLM's recovery mode): the victim's
  generated-so-far tokens join its prompt and its KV is re-prefilled on
  re-admission.
* **Chunked prefill**: one bounded chunk per still-prefilling slot per
  scheduler iteration (earliest-admitted first — empty decode slots are
  pure waste, so prefill runs at batch priority), so a long prompt
  interleaves with decode dispatches at most ``max_batch`` chunks apart
  instead of stalling the batch for its whole forward (prefill covers
  ``prompt[:-1]``; the final prompt token is the request's first decode
  input — its KV is written by the decode step itself).
* **Shared-prefix admission** (when the cache carries a
  ``PrefixCache``): the prompt is walked block-by-block against the
  content-addressed index and every leading hit is mapped READ-ONLY
  into the new table (one refcount apiece) — prefill then starts at
  the first uncached token, so a cache-hit prefix costs one block-table
  copy and zero chunk dispatches. A fully-cached prompt must still
  rewrite its final position (that forward produces the first sampled
  logits), which lands inside the last shared block: the scheduler
  plans a copy-on-write fork (fresh block + one device block copy,
  executed by the server before the request's first dispatch). Cold
  cache-only blocks are reclaimed before ANY preemption fires, so the
  preemption-by-eviction path and its recompute accounting compose
  unchanged.

The scheduler is pure host-side bookkeeping: it never touches device
state. The server (serving/server.py) turns its ``StepPlan`` into the
static tensors the compiled programs consume.
"""

import dataclasses
import enum
import time
from collections import deque
from typing import List, Optional


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    RUNNING = "running"
    FINISHED = "finished"


@dataclasses.dataclass
class Request:
    req_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0
    eos_token_id: Optional[int] = None
    # --- runtime state (scheduler/server owned) ---
    state: RequestState = RequestState.WAITING
    output_tokens: List[int] = dataclasses.field(default_factory=list)
    block_table: List[int] = dataclasses.field(default_factory=list)
    cached_len: int = 0                  # KV positions written
    max_cached_len: int = 0              # high-water mark across evictions:
    # re-prefilled positions below it are RECOMPUTE (their KV existed
    # before a preemption threw it away)
    next_input: Optional[int] = None     # token the next decode step embeds
    # --- shared-prefix state (kv_cache.PrefixCache) ---
    shared_blocks: int = 0      # leading table entries mapped READ-ONLY
    # from the prefix index this admission; every KV write lands at
    # >= shared_blocks * block_size (asserted at admission)
    prefix_hit_blocks: int = 0  # blocks served from the index at the
    # last admission (the ledger's cached_prefill attribution)
    indexed_blocks: int = 0     # leading full blocks already registered
    prefix_digest: Optional[bytes] = None   # chain digest after them
    cow_fork: Optional[tuple] = None        # (src_block, table_index):
    # a pending copy-on-write fork — the server device-copies src into
    # block_table[table_index] and releases the src reference before
    # this request's first dispatch
    slot: Optional[int] = None
    admit_seq: int = -1
    preemptions: int = 0
    finish_reason: Optional[str] = None
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    step_budget: int = 0        # tokens the next decode dispatch may emit
    # --- speculative decoding state (serving/speculative.py) ---
    spec_drafted: int = 0       # draft tokens proposed for this request
    spec_accepted: int = 0      # of those, accepted by the verify pass
    # (rejected drafts roll back as a position edit: cached_len simply
    # does not advance past the accepted prefix)

    @property
    def spec_acceptance_rate(self) -> Optional[float]:
        """Per-request acceptance: accepted/drafted, None before any
        draft was proposed (e.g. speculation off)."""
        if not self.spec_drafted:
            return None
        return self.spec_accepted / self.spec_drafted

    @property
    def full_prompt(self) -> List[int]:
        """Tokens whose KV must exist to continue decoding — the original
        prompt plus everything generated so far (what a preempted request
        re-prefills on re-admission)."""
        return self.prompt + self.output_tokens


@dataclasses.dataclass
class StepPlan:
    """One scheduler iteration: one prefill chunk per still-prefilling
    slot (earliest-admitted first) + the decode slot set + the pending
    copy-on-write forks the server must execute FIRST."""
    prefill: List[Request] = dataclasses.field(default_factory=list)
    decode_slots: List[int] = dataclasses.field(default_factory=list)
    cow_forks: List[Request] = dataclasses.field(default_factory=list)

    @property
    def has_work(self) -> bool:
        return bool(self.prefill) or bool(self.decode_slots)


class ContinuousBatchingScheduler:
    def __init__(self, cache, max_batch: int, max_model_len: int,
                 decode_steps: int = 1, observer=None):
        self.cache = cache                      # PagedKVCache (owns alloc)
        self.allocator = cache.allocator
        self.max_batch = int(max_batch)
        self.max_model_len = int(max_model_len)
        self.decode_steps = int(decode_steps)
        # optional lifecycle observer (the serving observatory): called
        # synchronously on admit / preempt / admission-fail with the
        # request still carrying its pre-transition state
        self.observer = observer
        self.waiting = deque()
        self.slots: List[Optional[Request]] = [None] * self.max_batch
        self._admit_counter = 0
        self.preemptions_total = 0
        self.preemptions_by_reason = {}         # reason -> count
        # requests that can NEVER fit the pool (e.g. a preempted request
        # whose prompt+generated outgrew the usable blocks) — failed at
        # admission instead of livelocking the FCFS head; the server
        # drains these into its finished queue
        self.failed: List[Request] = []

    # ------------------------------------------------------------- state
    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self.slots)

    def has_work(self) -> bool:
        return bool(self.waiting) or self.num_active > 0

    # ------------------------------------------------------------ submit
    def submit(self, req: Request):
        p = len(req.prompt)
        if p < 1:
            raise ValueError("empty prompt")
        if p > self.max_model_len:
            raise ValueError(
                f"prompt length {p} exceeds max_model_len "
                f"{self.max_model_len}")
        if self.cache.blocks_for(p) > self.allocator.num_usable:
            raise ValueError(
                f"prompt needs {self.cache.blocks_for(p)} KV blocks but "
                f"the pool only has {self.allocator.num_usable} usable — "
                f"raise serving.num_blocks")
        req.state = RequestState.WAITING
        req.submit_t = time.perf_counter()
        self.waiting.append(req)

    # ---------------------------------------------------------- schedule
    def schedule(self) -> StepPlan:
        """Admission + capacity growth for one iteration. Called between
        decode steps — never mid-step."""
        self._admit()
        plan = StepPlan()
        # capacity growth FIRST: it may preempt slots (possibly ones in
        # PREFILL state), and the plan must only name requests that still
        # occupy a slot afterwards
        plan.decode_slots = self._ensure_decode_capacity()
        # one chunk per prefilling slot, earliest admission first: empty
        # decode slots are pure waste, so prefill runs at batch priority
        # (each chunk is still bounded, so decode interleaves at most
        # max_batch chunks later)
        plan.prefill = sorted(
            (r for r in self.slots
             if r is not None and r.state is RequestState.PREFILL),
            key=lambda r: r.admit_seq)
        # pending COW forks, collected AFTER capacity growth for the same
        # reason as the prefill plan: a fork whose request a later slot's
        # eviction removed is cleaned up by _preempt, not dispatched
        plan.cow_forks = [r for r in self.slots
                          if r is not None and r.cow_fork is not None]
        return plan

    def _allocate_reclaiming(self, n, owner):
        """All-or-nothing allocate, reclaiming cold prefix-cache blocks
        first when the free list is short. Cheaper than preemption in
        strictly every case: a reclaimed block costs nothing, an evicted
        request costs its whole prefix again as recompute."""
        blocks = self.allocator.allocate(n, owner=owner)
        pc = self.cache.prefix_cache
        if blocks is None and pc is not None:
            if pc.reclaim(n - self.allocator.num_free) > 0:
                blocks = self.allocator.allocate(n, owner=owner)
        return blocks

    def _admit(self):
        pc = self.cache.prefix_cache
        bs = self.cache.block_size
        while self.waiting:
            try:
                free = self.slots.index(None)
            except ValueError:
                return
            req = self.waiting[0]
            full = req.full_prompt
            need = self.cache.blocks_for(len(full))
            if need > self.allocator.num_usable:
                # can NEVER fit (a preempted request whose prompt +
                # generated tokens outgrew the pool): fail it instead of
                # blocking the FCFS head forever
                self.waiting.popleft()
                req.state = RequestState.FINISHED
                req.finish_reason = "capacity"
                req.finish_t = time.perf_counter()
                self.failed.append(req)
                if self.observer is not None:
                    self.observer.on_admission_fail(req)
                continue
            # shared-prefix walk: map every leading full block the index
            # holds read-only into this request's table. A fully-cached
            # prompt still needs position len(full)-1 REWRITTEN (the
            # last token's forward produces the first sampled logits),
            # and that position lives inside the last shared block — the
            # one divergent write, resolved by a copy-on-write fork.
            shared, digests = pc.lookup(full) if pc is not None else ([], [])
            k = len(shared)
            fork = k > 0 and k * bs >= len(full)
            fresh_needed = need - k + (1 if fork else 0)
            if k:
                # take the shared references BEFORE allocating: the
                # allocate path may reclaim cold refcount-1 cache
                # entries, and the blocks just matched are exactly that
                # until this incref pins them
                self.allocator.share(shared, owner=req.req_id)
            blocks = self._allocate_reclaiming(fresh_needed, req.req_id)
            if blocks is None:
                if k:       # roll the mapping back — all-or-nothing
                    self.allocator.free(shared, owner=req.req_id)
                return                      # strict FCFS: head blocks tail
            self.waiting.popleft()
            if pc is not None:
                pc.record_lookup(k, len(full) // bs)
            if fork:
                # need == k here (k*bs >= len(full) and a match can never
                # cover more tokens than the prompt has, so k*bs ==
                # len(full)): the table is the shared chain with its last
                # block replaced by the fresh fork target; the src
                # reference taken above is released by the server once
                # the device copy lands
                req.block_table = shared[:-1] + blocks
                req.cached_len = len(full) - 1
                req.shared_blocks = k - 1
                req.cow_fork = (shared[-1], k - 1)
            else:
                req.block_table = shared + blocks
                req.cached_len = k * bs
                req.shared_blocks = k
                req.cow_fork = None
            assert req.cached_len >= req.shared_blocks * bs, \
                "KV write position inside the read-only shared prefix"
            req.prefix_hit_blocks = k
            req.indexed_blocks = k
            req.prefix_digest = (digests[-1] if k
                                 else (pc.root_digest if pc else None))
            req.slot = free
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            req.next_input = full[-1]
            req.state = (RequestState.PREFILL
                         if len(full) - 1 - req.cached_len > 0
                         else RequestState.RUNNING)
            self.slots[free] = req
            if self.observer is not None:
                self.observer.on_admit(req)

    def _ensure_decode_capacity(self) -> List[int]:
        """Compute each running slot's dispatch budget (tokens the next
        decode dispatch may emit: capped by decode_steps, remaining
        generation and the model-length cap), grow its block table to
        cover the budget's KV writes, and preempt-by-eviction when the
        pool runs dry.

        Two phases: capacity growth may preempt ANY slot — including one
        visited earlier — so the decode list is collected only after
        every slot's growth has settled (a one-pass append could name a
        slot that a later slot's eviction emptied)."""
        for i in range(self.max_batch):
            req = self.slots[i]
            if req is None or req.state is not RequestState.RUNNING:
                continue
            budget = min(self.decode_steps,
                         req.max_new_tokens - len(req.output_tokens),
                         max(1, self.max_model_len - req.cached_len))
            req.step_budget = max(1, budget)
            while self.cache.blocks_for(
                    min(req.cached_len + req.step_budget,
                        self.max_model_len)) > len(req.block_table):
                # reclaim-before-preempt rides inside the allocate: a
                # cold cached block is free capacity, so no preemption
                # ever fires while the prefix index holds reclaimable
                # blocks
                grown = self._allocate_reclaiming(1, req.req_id)
                if grown is not None:
                    req.block_table.extend(grown)
                    continue
                # before evicting anyone, shrink the budget to the
                # capacity this slot already owns — guaranteed forward
                # progress even when the whole pool belongs to it (the
                # self-preempt/re-admit cycle would otherwise loop
                # without ever emitting a token)
                owned = (len(req.block_table) * self.cache.block_size
                         - req.cached_len)
                if owned >= 1:
                    req.step_budget = min(req.step_budget, owned)
                    break
                victim = self._pick_victim()
                self._preempt(victim, reason="capacity_growth")
                if victim is req:
                    break
        return [i for i in range(self.max_batch)
                if self.slots[i] is not None
                and self.slots[i].state is RequestState.RUNNING]

    def _pick_victim(self) -> Request:
        """Latest-admitted occupied slot — the request that has consumed
        the least scheduler priority loses its blocks first."""
        live = [r for r in self.slots if r is not None]
        assert live, "allocator dry with no slot to evict"
        return max(live, key=lambda r: r.admit_seq)

    def _preempt(self, req: Request, reason: str = "capacity_growth"):
        """Evict *req* (recompute-style). ``reason`` labels the
        preemption counters: ``capacity_growth`` is the only policy
        today (a running slot needed one more KV block and the pool was
        dry); ``admission`` is reserved for a future evict-to-admit
        policy — strict FCFS never evicts at admission."""
        # the high-water mark is what re-prefill will RE-compute: every
        # position below it had KV before this eviction threw it away
        req.max_cached_len = max(req.max_cached_len, req.cached_len)
        if self.observer is not None:
            self.observer.on_preempt(req, reason, req.cached_len)
        self._release_blocks(req)
        req.cached_len = 0
        self.slots[req.slot] = None
        req.slot = None
        req.state = RequestState.WAITING
        req.preemptions += 1
        self.preemptions_total += 1
        self.preemptions_by_reason[reason] = \
            self.preemptions_by_reason.get(reason, 0) + 1
        # front of the line: it was admitted before anything still waiting
        self.waiting.appendleft(req)

    def _release_blocks(self, req: Request):
        """Drop every block reference *req* holds — its table AND a
        pending COW fork's source. Frees are refcount decrements: blocks
        a sharer or the prefix index still references stay live, so
        preempting (or finishing) one sharer never perturbs another
        sharer's table — the sharing tests pin exactly that."""
        if req.cow_fork is not None:
            # fork planned but the device copy never ran (preempted or
            # failed in the same schedule that admitted it): release the
            # source reference the admission took
            self.allocator.free([req.cow_fork[0]], owner=req.req_id)
            req.cow_fork = None
        self.allocator.free(req.block_table, owner=req.req_id)
        req.block_table = []
        req.shared_blocks = 0

    # ------------------------------------------------------------ finish
    def finish(self, req: Request, reason: str):
        self._release_blocks(req)
        self.slots[req.slot] = None
        req.slot = None
        req.state = RequestState.FINISHED
        req.finish_reason = reason
        req.finish_t = time.perf_counter()
