"""Streaming paged attention — work scales with LIVE tokens, not capacity.

The runner's ``gather`` impl materialises each slot's block table into a
contiguous ``[B, H, T_max, D]`` view and hands it to the
ops/transformer/decode.py kernel. That composes with the Pallas TPU
kernel, but it reads (and copies) the *allocated* window every step: a
request 40 tokens into a 2048-token capacity still pays 2048 columns of
gather+attention traffic — and decode is KV-bandwidth bound, so that tax
is the whole step.

This module is the PagedAttention-shaped alternative (SOSP '23): a
flash-style online-softmax loop over KV *blocks* with a DYNAMIC trip
count — ``ceil(max_past_len / block_size)`` is a traced scalar, so XLA
lowers the ``fori_loop`` to a while loop whose iterations touch only
blocks that actually hold tokens. One block gather per iteration
(``[B, H, block_size, D]``, consumed immediately — never a full-window
materialisation), one compiled program regardless of how lengths evolve.

Both functions attend over the PAST pool only and fold the current
token/chunk from registers (an extra online-softmax term / an intra-chunk
causal piece merged in). That lets the runner defer every layer's KV
write into ONE stacked scatter per step (kv_cache.write_all_layers) —
XLA scatter dispatch was the dominant per-step cost once attention
stopped reading dead columns. The int8 KV layout dequantises per block
from the per-row scale pools; the current token stays in registers at
full precision (it is quantised only when written, exactly like the
flax decode path, which attends to the quantised value from the NEXT
step on).

Both impls are selectable per engine (``serving.attention_impl``) and
pinned equal by tests/unit/test_serving.py.
"""

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _merge(m1, l1, a1, m2, l2, a2):
    """Combine two online-softmax partials over disjoint key sets."""
    m = jnp.maximum(m1, m2)
    w1 = jnp.exp(m1 - m)
    w2 = jnp.exp(m2 - m)
    return m, l1 * w1 + l2 * w2, a1 * w1[..., None] + a2 * w2[..., None]


def paged_decode_attention(q, k_cur, v_cur, layer, k_pool, v_pool,
                           block_tables, past_lens, *, k_scale_pool=None,
                           v_scale_pool=None, sm_scale=None):
    """One decode token per slot over the paged pools.

    q/k_cur/v_cur: ``[B, H, D]`` (the current token's K/V stay in
    registers — the pool write is deferred); pools: the layer-STACKED
    ``[L, N, H, BS, D]`` arrays indexed as ``pool[layer, ids]`` inside
    the loop (slicing the stacked pool outside the loop would
    materialise a per-layer copy); block_tables: ``[B, MB]`` int32;
    past_lens: ``[B]`` int32 tokens ALREADY in the pool. Returns
    ``[B, H, D]`` fp32.
    """
    B, H, D = q.shape
    BS = k_pool.shape[3]
    if sm_scale is None:
        sm_scale = D ** -0.5
    quantized = k_scale_pool is not None
    qf = q.astype(jnp.float32)
    n_blocks = ((jnp.max(past_lens) + BS - 1) // BS).astype(jnp.int32)

    def body(i, carry):
        m, l, acc = carry
        ids = block_tables[:, i]                       # [B]
        kb = k_pool[layer, ids]                        # [B, H, BS, D]
        vb = v_pool[layer, ids]
        if quantized:
            kb = kb.astype(jnp.float32) \
                * k_scale_pool[layer, ids][..., None]
            vb = vb.astype(jnp.float32) \
                * v_scale_pool[layer, ids][..., None]
        else:
            kb = kb.astype(jnp.float32)
            vb = vb.astype(jnp.float32)
        s = jnp.einsum("bhd,bhsd->bhs", qf, kb) * sm_scale
        col = i * BS + jnp.arange(BS, dtype=jnp.int32)
        s = jnp.where(col[None, None, :] < past_lens[:, None, None],
                      s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bhs,bhsd->bhd", p, vb)
        return m_new, l_new, acc

    m0 = jnp.full((B, H), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H), jnp.float32)
    a0 = jnp.zeros((B, H, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, a0))
    # fold the current token (always self-visible, so l can never be 0)
    s_cur = jnp.einsum("bhd,bhd->bh", qf,
                       k_cur.astype(jnp.float32)) * sm_scale
    m_f = jnp.maximum(m, s_cur)
    alpha = jnp.exp(m - m_f)
    p_cur = jnp.exp(s_cur - m_f)
    l = l * alpha + p_cur
    acc = acc * alpha[..., None] \
        + p_cur[..., None] * v_cur.astype(jnp.float32)
    return acc / l[..., None]


def paged_verify_attention(q, k_chunk, v_chunk, layer, k_pool, v_pool,
                           block_tables, past_lens, *, k_scale_pool=None,
                           v_scale_pool=None, sm_scale=None):
    """Speculative verify: ``C = K+1`` queries PER SLOT over each slot's
    PAST pages plus the candidate chunk itself (registers, causal).

    The batched cross of the two functions above: decode's ``[B, MB]``
    block tables and per-slot ``past_lens``, prefill's multi-position
    chunk with the intra-chunk causal piece merged from registers. One
    program verifies K drafted tokens for every slot in a single target
    forward — the pool writes stay deferred, so a rejected suffix never
    has to be undone on-device.

    q/k_chunk/v_chunk: ``[B, H, C, D]`` (query c sits at absolute
    position ``past_lens[b] + c``); block_tables: ``[B, MB]`` int32;
    past_lens: ``[B]`` int32 tokens ALREADY in the pool. Returns
    ``[B, H, C, D]`` fp32.
    """
    B, H, C, D = q.shape
    BS = k_pool.shape[3]
    if sm_scale is None:
        sm_scale = D ** -0.5
    quantized = k_scale_pool is not None
    qf = q.astype(jnp.float32)
    n_blocks = ((jnp.max(past_lens) + BS - 1) // BS).astype(jnp.int32)

    def body(i, carry):
        m, l, acc = carry
        ids = block_tables[:, i]                       # [B]
        kb = k_pool[layer, ids]                        # [B, H, BS, D]
        vb = v_pool[layer, ids]
        if quantized:
            kb = kb.astype(jnp.float32) \
                * k_scale_pool[layer, ids][..., None]
            vb = vb.astype(jnp.float32) \
                * v_scale_pool[layer, ids][..., None]
        else:
            kb = kb.astype(jnp.float32)
            vb = vb.astype(jnp.float32)
        s = jnp.einsum("bhcd,bhsd->bhcs", qf, kb) * sm_scale
        col = i * BS + jnp.arange(BS, dtype=jnp.int32)
        s = jnp.where(col[None, None, None, :]
                      < past_lens[:, None, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] \
            + jnp.einsum("bhcs,bhsd->bhcd", p, vb)
        return m_new, l_new, acc

    m0 = jnp.full((B, H, C), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, C), jnp.float32)
    a0 = jnp.zeros((B, H, C, D), jnp.float32)
    m_p, l_p, a_p = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, a0))
    # intra-chunk causal piece from registers: candidate e visible to
    # query c iff e <= c; query 0 always sees itself, so l can never be 0
    s_in = jnp.einsum("bhcd,bhed->bhce", qf,
                      k_chunk.astype(jnp.float32)) * sm_scale
    causal = (jnp.arange(C)[:, None] >= jnp.arange(C)[None, :])
    s_in = jnp.where(causal[None, None], s_in, NEG_INF)
    m_in = jnp.max(s_in, axis=-1)
    p_in = jnp.exp(s_in - m_in[..., None])
    l_in = jnp.sum(p_in, axis=-1)
    a_in = jnp.einsum("bhce,bhed->bhcd", p_in,
                      v_chunk.astype(jnp.float32))
    _, l, acc = _merge(m_p, l_p, a_p, m_in, l_in, a_in)
    return acc / l[..., None]


def paged_prefill_attention(q, k_chunk, v_chunk, layer, k_pool, v_pool,
                            bt_row, pos, start, *, k_scale_pool=None,
                            v_scale_pool=None, sm_scale=None):
    """Chunk attention for ONE slot: ``C`` queries at positions ``pos``
    (= start + 0..C-1) over the slot's PAST pages plus the chunk itself
    (registers, causal) — the chunk's pool write is deferred.

    q/k_chunk/v_chunk: ``[H, C, D]``; bt_row: ``[MB]`` int32; start:
    traced scalar, tokens already in the pool. Returns ``[H, C, D]``
    fp32.
    """
    H, C, D = q.shape
    BS = k_pool.shape[3]
    if sm_scale is None:
        sm_scale = D ** -0.5
    quantized = k_scale_pool is not None
    qf = q.astype(jnp.float32)
    n_blocks = ((start + BS - 1) // BS).astype(jnp.int32)

    def body(i, carry):
        m, l, acc = carry
        bid = bt_row[i]
        kb = k_pool[layer, bid]                        # [H, BS, D]
        vb = v_pool[layer, bid]
        if quantized:
            kb = kb.astype(jnp.float32) \
                * k_scale_pool[layer, bid][..., None]
            vb = vb.astype(jnp.float32) \
                * v_scale_pool[layer, bid][..., None]
        else:
            kb = kb.astype(jnp.float32)
            vb = vb.astype(jnp.float32)
        s = jnp.einsum("hcd,hsd->hcs", qf, kb) * sm_scale
        col = i * BS + jnp.arange(BS, dtype=jnp.int32)
        s = jnp.where(col[None, None, :] < start, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("hcs,hsd->hcd", p, vb)
        return m_new, l_new, acc

    m0 = jnp.full((H, C), NEG_INF, jnp.float32)
    l0 = jnp.zeros((H, C), jnp.float32)
    a0 = jnp.zeros((H, C, D), jnp.float32)
    m_p, l_p, a_p = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, a0))
    # intra-chunk causal piece from registers: key e visible to query c
    # iff e <= c (pad-tail queries produce garbage that is discarded)
    s_in = jnp.einsum("hcd,hed->hce", qf,
                      k_chunk.astype(jnp.float32)) * sm_scale
    causal = jnp.arange(C)[None, :, None] >= jnp.arange(C)[None, None, :]
    s_in = jnp.where(causal, s_in, NEG_INF)
    m_in = jnp.max(s_in, axis=-1)
    p_in = jnp.exp(s_in - m_in[..., None])
    l_in = jnp.sum(p_in, axis=-1)
    a_in = jnp.einsum("hce,hed->hcd", p_in, v_chunk.astype(jnp.float32))
    _, l, acc = _merge(m_p, l_p, a_p, m_in, l_in, a_in)
    return acc / l[..., None]
