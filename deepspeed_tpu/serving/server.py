"""Serving engine — the synchronous request-level front-end.

``ServingEngine`` glues the subsystem together on top of an
``InferenceEngine`` (which owns params, dtype/int8-weight handling and
the mesh): a ``PagedKVCache`` block pool, the ``PagedGPT2Runner``'s two
compiled programs, the FCFS continuous-batching scheduler, and chunked
prefill. The API is deliberately synchronous — ``submit()`` enqueues,
``step()`` advances the world by one scheduler iteration (one bounded
prefill chunk per still-prefilling slot + one decode dispatch),
``collect()`` drains finished requests — so a caller (or
``serve_forever``) owns the loop and there is no hidden thread to
reason about.

Observability rides the PR-1 registry (so the existing JSONL/Prometheus
sinks carry serving without new plumbing): per-request TTFT and
inter-token latency histograms, queue-depth / active-slot / KV-occupancy
gauges, token/request/preemption counters — and both compiled entry
points are compile-watch wrapped, which is how the tests pin "exactly
one decode program across a heterogeneous trace".
"""

import dataclasses
import os
import time
from typing import List, Optional

import numpy as np

from deepspeed_tpu.serving.kv_cache import PagedKVCache
from deepspeed_tpu.serving.prefill import ChunkedPrefill
from deepspeed_tpu.serving.runner import PagedGPT2Runner
from deepspeed_tpu.serving.sampling import make_rng_lane
from deepspeed_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                             Request, RequestState)
from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.telemetry import metrics as _metrics
from deepspeed_tpu.telemetry.compile_watch import CompileWatch
from deepspeed_tpu.telemetry.serving_observatory import (
    SERVING_HEALTH_SCHEMA, ServingObservatory)
from deepspeed_tpu.telemetry.tracer import trace_span
from deepspeed_tpu.utils.logging import log_dist

# latency histograms: serving cares about the 0.1 ms .. 10 s band
_LAT_BUCKETS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                5000, 10000)


class ServingLivelockError(RuntimeError):
    """serve_forever made no progress for its hard limit of iterations.

    Carries the full ``serving_report()`` dict in ``.report`` — the
    scheduler/slot/KV state dump and (observability on) the slot-step
    ledger, windows and timelines — so the forensics that motivated the
    guard are captured at the point of death instead of lost with the
    process."""

    def __init__(self, message, report=None):
        super().__init__(message)
        self.report = report


class ServingAdmissionPausedError(RuntimeError):
    """``submit()`` refused a request because the guardian paused
    admission (overload degradation). Carries the SLO rule that
    triggered the pause in ``.rule`` — the structured reason a client
    can act on (back off, shed, retry later)."""

    def __init__(self, rule):
        super().__init__(
            f"admission paused by the guardian (rule {rule!r}): the "
            f"server is shedding load; retry after recovery")
        self.rule = rule


@dataclasses.dataclass
class RequestOutput:
    req_id: int
    prompt: List[int]
    tokens: List[int]
    finish_reason: str
    ttft_s: Optional[float]
    latency_s: float
    preemptions: int


class ServingEngine:
    def __init__(self, engine, config=None, registry=None, use_flash=None,
                 guardian=None, obs_server=None, slo=None,
                 draft_params=None, draft_scales=None):
        """``engine``: an ``InferenceEngine`` wrapping a GPT-2-family
        model; ``config``: ``DeepSpeedServingConfig``, a ds-config dict
        (with or without the outer ``{"serving": ...}``), or ``None`` for
        defaults; ``guardian``: a :class:`runtime.guardian.Guardian` to
        wire the overload-degradation policy into (falls back to the
        wrapped engine's own, when it has one — training and serving
        actions then share one journal). ``obs_server``/``slo``: the
        mission-control surfaces (telemetry/obs_server.py, telemetry/
        slo.py) — like the guardian they fall back to the wrapped
        engine's own, so an engine armed with ``telemetry.server`` /
        ``telemetry.slo`` config exposes the serving report as a scrape
        route and burns the serving latency objectives automatically.
        ``draft_params``/``draft_scales``: an explicitly configured
        small draft model for ``serving.speculative`` (params pytree,
        pool- and vocab-compatible with the target — see
        serving/speculative.py); ``None`` selects the truncated-layer
        self-draft."""
        from deepspeed_tpu.runtime.config import DeepSpeedServingConfig
        if config is None:
            config = DeepSpeedServingConfig({})
        elif isinstance(config, dict):
            pd = config if "serving" in config else {"serving": config}
            config = DeepSpeedServingConfig(pd)
        self.config = config
        self.engine = engine
        assert engine.mp_world_size == 1, (
            "serving currently drives single-chip decode (mp=1); "
            "tensor-parallel serving is a roadmap item")
        model = engine.module
        cfg = model.config
        n_pos = int(getattr(cfg, "n_positions"))
        self.max_model_len = (min(int(config.max_model_len), n_pos)
                              if config.max_model_len else n_pos)
        self.max_batch = int(config.max_batch)
        head_dim = cfg.n_embd // cfg.n_head
        int8_kv = getattr(cfg, "kv_cache_dtype", "auto") == "int8"
        self.max_blocks_per_seq = -(-self.max_model_len
                                    // int(config.block_size))
        num_blocks = int(config.num_blocks) or (
            1 + self.max_batch * self.max_blocks_per_seq)
        self.cache = PagedKVCache(
            n_layer=cfg.n_layer, n_head=cfg.n_head, head_dim=head_dim,
            block_size=config.block_size, num_blocks=num_blocks,
            dtype=engine.dtype, int8_kv=int8_kv)
        self.runner = PagedGPT2Runner(
            model, self.cache, use_flash=use_flash,
            attention_impl=config.attention_impl,
            decode_steps=config.decode_steps)
        # speculative decoding (serving/speculative.py): replaces the
        # decode dispatch with a draft + verify program pair. The
        # scheduler's per-dispatch token budget (and the slot-step
        # ledger's K basis) becomes k+1 — the verify width — so block
        # growth covers every candidate position and the ledger's
        # sums-exact invariant holds on both engines of an A/B.
        spec_cfg = getattr(config, "speculative", None)
        self.speculative = None
        self._spec_disabled_rule = None       # None = speculation live
        if spec_cfg is not None and spec_cfg.enabled:
            from deepspeed_tpu.serving.speculative import (
                SpeculativeDecoder, default_draft_layers,
                validate_draft_params)
            draft_layers = spec_cfg.draft_layers or default_draft_layers(
                cfg.n_layer)
            if draft_params is not None:
                validate_draft_params(draft_params, engine.params,
                                      draft_layers)
            self.speculative = SpeculativeDecoder(
                self.runner, k=spec_cfg.k, draft_layers=draft_layers,
                acceptance=spec_cfg.acceptance,
                typical_threshold=spec_cfg.typical_threshold,
                draft_params=draft_params, draft_scales=draft_scales)
        dispatch_tokens = (self.speculative.k + 1
                           if self.speculative is not None
                           else int(config.decode_steps))
        self.scheduler = ContinuousBatchingScheduler(
            self.cache, max_batch=self.max_batch,
            max_model_len=self.max_model_len,
            decode_steps=dispatch_tokens)
        self.registry = registry if registry is not None \
            else _metrics.get_registry()
        # serving observatory (telemetry/serving_observatory.py): pure
        # host bookkeeping — timelines, the slot-step ledger, SLO rules.
        # None when disabled, so every call site is one attribute check.
        obs_cfg = getattr(config, "observability", None)
        self.observatory = None
        if obs_cfg is not None and obs_cfg.enabled:
            self.observatory = ServingObservatory.from_config(
                obs_cfg, max_batch=self.max_batch,
                decode_steps=dispatch_tokens,
                registry=self.registry,
                engine_state_fn=self._engine_state,
                spec_acceptance_floor=(
                    spec_cfg.acceptance_floor
                    if self.speculative is not None else None))
            self.scheduler.observer = self.observatory
        # guardian overload degradation (runtime/guardian.py): the SLO
        # monitor's anomalies feed the guardian, whose serving policy
        # pauses/resumes admission through the callbacks below
        self.guardian = guardian if guardian is not None \
            else getattr(engine, "_guardian", None)
        self._serving_steps = 0
        self._admission_pause_rule = None     # None = admission open
        if self.guardian is not None and self.guardian.enabled \
                and self.guardian.serving_degrade:
            self.guardian.pause_fn = self._pause_admission
            self.guardian.resume_fn = self._resume_admission
            self.guardian.spec_disable_fn = self._disable_speculation
            if self.observatory is not None:
                self.observatory.on_anomaly = self.guardian.hook("serving")
        # mission-control plane (telemetry/obs_server.py + slo.py),
        # shared with the wrapped engine: the serving report becomes one
        # more scrape route, and the serving latency objectives (ttft /
        # e2e percentile targets from the registry histograms) join the
        # burn monitor the training-goodput objective already rides. A
        # page-tier burn (slo_burn_page) lands on the guardian's
        # admission-pause rule list — the SLO monitor closes the loop
        # back to the pause/resume callbacks wired above.
        self._slo = slo if slo is not None else getattr(
            engine, "_slo", None)
        if self._slo is not None and getattr(self._slo, "enabled", False):
            for obj in getattr(self._slo, "serving_defaults", ()):
                self._slo.add_objective(obj)
        self._obs_server = obs_server if obs_server is not None \
            else getattr(engine, "_obs_server", None)
        if self._obs_server is not None:
            self._obs_server.register("serving", self.serving_report)
        # shared-prefix KV reuse (serving.prefix_cache block): the
        # scheduler reads cache.prefix_cache at admission; the server
        # executes the planned COW forks and registers full blocks as
        # prefill/decode completes them
        pc_cfg = getattr(config, "prefix_cache", None)
        if pc_cfg is not None and pc_cfg.enabled:
            self.cache.attach_prefix_cache(
                capacity_blocks=pc_cfg.capacity_blocks,
                attention_impl=config.attention_impl)
        # HBM residency observatory (telemetry/memory_observatory.py):
        # shared with the train engine's manager — the serving tick adds
        # THIS server's paged-KV pool to the inventory, so the
        # kv_fragmentation rule reads the allocator's own numbers (the
        # same ones serving_report and the gauges book). None when
        # telemetry.memory is off: one attribute check per step.
        self._memory = getattr(engine, "_memory", None)
        _spp = getattr(engine, "steps_per_print", None)
        self._memory_cadence = (getattr(engine, "_memory_cadence", 0)
                                or (_spp() if callable(_spp) else 0) or 10)
        self._memory_steps = 0
        self._watch = CompileWatch(registry=self.registry)
        self._decode_fn = self._watch.wrap(self.runner.decode_step,
                                           name="serving_decode_step")
        self._prefill_fn = self._watch.wrap(self.runner.prefill_chunk,
                                            name="serving_prefill_chunk")
        # the COW fork's device copy is its OWN compiled program (one
        # signature for the serving lifetime — src/dst are traced
        # scalars), never a third decode/prefill signature
        self._copy_fn = self._watch.wrap(self.runner.copy_block,
                                         name="serving_block_copy")
        # speculative programs: separately named so the acceptance pin
        # "exactly {1 draft, 1 verify}, 0 retraces" reads per-program
        # signature counts, the same discipline as decode/prefill
        self._draft_fn = self._verify_fn = None
        if self.speculative is not None:
            self._draft_fn = self._watch.wrap(
                self.speculative.draft_step, name="serving_draft_step")
            self._verify_fn = self._watch.wrap(
                self.speculative.verify_step, name="serving_verify_step")
        self.prefill = ChunkedPrefill(self._prefill_fn,
                                      chunk_size=config.prefill_chunk)
        from jax.sharding import NamedSharding, PartitionSpec
        self.pools = self.cache.init_pools(
            NamedSharding(engine.mesh, PartitionSpec()))
        self._next_id = 0
        self._finished = []
        self._lanes = {}              # req_id -> uint32[2] rng lane
        self.registry.gauge(
            "serving_kv_pool_bytes",
            "allocated paged-KV pool size").set(self.cache.pool_bytes())
        log_dist(
            f"ServingEngine ready: max_batch={self.max_batch} "
            f"block_size={self.cache.block_size} "
            f"blocks={num_blocks} (usable "
            f"{self.cache.allocator.num_usable}) "
            f"max_model_len={self.max_model_len} "
            f"prefill_chunk={self.prefill.chunk_size} "
            f"kv={'int8' if int8_kv else 'native'}"
            + (f" speculative=k{self.speculative.k}/"
               f"L{self.speculative.draft_layers}"
               f"{'(draft model)' if draft_params is not None else ''}"
               if self.speculative is not None else ""), ranks=[0])

    # ------------------------------------------------------------ submit
    def submit(self, prompt, max_new_tokens=16, temperature=0.0,
               top_p=1.0, seed=0, eos_token_id=None) -> int:
        """Enqueue one request; returns its id. ``temperature<=0`` is
        greedy; otherwise temperature+top-p sampling on the request's own
        seeded RNG lane. Raises :class:`ServingAdmissionPausedError`
        while the guardian has admission paused — failing fast beats
        joining a queue that cannot drain."""
        if self._admission_pause_rule is not None:
            self.registry.counter(
                "serving_requests_rejected_total",
                "submits refused while admission was paused",
                labels={"reason": "admission_paused"}).inc()
            self._chronicle_serving("submit_refused", severity="watch",
                                    rule=self._admission_pause_rule)
            raise ServingAdmissionPausedError(self._admission_pause_rule)
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        vs = self.engine.module.config.vocab_size
        if prompt and (min(prompt) < 0 or max(prompt) >= vs):
            raise ValueError(f"prompt token out of range [0, {vs})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0.0 < top_p <= 1.0:
            # top_p=0 would mask EVERY token (the nucleus keep-mask is
            # exclusive-cumsum < p) and sample token 0 forever; "greedy"
            # is temperature<=0, not top_p=0
            raise ValueError(
                f"top_p must be in (0, 1], got {top_p} (use "
                f"temperature=0 for greedy)")
        req = Request(req_id=self._next_id, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_p=float(top_p),
                      seed=int(seed), eos_token_id=eos_token_id)
        self._next_id += 1
        self.scheduler.submit(req)
        self._lanes[req.req_id] = make_rng_lane(seed)
        if self.observatory is not None:
            self.observatory.record_submit(req)
        self.registry.counter("serving_requests_submitted_total",
                              "requests accepted by submit()").inc()
        self._publish_gauges()
        return req.req_id

    # -------------------------------------------------------------- step
    def step(self) -> bool:
        """One scheduler iteration: admission, one prefill chunk per
        still-prefilling slot, one decode dispatch. Returns True when
        any work was done."""
        with trace_span("serving_step"):
            plan = self.scheduler.schedule()
            progress = self._drain_failed()
            # acts: slot -> what it did this step (("prefill"|"recompute",
            # n_valid) or ("decode", delivered)) — the slot-step ledger's
            # input; collected DURING the step because finished requests
            # vacate their slots before the step ends
            acts = {}
            # COW forks first: a forked request may decode THIS step, and
            # its table already names the fork target — the copy must
            # land before any dispatch reads or writes it
            for req in plan.cow_forks:
                progress |= self._run_cow_fork(req)
            for req in plan.prefill:
                progress |= self._run_prefill(req, acts)
            if plan.decode_slots:
                self._run_decode(plan.decode_slots, acts)
                progress = True
            self._publish_gauges()
            if self.observatory is not None:
                occupied = {i for i, r in enumerate(self.scheduler.slots)
                            if r is not None}
                self.observatory.end_step(
                    acts, occupied,
                    queue_depth=self.scheduler.num_waiting,
                    active=self.scheduler.num_active,
                    kv_occupancy=self.cache.allocator.occupancy(),
                    kv_fragmentation=self._kv_fragmentation(),
                    progress=progress)
            if self.guardian is not None or self._slo is not None:
                # serving's own step clock (NOT training steps): the
                # pause policy fires here, and recovery is measured in
                # quiet serving steps
                self._serving_steps += 1
                if self._slo is not None:
                    # burn-rate eval BEFORE the guardian tick so a page
                    # fired this step pauses admission this step
                    self._slo.tick(step=self._serving_steps)
                if self.guardian is not None:
                    self.guardian.serving_tick(self._serving_steps)
            self._memory_tick()
        return progress

    def _pause_admission(self, rule):
        """Guardian overload action: refuse new submits (fail fast with
        the rule as the structured reason) until recovery. Already-queued
        requests keep draining — the point is to stop the queue growing,
        not to drop accepted work."""
        self._admission_pause_rule = str(rule)
        self.registry.gauge(
            "serving_admission_paused",
            "1 while the guardian has admission paused").set(1)
        # rule rides the event: the correlator's join key back to the
        # SLO anomaly that triggered the pause
        self._chronicle_serving("admission_pause", severity="warning",
                                rule=str(rule))
        log_dist(f"serving: admission PAUSED (rule {rule}); new submits "
                 f"fail fast until recovery", ranks=[0])

    def _resume_admission(self):
        """Guardian recovery action: the overload rules stayed quiet for
        ``resume_clear_steps`` serving steps."""
        self._chronicle_serving("admission_resume", severity="info",
                                rule=self._admission_pause_rule)
        self._admission_pause_rule = None
        self.registry.gauge(
            "serving_admission_paused",
            "1 while the guardian has admission paused").set(0)
        log_dist("serving: admission RESUMED", ranks=[0])

    def _disable_speculation(self, rule):
        """Guardian degradation action (``speculation_waste``): windowed
        acceptance collapsed below the configured floor, so every draft
        dispatch is mostly rejected compute — fall back to the plain
        decode program. One-way for the serving lifetime: acceptance is
        a property of the traffic/draft pairing, and flapping between
        program sets would retrace."""
        if self.speculative is None or self._spec_disabled_rule is not None:
            return
        self._spec_disabled_rule = str(rule)
        self.registry.gauge(
            "serving_speculation_disabled",
            "1 after the guardian disabled speculative decoding").set(1)
        self._chronicle_serving("speculation_disable", severity="warning",
                                rule=str(rule))
        log_dist(f"serving: speculation DISABLED (rule {rule}); decode "
                 f"falls back to the plain program", ranks=[0])

    def _fail_all_pending(self, reason):
        """Fail every waiting AND slotted request with *reason* —
        structured last rites instead of a silent livelock death. Slotted
        requests release their KV blocks through the normal finish path,
        so the pool is clean for a post-mortem restart."""
        count = 0
        waiting, self.scheduler.waiting = \
            list(self.scheduler.waiting), type(self.scheduler.waiting)()
        for req in waiting:
            req.state = RequestState.FINISHED
            req.finish_reason = reason
            req.finish_t = time.perf_counter()
            self._finished.append(req)
            count += 1
        for slot, req in enumerate(self.scheduler.slots):
            if req is None:
                continue
            self.scheduler.finish(req, reason)
            self._finished.append(req)
            if self.observatory is not None:
                self.observatory.record_finish(req, reason, slot)
            count += 1
        if count:
            self.registry.counter(
                "serving_requests_finished_total",
                "requests completed", labels={"reason": reason}).inc(count)
        return count

    def _drain_failed(self) -> bool:
        """Requests the scheduler failed at admission (prompt + generated
        tokens outgrew the pool) finish with reason 'capacity'."""
        failed = self.scheduler.failed
        if not failed:
            return False
        self.scheduler.failed = []
        for req in failed:
            self._finished.append(req)
            self.registry.counter(
                "serving_requests_finished_total",
                "requests completed", labels={"reason": "capacity"}).inc()
        return True

    def _run_cow_fork(self, req) -> bool:
        """Execute one planned copy-on-write fork: device-copy the shared
        source block into the request's private fork target, then release
        the source reference the admission pinned. One compiled program,
        one block of traffic — the whole cost of diverging from a shared
        prefix."""
        src, idx = req.cow_fork
        with trace_span("serving_cow_fork", req=req.req_id):
            with self.engine.mesh:
                self.pools = self._copy_fn(
                    self.pools, np.int32(src),
                    np.int32(req.block_table[idx]))
        self.cache.allocator.free([src], owner=req.req_id)
        req.cow_fork = None
        pc = self.cache.prefix_cache
        if pc is not None:
            pc.cow_forks += 1
        self.registry.counter(
            "serving_prefix_cow_forks_total",
            "copy-on-write block forks (first divergent write to a "
            "shared block)").inc()
        return True

    def _index_blocks(self, req):
        """Register every newly-FULL block of *req* in the prefix index
        (chain digest extended block by block). Called after prefill
        chunks and decode deliveries — generated tokens index too, so a
        follow-up turn carrying this request's output as context hits,
        and a preempted request re-admits onto its own still-cached
        blocks instead of recomputing them."""
        pc = self.cache.prefix_cache
        if pc is None:
            return
        bs = self.cache.block_size
        n_full = min(req.cached_len // bs, len(req.block_table))
        if req.indexed_blocks >= n_full:
            return
        full = req.full_prompt
        while req.indexed_blocks < n_full:
            b = req.indexed_blocks
            req.prefix_digest = pc.insert(
                req.prefix_digest, full[b * bs:(b + 1) * bs], b * bs,
                req.block_table[b])
            req.indexed_blocks += 1

    def _run_prefill(self, req, acts=None) -> bool:
        slot, start = req.slot, req.cached_len
        t0 = time.perf_counter_ns()
        with trace_span("serving_prefill", req=req.req_id):
            with self.engine.mesh:
                self.pools, n_valid, n_recompute, done = self.prefill.run(
                    self.engine.params, self.engine.quant_scales,
                    self.pools, req, self.max_blocks_per_seq)
        t1 = time.perf_counter_ns()
        self.registry.counter("serving_prefill_chunks_total",
                              "prefill chunks executed").inc()
        self.registry.counter("serving_prefill_tokens_total",
                              "prompt tokens cached by prefill").inc(n_valid)
        if n_recompute:
            # preemption COST, not just count: every token here is KV the
            # pool already computed once and an eviction threw away
            self.registry.counter(
                "serving_recompute_tokens_total",
                "tokens re-prefilled because a preemption evicted their "
                "KV").inc(n_recompute)
        if acts is not None:
            # cached_prefill: this chunk exists because the cache DIDN'T
            # cover the whole prompt — the tail of a prefix-hit
            # admission. Still useful work (recompute outranks it: a
            # re-prefilled position is waste whatever got it admitted)
            acts[slot] = ("recompute" if n_recompute
                          else ("cached_prefill" if req.prefix_hit_blocks
                                else "prefill"), n_valid)
        self._index_blocks(req)
        if self.observatory is not None:
            self.observatory.record_prefill(req, slot, start, n_valid,
                                            n_recompute, t0, t1, done)
        if done:
            req.state = RequestState.RUNNING
        return True

    def _run_decode(self, decode_slots, acts=None):
        B = self.max_batch
        MB = self.max_blocks_per_seq
        slots = self.scheduler.slots
        bt = self.cache.table_array(
            [r.block_table if r is not None else None for r in slots], MB,
            n_rows=B)
        pos = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        tok = np.zeros((B,), np.int32)
        temp = np.zeros((B,), np.float32)
        top_p = np.ones((B,), np.float32)
        lanes = np.zeros((B, 2), np.uint32)
        budget = np.zeros((B,), np.int32)
        for i in decode_slots:
            r = slots[i]
            pos[i] = r.cached_len
            active[i] = True
            tok[i] = r.next_input
            temp[i] = r.temperature
            top_p[i] = r.top_p
            lanes[i] = self._lanes[r.req_id]
            budget[i] = r.step_budget
        spec = (self.speculative
                if self._spec_disabled_rule is None else None)
        t0 = time.perf_counter_ns()
        with trace_span("serving_decode", batch=len(decode_slots)):
            with self.engine.mesh:
                if spec is not None:
                    # draft -> verify, device-to-device: the drafted
                    # tokens feed the verify program WITHOUT a host
                    # round-trip, so the pair keeps decode's one-sync-
                    # per-dispatch discipline
                    dparams = (spec.draft_params
                               if spec.draft_params is not None
                               else self.engine.params)
                    dscales = (spec.draft_scales
                               if spec.draft_params is not None
                               else self.engine.quant_scales)
                    self.pools, drafted = self._draft_fn(
                        dparams, dscales, self.pools, bt, pos, active,
                        tok, budget)
                    self.pools, accepted, toks = self._verify_fn(
                        self.engine.params, self.engine.quant_scales,
                        self.pools, bt, pos, active, drafted, tok, temp,
                        top_p, lanes, budget)
                else:
                    self.pools, toks = self._decode_fn(
                        self.engine.params, self.engine.quant_scales,
                        self.pools, bt, pos, active, tok, temp, top_p,
                        lanes, budget)
            if spec is not None:
                accepted = np.asarray(accepted)    # [B]
            toks = np.asarray(toks)        # [K, B]; the one host sync
        t1 = time.perf_counter_ns()
        now = time.perf_counter()
        self.registry.counter("serving_decode_steps_total",
                              "compiled decode dispatches executed").inc()
        if self.observatory is not None:
            # before delivery, so each timeline's decode_begin precedes
            # its first_token
            self.observatory.record_decode(
                {i: (slots[i], int(budget[i])) for i in decode_slots},
                t0, t1)
        if spec is None:
            for i in decode_slots:
                delivered = self._deliver(slots[i],
                                          toks[:budget[i], i].tolist(),
                                          now)
                if acts is not None:
                    acts[i] = ("decode", delivered)
            return
        # speculative delivery: per slot, min(accepted+1, budget) tokens
        # are real (accepted drafts + the target's bonus token); the
        # rest ROLL BACK by simply not advancing cached_len — the stale
        # pool bytes past the accepted point are masked by past_lens and
        # overwritten by the next dispatch. drafted_rejected books the
        # rejection cost into the slot-step ledger.
        drafted_t = accepted_t = rejected_t = 0
        for i in decode_slots:
            r = slots[i]
            b = int(budget[i])
            cap = min(int(accepted[i]) + 1, b)
            delivered = self._deliver(r, toks[:cap, i].tolist(), now)
            considered = min(spec.k, max(b - 1, 0))
            rejected = considered - (cap - 1)
            r.spec_drafted += considered
            r.spec_accepted += cap - 1
            drafted_t += considered
            accepted_t += cap - 1
            rejected_t += rejected
            if acts is not None:
                acts[i] = ("decode", delivered, rejected)
        if drafted_t:
            self.registry.counter(
                "serving_spec_drafted_total",
                "draft tokens proposed to the verify program").inc(
                    drafted_t)
            self.registry.counter(
                "serving_spec_accepted_total",
                "draft tokens the target accepted").inc(accepted_t)
            if rejected_t:
                self.registry.counter(
                    "serving_spec_rejected_total",
                    "draft tokens the target rejected (rolled back as "
                    "a position edit)").inc(rejected_t)
            drafted_c = self.registry.counter(
                "serving_spec_drafted_total",
                "draft tokens proposed to the verify program").value
            accepted_c = self.registry.counter(
                "serving_spec_accepted_total",
                "draft tokens the target accepted").value
            self.registry.gauge(
                "serving_spec_acceptance_rate",
                "cumulative accepted/drafted ratio of speculative "
                "decoding").set(
                    accepted_c / drafted_c if drafted_c else 0.0)

    def _deliver(self, req, tokens, now):
        """Hand a dispatch's tokens to the request (one token in
        single-step mode, up to ``decode_steps`` otherwise; anything the
        request samples past eos/max_tokens is discarded). Returns the
        KEPT token count — the slot-step ledger's ``decode_useful``."""
        slot = req.slot
        prev = req.last_token_t if req.first_token_t is not None else None
        delivered = 0
        reason = None
        for token in tokens:
            delivered += 1
            req.output_tokens.append(token)
            req.cached_len += 1
            req.next_input = token
            if req.eos_token_id is not None and token == req.eos_token_id:
                reason = "eos"
            elif len(req.output_tokens) >= req.max_new_tokens:
                reason = "max_tokens"
            elif req.cached_len >= self.max_model_len:
                reason = "model_len"
            if reason is not None:
                break
        if not delivered:
            return 0
        # register newly-full blocks BEFORE any finish releases the
        # table: a finished request's prefix stays warm in the index
        self._index_blocks(req)
        req.last_token_t = now
        if req.first_token_t is None:
            req.first_token_t = now
            ttft_ms = (now - req.submit_t) * 1e3
            self.registry.histogram(
                "serving_ttft_ms", "submit -> first generated token",
                buckets=_LAT_BUCKETS).observe(ttft_ms)
            if self.observatory is not None:
                self.observatory.record_first_token(req, ttft_ms)
            extra = 0      # same-dispatch tokens are part of the TTFT
        else:
            extra = delivered
        if extra > 0:
            # multi-step dispatches deliver K tokens at once; record the
            # amortised per-token interval so the histogram stays
            # comparable across decode_steps settings
            per_tok = (now - prev) / extra * 1e3
            h = self.registry.histogram(
                "serving_token_latency_ms",
                "inter-token latency per request (dispatch-amortised)",
                buckets=_LAT_BUCKETS)
            for _ in range(extra):
                h.observe(per_tok)
        self.registry.counter(
            "serving_tokens_generated_total",
            "tokens sampled across all requests").inc(delivered)
        if reason is not None:
            self.scheduler.finish(req, reason)
            self._finished.append(req)
            if self.observatory is not None:
                self.observatory.record_finish(req, reason, slot)
            self.registry.counter(
                "serving_requests_finished_total",
                "requests completed", labels={"reason": reason}).inc()
            self.registry.histogram(
                "serving_e2e_latency_ms", "submit -> finish",
                buckets=_LAT_BUCKETS).observe(
                    (req.finish_t - req.submit_t) * 1e3)
        return delivered

    def _publish_gauges(self):
        self.registry.gauge("serving_queue_depth",
                            "requests waiting for admission").set(
                                self.scheduler.num_waiting)
        self.registry.gauge("serving_active_requests",
                            "requests occupying batch slots").set(
                                self.scheduler.num_active)
        self.registry.gauge("serving_kv_occupancy",
                            "fraction of usable KV blocks allocated").set(
                                self.cache.allocator.occupancy())
        self.registry.gauge("serving_kv_free_blocks",
                            "usable KV blocks currently free").set(
                                self.cache.allocator.num_free)
        if self.observatory is not None:
            self.registry.gauge(
                "serving_kv_fragmentation",
                "fraction of allocated KV positions no token has been "
                "written to (block-granularity over-allocation)").set(
                    self._kv_fragmentation())
        pc = self.cache.prefix_cache
        if pc is not None:
            for name, help_, total in (
                    ("serving_prefix_cache_hits_total",
                     "full prompt blocks served read-only from the "
                     "prefix index at admission", pc.hits),
                    ("serving_prefix_cache_misses_total",
                     "full prompt blocks the prefix index did not hold "
                     "at admission", pc.misses)):
                c = self.registry.counter(name, help_)
                delta = total - c.value
                if delta > 0:
                    c.inc(delta)
            self.registry.gauge(
                "serving_prefix_blocks_shared",
                "resident prefix-index blocks currently mapped by at "
                "least one live request").set(pc.shared_blocks())
        for reason, total in self.scheduler.preemptions_by_reason.items():
            # labeled by WHY the eviction happened (capacity_growth: a
            # running slot needed a block and the pool was dry; admission
            # is reserved for a future evict-to-admit policy), so the
            # sinks carry preemption cause — recompute cost rides
            # serving_recompute_tokens_total
            pre = self.registry.counter(
                "serving_preemptions_total",
                "evictions under block pressure, by reason",
                labels={"reason": reason})
            delta = total - pre.value
            if delta > 0:
                pre.inc(delta)
                self._chronicle_serving("preemption", severity="watch",
                                        reason=reason, count=delta)

    # ----------------------------------------------------------- collect
    def collect(self) -> List[RequestOutput]:
        """Drain finished requests (in finish order)."""
        out = []
        for req in self._finished:
            self._lanes.pop(req.req_id, None)
            out.append(RequestOutput(
                req_id=req.req_id, prompt=list(req.prompt),
                tokens=list(req.output_tokens),
                finish_reason=req.finish_reason,
                ttft_s=(None if req.first_token_t is None
                        else req.first_token_t - req.submit_t),
                latency_s=req.finish_t - req.submit_t,
                preemptions=req.preemptions))
        self._finished = []
        return out

    # -------------------------------------------------------------- loop
    def serve_forever(self, request_source=None, max_steps=None):
        """Drive the loop until drained: optionally pull submit-kwargs
        dicts from ``request_source`` (an iterable) to keep the queue
        primed, step until no work remains, return collected outputs."""
        source = iter(request_source) if request_source is not None else None
        outputs = []
        steps = 0
        idle = 0
        while True:
            while source is not None and \
                    self._admission_pause_rule is None and \
                    self.scheduler.num_waiting < 2 * self.max_batch:
                try:
                    self.submit(**next(source))
                except StopIteration:
                    source = None
                    break
            if not self.scheduler.has_work() and source is None:
                break
            idle = idle + 1 if not self.step() else 0
            if idle > 1000:
                # the scheduler guarantees forward progress (budget
                # shrink-to-owned-capacity + admission-infeasibility
                # failure); a long idle spin means that invariant broke.
                # Last rites BEFORE raising: every pending request fails
                # with a structured reason (a client sees "livelock", not
                # a hang), and the forensics snapshot is forced to disk —
                # then the report also rides the exception.
                n = self._fail_all_pending("livelock")
                self._chronicle_serving(
                    "livelock", severity="critical", failed=n,
                    detail=f"no progress for 1000 iterations; failed {n} "
                           f"pending request(s)")
                report = self.serving_report(write=True)
                raise ServingLivelockError(
                    "serving made no progress for 1000 iterations — "
                    f"failed {n} pending request(s) with reason "
                    f"'livelock'; "
                    f"kv_free={self.cache.allocator.num_free}/"
                    f"{self.cache.allocator.num_usable} blocks "
                    "(scheduler/slot/KV state dump attached as "
                    ".report)", report=report)
            outputs.extend(self.collect())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return outputs

    # ------------------------------------------- HBM residency observatory
    def _memory_tick(self, force=False):
        """Serving-side residency window at the memory cadence: the
        train-engine inventory plus this server's paged-KV pool, so the
        observatory attributes the pool as ``kv_pool`` and its
        ``kv_fragmentation`` rule judges the allocator's own numbers —
        the same ones ``serving_report()`` and the gauges book. A host
        RPC into the runtime's allocator bookkeeping only; never a
        device sync, never a new decode/prefill signature."""
        mon = self._memory
        if mon is None:
            return None
        self._memory_steps += 1
        if not force and self._memory_steps % self._memory_cadence != 0:
            return None
        self.engine._memory_arm(mon)
        try:
            from deepspeed_tpu.telemetry import memory_observatory as _mo
            from deepspeed_tpu.telemetry import pprof as _pprof
            sample = _mo.profile_sample(
                _pprof.fetch_device_memory_profile())
        except Exception as e:
            if not self.engine._memory_warned_fetch:
                self.engine._memory_warned_fetch = True
                log_dist(
                    f"[memory] device memory profile unavailable on this "
                    f"backend: {e} — serving residency windows disabled",
                    ranks=[0])
            return None
        inv = self.engine._memory_build_inventory()
        totals = dict(inv["totals"])
        totals["kv_pool"] = self.cache.pool_bytes()
        alloc = self.cache.allocator
        sample["step"] = self._memory_steps
        sample["inventory"] = totals
        sample["param_buckets"] = inv["param_buckets"]
        sample["opt_buckets"] = inv["opt_buckets"]
        sample["kv"] = {
            "pool_bytes": self.cache.pool_bytes(),
            "block_size": self.cache.block_size,
            "free_blocks": alloc.num_free,
            "usable_blocks": alloc.num_usable,
            "occupancy": round(alloc.occupancy(), 4),
            "fragmentation": round(self._kv_fragmentation(), 4),
        }
        mon.observe(sample)
        return sample

    def memory_report(self, write=False):
        """The serving-side residency report: forces one window (with
        the KV pool in the inventory) and returns the monitor's report;
        ``write=True`` also writes MEMORY_ANATOMY.json.
        ``{"enabled": False}`` when ``telemetry.memory`` is off."""
        mon = self._memory
        if mon is None:
            return {"enabled": False}
        self._memory_tick(force=True)
        if write:
            mon.write_report()
        return mon.report()

    # -------------------------------------------------------- inspection
    def _kv_fragmentation(self):
        """Internal fragmentation of the live block tables: the fraction
        of allocated KV positions no token has been written to (block
        granularity over-allocation). 0.0 with nothing allocated."""
        allocated = used = 0
        for r in self.scheduler.slots:
            if r is not None:
                allocated += len(r.block_table) * self.cache.block_size
                used += r.cached_len
        return (1.0 - used / allocated) if allocated else 0.0

    def _engine_state(self):
        """Host-side scheduler/slot/KV dump — the forensics core of
        ``serving_report()`` and the livelock exception."""
        slots = []
        for r in self.scheduler.slots:
            slots.append(None if r is None else {
                "req_id": r.req_id,
                "state": r.state.value,
                "prompt_len": len(r.prompt),
                "generated": len(r.output_tokens),
                "cached_len": r.cached_len,
                "blocks": len(r.block_table),
                "step_budget": r.step_budget,
                "preemptions": r.preemptions,
            })
        alloc = self.cache.allocator
        return {
            "scheduler": {
                "waiting": self.scheduler.num_waiting,
                "active": self.scheduler.num_active,
                "waiting_req_ids": [r.req_id for r in
                                    list(self.scheduler.waiting)[:32]],
                "slots": slots,
                "preemptions_by_reason":
                    dict(self.scheduler.preemptions_by_reason),
            },
            "kv": {
                "block_size": self.cache.block_size,
                "num_blocks": alloc.num_blocks,
                "usable": alloc.num_usable,
                "free": alloc.num_free,
                "allocated": alloc.num_allocated,
                "occupancy": round(alloc.occupancy(), 4),
                "fragmentation": round(self._kv_fragmentation(), 4),
                "pool_bytes": self.cache.pool_bytes(),
            },
            "prefix_cache": (None if self.cache.prefix_cache is None
                             else self.cache.prefix_cache.stats()),
            "compile": self.compile_stats(),
        }

    def router_signals(self):
        """The per-replica admission signals a :class:`ServingRouter`
        scores: queue/occupancy pressure plus whether the PR-9 SLO rules
        fired RECENTLY (within the last two observation windows —
        treating an incident from an hour ago as live would park a
        healthy replica forever). With observability off the SLO flags
        stay False and routing degrades to load + affinity."""
        sig = {
            "queue_depth": self.scheduler.num_waiting,
            "active": self.scheduler.num_active,
            "kv_occupancy": self.cache.allocator.occupancy(),
            "ttft_slo_breach": False,
            "queue_growth": False,
        }
        obs = self.observatory
        if obs is not None:
            horizon = obs.steps_seen - 2 * obs.window
            for a in obs.anomalies:
                if a.get("step", 0) >= horizon and \
                        a.get("rule") in ("ttft_slo_breach",
                                          "queue_growth"):
                    sig[a["rule"]] = True
        return sig

    def _chronicle_serving(self, event, severity=None, detail=None,
                           **data):
        """Serving event into the run chronicle (admission pause/resume,
        preemption, livelock last rites). ``step`` is the SERVING step
        clock, not the train step — readers disambiguate by the event's
        ``source``."""
        chron = _chronicle.get_chronicle()
        if chron.enabled:
            chron.emit("serving", source="serving",
                       step=self._serving_steps, severity=severity,
                       detail=detail, event=event, **data)

    def chronicle_report(self, write=False):
        """Serving counterpart of ``engine.chronicle_report``: the
        chronicle is process-global and armed by the engine that owns
        it, so this delegates to the wrapped engine (the serving events
        above are already in the same timeline).
        ``{"enabled": False}`` when no chronicle is armed."""
        fn = getattr(self.engine, "chronicle_report", None)
        if fn is not None:
            return fn(write=write)
        return {"enabled": False}

    def serving_report(self, write=False):
        """The structured serving forensics dict: the observatory report
        (slot-step ledger, windows, SLO anomalies, per-request
        timelines) plus the live scheduler/slot/KV dump under
        ``engine_state``. With observability disabled the engine-state
        dump is still returned — the livelock guard needs it either way.
        ``write=True`` also snapshots it to the observatory's
        ``SERVING_HEALTH.json`` path (observability on only)."""
        if self.observatory is not None:
            report = self.observatory.report()
            if write:
                self.observatory.write_snapshot(report=report, force=True)
            return report
        return {"schema": SERVING_HEALTH_SCHEMA, "enabled": False,
                "engine_state": self._engine_state()}

    def profile_window(self, steps=3, out=None, write=True):
        """Measured device-time anatomy for *steps* scheduler
        iterations — the serving analog of ``engine.profile_step``.

        Runs a bounded ``jax.profiler`` capture around N annotated
        ``step()`` calls (blocking on the KV pools inside each
        annotation so device work lands in-window), post-processes the
        trace with the xplane parser and writes the schema-pinned
        report (default ``telemetry/STEP_ANATOMY.serving.json``).
        Inert (``{"enabled": False}``) when the profiler is
        unavailable or ``DS_TELEMETRY_ANATOMY=0``."""
        import jax
        from deepspeed_tpu.telemetry import step_anatomy
        from deepspeed_tpu.telemetry.ledger import (
            profiler_available, _start_trace, _stop_trace)
        env = os.environ.get("DS_TELEMETRY_ANATOMY")
        if env is not None and env.lower() not in ("1", "true", "yes",
                                                   "on"):
            return {"enabled": False,
                    "reason": "DS_TELEMETRY_ANATOMY disabled"}
        if not profiler_available():
            return {"enabled": False,
                    "reason": "jax.profiler programmatic capture "
                              "unavailable"}
        outdir = os.path.dirname(out) if out else "telemetry/"
        trace_dir = os.path.join(outdir or ".", "anatomy_profile_serving")
        os.makedirs(trace_dir, exist_ok=True)
        try:
            _start_trace(trace_dir)
        except Exception as e:
            return {"enabled": False,
                    "reason": f"profiler start_trace failed: {e}"}
        try:
            from jax.profiler import TraceAnnotation
            for i in range(int(steps)):
                with TraceAnnotation(step_anatomy.STEP_MARK, step=i):
                    self.step()
                    jax.block_until_ready(self.pools)
        finally:
            try:
                _stop_trace()
            except Exception:
                pass
        report = step_anatomy.summarize_capture(trace_dir)
        if report is None:
            return {"enabled": False,
                    "reason": f"profiler wrote no .xplane.pb under "
                              f"{trace_dir}"}
        report["enabled"] = True
        report.setdefault("source", {})["surface"] = "serving"
        if write:
            path = out or os.path.join(
                outdir or ".", "STEP_ANATOMY.serving.json")
            step_anatomy.write_report(report, path)
            report["report_path"] = path
        return report

    def close(self):
        """Teardown: force the observatory's final forensics snapshot.
        Anomalies whose only firings landed inside the 5 s snapshot
        throttle window would otherwise exit the process unexplained —
        ``close()`` is what guarantees the last incident reaches
        ``SERVING_HEALTH.json``. The obs-server scrape route is
        unregistered first — its report provider points at this
        object."""
        if self._obs_server is not None:
            self._obs_server.unregister("serving")
            self._obs_server = None
        if self.observatory is not None:
            self.observatory.close()

    def compile_stats(self):
        """Signature counts per compiled entry point (the 'one decode
        program' acceptance guard reads this)."""
        per_fn = self._watch._per_fn
        stats = {
            "decode_signatures": len(
                per_fn.get("serving_decode_step", {}).get("sigs", ())),
            "prefill_signatures": len(
                per_fn.get("serving_prefill_chunk", {}).get("sigs", ())),
            "retraces": self._watch.retraces,
        }
        if self.speculative is not None:
            # only present with speculation configured, so the exact
            # dict pins on the non-speculative arms stay exact
            stats["draft_signatures"] = len(
                per_fn.get("serving_draft_step", {}).get("sigs", ()))
            stats["verify_signatures"] = len(
                per_fn.get("serving_verify_step", {}).get("sigs", ()))
        return stats
