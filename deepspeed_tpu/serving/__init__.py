"""Production inference serving: continuous batching over a paged KV
cache (see serving/server.py for the subsystem map)."""

from deepspeed_tpu.serving.kv_cache import (BlockAllocator,  # noqa: F401
                                            BlockAllocatorError,
                                            PagedKVCache)
from deepspeed_tpu.serving.paged_attention import (  # noqa: F401
    paged_decode_attention, paged_prefill_attention)
from deepspeed_tpu.serving.prefill import ChunkedPrefill  # noqa: F401
from deepspeed_tpu.serving.router import (RouteDecision,  # noqa: F401
                                          ServingRouter)
from deepspeed_tpu.serving.runner import PagedGPT2Runner  # noqa: F401
from deepspeed_tpu.serving.sampling import (sample_tokens,  # noqa: F401
                                            top_p_filter)
from deepspeed_tpu.serving.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler, Request, RequestState, StepPlan)
from deepspeed_tpu.serving.server import (RequestOutput,  # noqa: F401
                                          ServingEngine,
                                          ServingLivelockError)
