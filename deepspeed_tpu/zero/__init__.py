"""Public ``deepspeed_tpu.zero`` surface (reference ``deepspeed.zero``:
``Init`` at runtime/zero/partition_parameters.py:548, ``GatheredParameters``
:1522, plus the config/estimator helpers).

On TPU, parameters are born sharded DECLARATIVELY: the engine jits its
state constructor with ZeRO out_shardings (runtime/engine.py), so there is
no construction-time monkey-patching to do. ``Init`` therefore validates
its arguments and records the offload intent (the ``remote_device``
cpu/nvme path is the layered ``Zero3OffloadEngine``, selected by the
``zero_optimization.offload_param`` config block); ``GatheredParameters``
does real work — it materialises fully-gathered host copies of sharded
``jax.Array`` trees, the analogue of the reference's allgather context.
"""

import contextlib
import enum

import jax

from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig  # noqa: F401
from deepspeed_tpu.runtime.zero.partition import (  # noqa: F401
    ModelParallelRules, build_opt_shardings, build_param_shardings,
    estimate_zero_mem)
from deepspeed_tpu.runtime.zero.param_offload import (  # noqa: F401
    HostParamStore, Zero3OffloadEngine)
from deepspeed_tpu.runtime.zero.tiling import (  # noqa: F401
    TiledLinear, TiledLinearReturnBias)
from deepspeed_tpu.utils.logging import logger


class ZeroParamType(enum.Enum):
    """Reference partition_parameters.py:182. Informational here: XLA
    array shardings carry the partitioning state the reference tracks
    per-parameter."""
    NORMAL = 1
    PARTITIONED = 2
    REMOTE = 3


class ZeroParamStatus(enum.Enum):
    """Reference partition_parameters.py:195."""
    AVAILABLE = 1
    NOT_AVAILABLE = 2
    INFLIGHT = 3


def register_external_parameter(module, parameter):
    """Reference partition_parameters.py:108: tells the ZeRO-3 hook
    machinery to gather ``parameter`` around ANOTHER module's forward.
    Under XLA there are no fetch/release hooks to inform — a traced
    forward that reads a sharded param makes the compiler insert the
    allgather exactly where it is used, whichever module reads it — so
    cross-module parameter use needs no registration. Kept as an
    accepted no-op so reference training code runs unchanged."""
    del module, parameter


def unregister_external_parameter(module, parameter):
    """Reverse of :func:`register_external_parameter` (reference
    partition_parameters.py:160) — equally a no-op under XLA."""
    del module, parameter


class Init:
    """reference zero.Init context-manager surface. Under XLA the param
    partitioning the reference performs imperatively happens at state
    construction (declarative shardings), so entering the context is a
    no-op; a cpu/nvme ``remote_device`` points at the layered offload
    engine, which `initialize()` selects from the config."""

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear=True, remote_device=None,
                 pin_memory=False, config=None, enabled=True,
                 dtype=None, mpu=None):
        self.remote_device = remote_device
        self.enabled = enabled
        if enabled and remote_device in ("cpu", "nvme"):
            logger.info(
                f"zero.Init(remote_device={remote_device!r}): pass "
                "zero_optimization.offload_param.device in the config and "
                "a layered model to initialize() — the Zero3OffloadEngine "
                "streams layers from host/NVMe (param_offload.py)")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank=None, fwd_module=None,
                       enabled=True):
    """Yield fully-gathered HOST copies of a (possibly sharded) param tree
    (reference partition_parameters.py:1522). jax.device_get resolves
    every shard regardless of its placement; mutations inside the context
    do NOT write back (the reference only writes back from modifier_rank
    on exit — in the declarative model updates go through the engine's
    state, so this context is read-only by design)."""
    if not enabled:
        yield params
        return
    if modifier_rank is not None:
        logger.warning(
            "GatheredParameters(modifier_rank=...): the yielded tree is a "
            "detached host copy — mutations are NOT written back (update "
            "weights through the engine state instead)")
    yield jax.device_get(params)
