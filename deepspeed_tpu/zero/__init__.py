"""Public ``deepspeed_tpu.zero`` surface (reference ``deepspeed.zero``:
``Init`` at runtime/zero/partition_parameters.py:548, ``GatheredParameters``
:1522, plus the config/estimator helpers).

On TPU, parameters are born sharded DECLARATIVELY: the engine jits its
state constructor with ZeRO out_shardings (runtime/engine.py), so there is
no construction-time monkey-patching to do. ``Init`` therefore validates
its arguments and records the offload intent (the ``remote_device``
cpu/nvme path is the layered ``Zero3OffloadEngine``, selected by the
``zero_optimization.offload_param`` config block); ``GatheredParameters``
does real work — it materialises fully-gathered host copies of sharded
``jax.Array`` trees, the analogue of the reference's allgather context.
"""

import contextlib

import jax

from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig  # noqa: F401
from deepspeed_tpu.runtime.zero.partition import (  # noqa: F401
    ModelParallelRules, build_opt_shardings, build_param_shardings,
    estimate_zero_mem)
from deepspeed_tpu.runtime.zero.param_offload import (  # noqa: F401
    HostParamStore, Zero3OffloadEngine)
from deepspeed_tpu.runtime.zero.tiling import TiledLinear  # noqa: F401
from deepspeed_tpu.utils.logging import logger


class Init:
    """reference zero.Init context-manager surface. Under XLA the param
    partitioning the reference performs imperatively happens at state
    construction (declarative shardings), so entering the context is a
    no-op; a cpu/nvme ``remote_device`` points at the layered offload
    engine, which `initialize()` selects from the config."""

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear=True, remote_device=None,
                 pin_memory=False, config=None, enabled=True,
                 dtype=None, mpu=None):
        self.remote_device = remote_device
        self.enabled = enabled
        if enabled and remote_device in ("cpu", "nvme"):
            logger.info(
                f"zero.Init(remote_device={remote_device!r}): pass "
                "zero_optimization.offload_param.device in the config and "
                "a layered model to initialize() — the Zero3OffloadEngine "
                "streams layers from host/NVMe (param_offload.py)")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank=None, fwd_module=None,
                       enabled=True):
    """Yield fully-gathered HOST copies of a (possibly sharded) param tree
    (reference partition_parameters.py:1522). jax.device_get resolves
    every shard regardless of its placement; mutations inside the context
    do NOT write back (the reference only writes back from modifier_rank
    on exit — in the declarative model updates go through the engine's
    state, so this context is read-only by design)."""
    if not enabled:
        yield params
        return
    if modifier_rank is not None:
        logger.warning(
            "GatheredParameters(modifier_rank=...): the yielded tree is a "
            "detached host copy — mutations are NOT written back (update "
            "weights through the engine state instead)")
    yield jax.device_get(params)
