"""Public ``deepspeed_tpu.pipe`` namespace (reference deepspeed/pipe/
__init__.py re-exports the pipeline module surface)."""

from deepspeed_tpu.runtime.pipe.module import (  # noqa: F401
    LayerSpec, PipelineModule, TiedLayerSpec)
