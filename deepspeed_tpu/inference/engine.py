"""Inference engine.

Rebuild of deepspeed/inference/engine.py (``InferenceEngine`` :19):
checkpoint load via the shard-aware IO, dtype conversion, tensor-parallel
sharding over the mesh model axis (`_create_model_parallel_group` :131
analogue), and a compiled forward. Kernel injection
(`_apply_injection_policy` → module_inject) is a no-op transformation on
TPU for flax models built from this package (they already call the Pallas
ops); for HF-style models module_inject.replace_module swaps supported
layer classes.

Generation: ``generate`` runs greedy/temperature decoding as one
``lax.scan`` over the sequence — compiled once per (batch, length) shape.
"""

import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.zero.partition import (ModelParallelRules,
                                                  build_param_shardings)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import log_dist


class InferenceEngine:
    def __init__(self, model, mp_size=1, mpu=None, checkpoint=None,
                 dtype=None, injection_dict=None, replace_method="auto",
                 quantization_setting=None, replace_with_kernel_inject=False,
                 params=None, mp_rules=None, apply_fn=None):
        self.module = model
        self.mp_world_size = mp_size
        self.checkpoint = checkpoint
        self.dtype = dtype or jnp.bfloat16
        self.injection_dict = injection_dict

        if not groups.mesh_is_initialized():
            groups.initialize(mp_size=mp_size, mpu=mpu)
        self.mesh = groups.get_mesh()
        self.mp_rules = mp_rules or ModelParallelRules()

        if params is None and checkpoint is not None:
            params = self._load_checkpoint(checkpoint)
        assert params is not None, "need params or checkpoint"

        params = jax.tree.map(
            lambda x: x.astype(self.dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x, params)
        shardings = build_param_shardings(params, self.mesh, stage=0,
                                          mp_rules=self.mp_rules)
        with self.mesh:
            self.params = jax.device_put(params, shardings)

        self._apply = apply_fn or (
            lambda p, batch: self.module.apply(
                p if isinstance(p, dict) and "params" in p else {"params": p},
                batch))
        self._jit_forward = jax.jit(self._apply)
        log_dist(f"InferenceEngine ready: mp={mp_size} "
                 f"dtype={self.dtype.__name__}", ranks=[0])

    def _load_checkpoint(self, path):
        """Model-states file or consolidated 16bit export."""
        with open(path, "rb") as f:
            sd = pickle.load(f)
        if isinstance(sd, dict) and "module" in sd:
            return sd["module"]
        return sd

    def forward(self, batch):
        with self.mesh:
            return self._jit_forward(self.params, batch)

    __call__ = forward

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 logits_fn=None, rng=None, eos_token_id=None):
        """Greedy / sampled decoding (reference forward :301 loop).

        ``logits_fn(params, ids) -> [B, S, V]`` defaults to the module
        apply on a dict batch (GPT2LMHeadModel convention needs
        ``labels=None`` → logits path is model-specific, so LM models
        should pass logits_fn)."""
        logits_fn = logits_fn or (
            lambda p, ids: self._apply(p, {"input_ids": ids}))
        B, S = input_ids.shape
        total = S + max_new_tokens
        ids = jnp.zeros((B, total), jnp.int32)
        ids = ids.at[:, :S].set(input_ids)
        if rng is None:
            rng = jax.random.PRNGKey(0)

        def step(carry, t):
            ids, rng = carry
            logits = logits_fn(self.params, ids)          # [B, total, V]
            # gather position t-1 logits (next-token head)
            last = jnp.take_along_axis(
                logits, (t - 1)[None, None, None].repeat(B, 0), axis=1)[:, 0]
            rng, sub = jax.random.split(rng)
            if temperature > 0:
                nxt = jax.random.categorical(sub, last / temperature, axis=-1)
            else:
                nxt = jnp.argmax(last, axis=-1)
            ids = ids.at[:, t].set(nxt.astype(jnp.int32))
            return (ids, rng), None

        with self.mesh:
            (ids, _), _ = jax.lax.scan(
                jax.jit(step), (ids, rng), jnp.arange(S, total))
        return ids
