"""Inference engine.

Rebuild of deepspeed/inference/engine.py (``InferenceEngine`` :19):
checkpoint load via the shard-aware IO, dtype conversion, tensor-parallel
sharding over the mesh model axis (`_create_model_parallel_group` :131
analogue), and a compiled forward. Kernel injection
(`_apply_injection_policy` → module_inject) is a no-op transformation on
TPU for flax models built from this package (they already call the Pallas
ops); for HF-style models module_inject.replace_module swaps supported
layer classes.

Generation: ``generate`` runs greedy/temperature decoding as one
``lax.scan`` over the sequence — compiled once per (batch, length) shape.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.state_dict_factory import load_checkpoint_file
from deepspeed_tpu.runtime.zero.partition import (ModelParallelRules,
                                                  build_param_shardings)
from deepspeed_tpu.telemetry.metrics import get_registry
from deepspeed_tpu.telemetry.tracer import trace_span
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import log_dist


def _tree_bytes(tree) -> int:
    """Total leaf bytes of a params pytree (np or jax arrays)."""
    return int(sum(getattr(leaf, "nbytes", 0)
                   for leaf in jax.tree.leaves(tree)))


class InferenceEngine:
    def __init__(self, model, mp_size=1, mpu=None, checkpoint=None,
                 dtype=None, injection_dict=None, replace_method="auto",
                 quantization_setting=None, replace_with_kernel_inject=False,
                 params=None, mp_rules=None, apply_fn=None,
                 ep_size=1, moe=False, moe_experts=1, moe_type="standard"):
        self.module = model
        self.mp_world_size = mp_size
        self.checkpoint = checkpoint
        # dtype=int8 (reference init_inference(dtype=torch.int8)) selects
        # TRUE int8 weight storage: transformer kernels live in HBM as
        # int8 + per-column scales and dequantize inside the matmul
        # (module_inject/module_quantize.py); compute stays bf16
        try:
            self._int8_weights = (dtype is not None
                                  and np.dtype(dtype) == np.int8)
        except TypeError:
            self._int8_weights = False
        if self._int8_weights:
            dtype = jnp.bfloat16
        self.dtype = dtype or jnp.bfloat16
        self.quant_scales = None
        self.injection_dict = injection_dict
        self.quantization_setting = quantization_setting
        # MoE inference (reference inference/engine.py:146
        # _create_ep_parallel_group + moe_inference.py): the expert axis
        # joins the inference mesh and the stacked expert params shard over
        # it — the all-to-all dispatch then rides the same mesh axis as in
        # training. moe_experts (the reference's per-group expert counts)
        # is informational here: the expert tables themselves carry their
        # count; the mesh only needs ep_size.
        self.moe = bool(moe) or ep_size > 1
        self.ep_size = ep_size
        self.moe_experts = moe_experts
        self.moe_type = moe_type

        if not groups.mesh_is_initialized():
            groups.initialize(ep_size=ep_size, mp_size=mp_size, mpu=mpu)
        self.mesh = groups.get_mesh()
        self.mp_rules = mp_rules or ModelParallelRules()
        if self.moe:
            from deepspeed_tpu.moe.layer import moe_sharding_rules
            existing = {pat.pattern for pat, _ in self.mp_rules.rules}
            extra = [(pat, spec) for pat, spec in moe_sharding_rules()
                     if pat not in existing]
            if extra:
                self.mp_rules = ModelParallelRules(
                    [(pat.pattern, spec) for pat, spec in
                     self.mp_rules.rules] + extra)

        if params is None and checkpoint is not None:
            params = self._load_checkpoint(checkpoint)
        assert params is not None, "need params or checkpoint"

        params = jax.tree.map(
            lambda x: x.astype(self.dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x, params)
        if self._int8_weights:
            if apply_fn is not None:
                raise ValueError(
                    "dtype=int8 quantizes kernels and threads a "
                    "'quant_scales' collection through module.apply; a "
                    "custom apply_fn would bypass it and QuantDense would "
                    "fail — drop apply_fn or quantize explicitly via "
                    "module_inject.quantize_transformer_layer")
            from deepspeed_tpu.module_inject.module_quantize import \
                quantize_transformer_layer
            before = _tree_bytes(params)
            with trace_span("inference_int8_quantize"):
                params, self.quant_scales = quantize_transformer_layer(
                    params)
            get_registry().counter(
                "inference_int8_bytes_saved_total",
                "param bytes shed by int8 weight storage"
            ).inc(max(0, before - _tree_bytes(params)
                      - _tree_bytes(self.quant_scales)))
        shardings = build_param_shardings(params, self.mesh, stage=0,
                                          mp_rules=self.mp_rules)
        with self.mesh:
            self.params = jax.device_put(params, shardings)
            if self.quant_scales is not None:
                # per-output-column fp32 vectors: tiny; replicated
                self.quant_scales = jax.device_put(self.quant_scales)

        self._user_apply = apply_fn
        self._apply = apply_fn or (
            lambda p, batch: self.module.apply(self._wrap(p), batch))
        self._jit_forward = jax.jit(self._apply)
        self._gen_cache = {}  # (temperature, eos) -> compiled decode loop
        log_dist(f"InferenceEngine ready: mp={mp_size} "
                 f"dtype={self.dtype.__name__}", ranks=[0])

    def _load_checkpoint(self, path):
        """Three accepted forms (reference InferenceEngine._load_checkpoint
        :244): a checkpoint-description JSON (SDLoaderFactory — Megatron
        checkpoints, auto mp merge + flax conversion), a model-states
        pickle, or a consolidated 16bit export.

        The whole load (file IO + format conversion) is traced as one
        ``inference_checkpoint_load`` span with the loaded param bytes on
        a registry counter — ``init_inference`` can spend minutes here on
        big checkpoints and was previously invisible to the tracer (the
        checkpoint_io spans cover only the raw file reads)."""
        with trace_span("inference_checkpoint_load", path=str(path)):
            params = self._load_checkpoint_impl(path)
        get_registry().counter(
            "inference_checkpoint_bytes_total",
            "param bytes materialised by inference checkpoint loads"
        ).inc(_tree_bytes(params))
        return params

    def _load_checkpoint_impl(self, path):
        if str(path).endswith(".json"):
            from deepspeed_tpu.runtime.state_dict_factory import (
                SDLoaderFactory, megatron_to_gpt2_params)
            loader = SDLoaderFactory.get_sd_loader_json(path)
            # single-controller SPMD: merge to mp=1 host-side, then the
            # engine re-shards onto the mesh via mp_rules (device_put) —
            # the reference's per-rank split happens declaratively here
            _, sd, _ = loader.load(mp_world_size=1, mp_rank=0)
            module_sd = loader.get_module(sd)
            if self.quantization_setting is not None:
                module_sd = self._apply_weight_quantization(module_sd)
            from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
            if isinstance(self.module, GPT2LMHeadModel):
                version = loader.get_checkpoint_version(sd)
                return megatron_to_gpt2_params(module_sd,
                                               self.module.config,
                                               checkpoint_version=version)
            return module_sd
        if self.quantization_setting is not None:
            log_dist(
                "quantization_setting is only applied to Megatron-format "
                "checkpoint JSONs (the weight names drive the grouping); "
                "this flax/pickle checkpoint loads UNQUANTIZED", ranks=[0])
        sd = load_checkpoint_file(path)
        # Megatron checkpoints record their QKV head layout version on the
        # OUTER dict (state_dict_factory get_checkpoint_version); keep it
        # across the module unwrap for the policy conversion below
        ckpt_version = (sd.get("checkpoint_version", 0)
                        if isinstance(sd, dict) else 0)
        if isinstance(sd, dict) and "module" in sd:
            module_sd = sd["module"]
            if sd.get("has_moe_layers"):
                # per-expert file layout (engine _save_moe_checkpoint
                # analogue): re-stack layer_{L}_expert_{E} files
                import os
                from deepspeed_tpu.runtime.checkpoint_io import \
                    restore_moe_experts
                module_sd = restore_moe_experts(
                    os.path.dirname(str(path)), module_sd,
                    sd.get("moe_layer_prefixes", []),
                    expert_counts=sd.get("moe_expert_counts"))
            sd = module_sd
        if isinstance(sd, dict):
            # replace_method='auto': detect HF/Megatron checkpoint naming
            # and convert through the matching injection policy
            # (module_inject.CHECKPOINT_POLICIES)
            from deepspeed_tpu.module_inject import detect_checkpoint_policy
            pol = detect_checkpoint_policy(sd)
            if pol is not None and hasattr(self.module, "config"):
                target_cls = type(pol.target_model(self.module.config))
                if isinstance(self.module, target_cls):
                    log_dist(f"injection policy '{pol.name}' converting "
                             "checkpoint", ranks=[0])
                    return pol.convert(sd, self.module.config,
                                       checkpoint_version=ckpt_version)
        return sd

    def _apply_weight_quantization(self, module_sd):
        """MoQ post-training weight quantization (reference
        quantization_setting → WeightQuantization). quantization_setting:
        groups (int) or (mlp_extra_grouping, groups)."""
        from deepspeed_tpu.runtime.weight_quantizer import \
            quantize_dequantize_sd
        qs = self.quantization_setting
        if isinstance(qs, (tuple, list)):
            mlp_extra_grouping, groups = qs
        else:
            mlp_extra_grouping, groups = True, int(qs)
        with trace_span("inference_weight_quantize", groups=groups):
            out, quantized = quantize_dequantize_sd(
                module_sd, groups, mlp_extra_grouping=mlp_extra_grouping,
                mp_size=self.mp_world_size)
        get_registry().counter(
            "inference_quantized_tensors_total",
            "tensors passed through MoQ weight quantization"
        ).inc(quantized)
        log_dist(f"MoQ weight quantization applied to {quantized} tensors "
                 f"(groups={groups})", ranks=[0])
        return out

    def forward(self, batch):
        with self.mesh:
            return self._jit_forward(self.params, batch)

    __call__ = forward

    def _call_params(self):
        """Parameter names of the wrapped module's __call__."""
        import inspect
        try:
            return inspect.signature(type(self.module).__call__).parameters
        except (TypeError, ValueError):
            return {}

    def _supports_kv_cache(self) -> bool:
        """True when the wrapped module takes the ``decode`` kwarg (the
        flax cache-collection protocol models/gpt2.py implements)."""
        return "decode" in self._call_params()

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 logits_fn=None, rng=None, eos_token_id=None,
                 use_cache=None):
        """Greedy / sampled decoding (reference forward :301 loop).

        Default path is KV-cache decoding (the `softmax_context_*` surface
        of csrc/transformer/inference/csrc/pt_binding.cpp:829): one prefill
        pass writes the prompt's K/V into the model's flax "cache"
        collection, then each generated token is ONE single-token forward —
        per-token cost independent of how many tokens were generated.
        Models without cache support (no ``decode`` kwarg, or a custom
        ``logits_fn``) fall back to full-sequence recompute per token."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        temperature = float(temperature)  # hashable compiled-loop cache key
        if use_cache is None:
            # a user apply_fn wraps module.apply in unknown ways (extra
            # collections/rngs), so the bare-apply cache path can't be used
            use_cache = (logits_fn is None and self._user_apply is None
                         and self._supports_kv_cache())
        if use_cache:
            return self._generate_cached(input_ids, max_new_tokens,
                                         temperature, rng, eos_token_id)
        return self._generate_recompute(input_ids, max_new_tokens,
                                        temperature, logits_fn, rng,
                                        eos_token_id)

    def _wrap(self, p):
        out = p if isinstance(p, dict) and "params" in p else {"params": p}
        if self.quant_scales is not None and "quant_scales" not in out:
            out = {**out, "quant_scales": self.quant_scales}
        return out

    def _sample(self, last, rng, temperature):
        # Megatron-style padded vocab: rows >= vocab_size exist only for
        # tile alignment and must never be sampled
        vs = getattr(getattr(self.module, "config", None), "vocab_size", None)
        if vs is not None and vs < last.shape[-1]:
            last = last[..., :vs]
        if temperature > 0:
            return jax.random.categorical(rng, last / temperature, axis=-1
                                          ).astype(jnp.int32)
        return jnp.argmax(last, axis=-1).astype(jnp.int32)

    def _generate_cached(self, input_ids, max_new_tokens, temperature, rng,
                         eos_token_id):
        S = input_ids.shape[1]
        cfg = getattr(self.module, "config", None)
        max_pos = getattr(cfg, "n_positions", None)
        if max_pos is not None and S + max_new_tokens > max_pos:
            # dynamic_update_slice CLAMPS out-of-range indices, so an
            # overfull cache would silently overwrite the last slot
            raise ValueError(
                f"prompt ({S}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"the model's n_positions ({max_pos})")
        key = (temperature, eos_token_id)
        loop = self._gen_cache.pop(key, None)
        if loop is None:
            if len(self._gen_cache) >= 32:  # bound compiled-program leak
                # LRU eviction: hits below re-insert, so insertion order
                # is recency order and the front is the least recent
                self._gen_cache.pop(next(iter(self._gen_cache)))
            loop = self._build_cached_loop(temperature, eos_token_id)
        self._gen_cache[key] = loop  # (re-)insert at the back: most recent
        with self.mesh:
            new = loop(self.params, input_ids, rng, max_new_tokens)
        return jnp.concatenate([input_ids, new], axis=1)

    def _build_cached_loop(self, temperature, eos_token_id):
        """One compiled decode loop: prefill + (max_new-1)-step scan.
        jit caches on (shapes, max_new), so repeat generate() calls with
        the same shapes skip compilation entirely."""
        import functools
        module = self.module

        @functools.partial(jax.jit, static_argnums=(3,))
        def run(params, input_ids, rng, max_new_tokens):
            wrapped = self._wrap(params)
            logits, variables = module.apply(
                wrapped, {"input_ids": input_ids}, decode=True,
                mutable=["cache"])
            rng, sub = jax.random.split(rng)
            first = self._sample(logits[:, -1], sub, temperature)

            def step(carry, _):
                tok, cache, rng, done = carry
                logits, variables = module.apply(
                    {**wrapped, "cache": cache},
                    {"input_ids": tok[:, None]}, decode=True,
                    mutable=["cache"])
                rng, sub = jax.random.split(rng)
                nxt = self._sample(logits[:, -1], sub, temperature)
                if eos_token_id is not None:
                    done = done | (tok == eos_token_id)
                    nxt = jnp.where(done, eos_token_id, nxt)
                return (nxt, variables["cache"], rng, done), nxt

            if max_new_tokens == 1:
                return first[:, None]
            done = jnp.zeros((input_ids.shape[0],), bool)
            _, rest = jax.lax.scan(step, (first, variables["cache"], rng,
                                          done),
                                   None, length=max_new_tokens - 1)
            return jnp.concatenate([first[:, None], rest.T], axis=1)

        return run

    def _generate_recompute(self, input_ids, max_new_tokens, temperature,
                            logits_fn, rng, eos_token_id):
        if logits_fn is None:
            if self._user_apply is None and \
                    "return_logits" in self._call_params():
                logits_fn = lambda p, ids: self.module.apply(  # noqa: E731
                    self._wrap(p), {"input_ids": ids}, return_logits=True)
            else:
                logits_fn = lambda p, ids: self._apply(  # noqa: E731
                    p, {"input_ids": ids})
        B, S = input_ids.shape
        total = S + max_new_tokens
        ids = jnp.zeros((B, total), jnp.int32)
        ids = ids.at[:, :S].set(input_ids)

        def step(carry, t):
            ids, rng = carry
            logits = logits_fn(self.params, ids)          # [B, total, V]
            # gather position t-1 logits (next-token head)
            last = jnp.take_along_axis(
                logits, (t - 1)[None, None, None].repeat(B, 0), axis=1)[:, 0]
            rng, sub = jax.random.split(rng)
            nxt = self._sample(last, sub, temperature)
            if eos_token_id is not None:
                prev_done = (t > S) & (ids[:, jnp.maximum(t - 1, 0)]
                                       == eos_token_id)
                nxt = jnp.where(prev_done, eos_token_id, nxt)
            ids = ids.at[:, t].set(nxt)
            return (ids, rng), None

        with self.mesh:
            (ids, _), _ = jax.lax.scan(
                jax.jit(step), (ids, rng), jnp.arange(S, total))
        return ids
