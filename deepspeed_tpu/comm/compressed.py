"""Compressed (1-bit) allreduce — a real collective, not just algorithm
parity.

Rebuild of the reference's error-compensated compressed allreduce
(deepspeed/runtime/comm/nccl.py:47 ``compressed_allreduce``; MPI variant
comm/mpi.py:170): each rank contributes sign bits (packed 8/byte into
uint8) plus ONE fp32 scale per tensor, cutting bytes-on-wire ~16x vs an
fp32 allreduce. Two-stage error feedback (worker + server) keeps the
quantisation error from accumulating — the 1-bit Adam convergence result.

TPU-native shape: the function runs INSIDE ``shard_map`` over a mesh axis.
The reference's cupy bit-packing + ``dist.all_to_all_single`` +
``dist.all_gather`` become jnp bit algebra + ``lax.all_to_all`` +
``lax.all_gather`` lowering to ICI/DCN collectives. The reference's
"server" (each rank reducing its own chunk) is the all_to_all row split.

Wire format per rank and tensor: ``numel/8`` uint8 sign bytes (all_to_all)
+ 1 fp32 worker scale (all_gather) out; ``numel/(8*size)`` uint8 server
sign bytes + 1 fp32 server scale broadcast back (all_gather). Exact-fp32
wire cost would be ``4*numel`` in + ``4*numel`` out.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_BIT_WEIGHTS = np.array([128, 64, 32, 16, 8, 4, 2, 1], np.uint8)


def pack_signs(positive):
    """bool [M] (M % 8 == 0) -> uint8 [M/8]; bit 7 first (cupy.packbits)."""
    b = positive.reshape(-1, 8).astype(jnp.uint8)
    return (b * jnp.asarray(_BIT_WEIGHTS)).sum(axis=-1).astype(jnp.uint8)


def unpack_signs(packed):
    """uint8 [K] -> float ±1 [K*8]."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts) & jnp.uint8(1)
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def padded_numel(numel: int, world: int) -> int:
    """Error buffers are allocated at this size (reference pads buffer_m up
    to worker_error.numel(), nccl.py:60-65): divisible by 8*world so sign
    bytes chunk evenly across ranks."""
    q = 8 * world
    return -(-numel // q) * q


def compressed_allreduce(x, worker_error, server_error, axis_name):
    """Mean-allreduce of ``x`` over ``axis_name`` in 1-bit precision.

    Must run inside shard_map/pjit with ``axis_name`` bound. ``x`` is this
    rank's flat fp32 tensor [N]; ``worker_error`` [P] and ``server_error``
    [P / world] carry the error feedback (P = padded_numel(N, world)).
    Returns (result [N], new_worker_error, new_server_error).
    """
    world = lax.psum(1, axis_name)
    n = x.shape[0]
    p = worker_error.shape[0]
    chunk = p // world
    assert server_error.shape[0] == chunk, (server_error.shape, chunk)

    buf = jnp.zeros((p,), jnp.float32).at[:n].set(x.astype(jnp.float32))
    buf = buf + worker_error
    # RMS scale (reference worker_scale = norm/sqrt(numel), nccl.py:66)
    worker_scale = jnp.linalg.norm(buf) / jnp.sqrt(p)
    positive = buf >= 0  # sign(0) -> +1, the reference's bool trick
    signs = jnp.where(positive, 1.0, -1.0)
    new_worker_error = buf - worker_scale * signs

    # phase 1: sign bytes all_to_all (each rank collects chunk r of every
    # rank), scale allgather — nccl.py:96-104
    packed = pack_signs(positive).reshape(world, chunk // 8)
    recv = lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    scales = lax.all_gather(worker_scale, axis_name)            # [world]

    # server stage: mean of the ranks' ±1 chunks weighted by their scales,
    # plus server error feedback — nccl.py:110-126
    vals = jax.vmap(unpack_signs)(recv)                         # [world, chunk]
    server_m = (vals * scales[:, None]).mean(axis=0) + server_error
    server_scale = jnp.linalg.norm(server_m) / jnp.sqrt(chunk)
    s_positive = server_m >= 0
    s_signs = jnp.where(s_positive, 1.0, -1.0)
    new_server_error = server_m - server_scale * s_signs

    # phase 2: server sign bytes + scale allgather back — nccl.py:131-142
    s_packed = pack_signs(s_positive)                           # [chunk/8]
    all_packed = lax.all_gather(s_packed, axis_name)            # [world, ..]
    all_scales = lax.all_gather(server_scale, axis_name)        # [world]
    parts = jax.vmap(unpack_signs)(all_packed)                  # [world, chunk]
    result = (parts * all_scales[:, None]).reshape(-1)[:n]
    return result.astype(x.dtype), new_worker_error, new_server_error


def make_compressed_allreduce(mesh, axis_name="data"):
    """shard_map-wrapped entry point: takes REPLICATED-per-rank inputs
    where dim 0 is the rank dim ([world, ...] stacked local tensors) and
    runs the collective over ``axis_name``.

    The host-facing analogue of NcclBackend.compressed_allreduce: use it
    when per-rank values genuinely differ (local momenta). Inside a pjit
    train step, call :func:`compressed_allreduce` directly under
    shard_map.
    """
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.utils.jax_compat import get_shard_map
    shard_map, smap_kw = get_shard_map()

    spec = P(axis_name)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec), **smap_kw)
    def run(x, we, se):
        out, we2, se2 = compressed_allreduce(
            x[0], we[0], se[0], axis_name)
        return out[None], we2[None], se2[None]

    return run


def collective_wire_bytes(fn, *args):
    """Sum of operand bytes entering collective primitives of ``fn(*args)``
    — the measured bytes-on-wire of one call (used by tests to verify the
    compression actually shrinks traffic)."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    total = 0
    coll = {"all_to_all", "all_gather", "psum", "all_reduce",
            "reduce_scatter"}

    def walk(jp):
        nonlocal total
        for eqn in jp.eqns:
            if eqn.primitive.name in coll:
                for v in eqn.invars:
                    aval = getattr(v, "aval", None)
                    if aval is not None and hasattr(aval, "shape"):
                        total += int(np.prod(aval.shape, initial=1)
                                     * aval.dtype.itemsize)
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)
                elif isinstance(sub, (list, tuple)):
                    for s in sub:
                        if hasattr(s, "jaxpr"):
                            walk(s.jaxpr)

    walk(jaxpr.jaxpr)
    return total
