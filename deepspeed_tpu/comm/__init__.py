"""Communication layer: XLA collectives over ICI/DCN.

This is the TPU-native rebuild of the reference's communication stack
(``deepspeed/utils/distributed.py:12`` ``init_distributed``,
``deepspeed/runtime/comm/coalesced_collectives.py:43``, and the
torch.distributed verb surface). Instead of NCCL process groups there is a
single :class:`jax.sharding.Mesh` with named axes; every verb is an XLA
collective bound to an axis name and must run inside ``jit`` / ``shard_map``
traced over that mesh — the compiler schedules them onto ICI (within slice)
or DCN (across slices) and fuses/overlaps them, which replaces the
reference's hand-written bucketing.

Two API levels:

* in-jit verbs (``all_reduce``, ``all_gather``, ``reduce_scatter``,
  ``all_to_all``, ``ppermute``, ``broadcast``, ``psum_scatter``): thin,
  axis-name-based wrappers over ``jax.lax`` collectives. They exist so the
  rest of the framework reads like the reference's comm calls and so the
  backend could be swapped.
* host-level helpers (``init_distributed``, ``get_world_size``,
  ``get_rank``, ``barrier``): process bootstrap and queries, the analogue of
  torch.distributed rendezvous.
"""

import os

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils.logging import logger

# ---------------------------------------------------------------------------
# Process bootstrap (reference: deepspeed/utils/distributed.py:12)
# ---------------------------------------------------------------------------

_INITIALIZED = False


def init_distributed(dist_backend="xla",
                     auto_mpi_discovery=True,
                     verbose=True,
                     init_method=None,
                     coordinator_address=None,
                     num_processes=None,
                     process_id=None):
    """Initialise multi-host JAX if environment variables demand it.

    Single-process (one host driving all local chips) needs no rendezvous —
    JAX sees every local device already. Multi-host (one process per TPU VM
    host) uses ``jax.distributed.initialize``, the analogue of
    ``torch.distributed.init_process_group`` (NCCL rendezvous) in the
    reference. Safe to call repeatedly.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    def env(*names):
        for n in names:
            if os.environ.get(n) is not None:
                return os.environ[n]
        return None

    # DS_* set directly; JAX_* exported by the launcher (runner.py)
    coordinator = coordinator_address or env("DS_COORDINATOR_ADDRESS",
                                             "JAX_COORDINATOR_ADDRESS")
    nprocs = num_processes if num_processes is not None else \
        env("DS_NUM_PROCESSES", "JAX_PROCESS_COUNT")
    pid = process_id if process_id is not None else \
        env("DS_PROCESS_ID", "JAX_PROCESS_ID")
    if pid is None and auto_mpi_discovery:
        # MPI transport (reference OpenMPIRunner/MVAPICHRunner,
        # launcher/multinode_runner.py:100/:155): mpirun exports the rank
        pid = env("OMPI_COMM_WORLD_RANK", "MV2_COMM_WORLD_RANK",
                  "PMI_RANK")
        if nprocs is None:
            nprocs = env("OMPI_COMM_WORLD_SIZE", "MV2_COMM_WORLD_SIZE",
                         "PMI_SIZE")
        if (pid is not None and coordinator is None
                and nprocs is not None and int(nprocs) > 1):
            # without a rendezvous address every mpirun rank would
            # silently train an independent single-process copy
            raise RuntimeError(
                f"MPI world of {nprocs} discovered (rank {pid}) but no "
                "coordinator address is set — export "
                "JAX_COORDINATOR_ADDRESS=host:port (the deepspeed "
                "--launcher openmpi transport does this), or ranks would "
                "each train an independent copy of the job")
    if pid is None and os.environ.get("DS_WORLD_INFO"):
        pid, n = rank_from_world_info(os.environ["DS_WORLD_INFO"])
        if nprocs is None:
            nprocs = n

    if coordinator is not None and nprocs is not None and pid is not None:
        if verbose:
            logger.info(
                f"Initializing jax.distributed: coordinator={coordinator} "
                f"num_processes={nprocs} process_id={pid}")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=int(nprocs),
                                   process_id=int(pid))
    elif verbose:
        logger.info("Single-controller JAX: no multi-host rendezvous needed "
                    f"({len(jax.devices())} local device(s))")
    _INITIALIZED = True


def rank_from_world_info(world_info: str):
    """Derive (process_id, num_processes) for the pdsh transport
    (reference PDSHRunner, multinode_runner.py:45): one identical command
    fans out to every host; the rank is this host's position in the
    hostfile encoded in DS_WORLD_INFO.

    Raises loudly when this host's name matches no hostfile entry — a
    silent fall-through would leave every pdsh-launched host training an
    independent single-process copy of the job. Hostnames are matched
    exactly, then by short-name (FQDN vs hostfile short names either way
    round)."""
    import base64 as _b64
    import json as _json
    import socket as _socket
    world = _json.loads(_b64.urlsafe_b64decode(world_info).decode())
    hosts = list(world)
    me = _socket.gethostname()
    if me not in hosts:
        short = {h.split(".")[0]: h for h in hosts}
        if me.split(".")[0] in short:
            me = short[me.split(".")[0]]
        else:
            raise RuntimeError(
                f"DS_WORLD_INFO is set but this host "
                f"({_socket.gethostname()!r}) matches none of its entries "
                f"{hosts} — rank cannot be derived. The pdsh transport "
                f"needs hostfile names that resolve to worker hostnames "
                f"(IP-based hostfiles need --launcher ssh, which assigns "
                f"ranks driver-side)")
    return str(hosts.index(me)), str(len(hosts))


def is_initialized():
    return _INITIALIZED


def get_world_size():
    """Total number of participating devices (chips), not processes."""
    return jax.device_count()


def get_local_device_count():
    return jax.local_device_count()


def get_rank():
    """Process (host) index — the analogue of a node rank."""
    return jax.process_index()


def get_process_count():
    return jax.process_count()


def barrier():
    """Block until all HOSTS reach this point and their device work is
    done (reference dist.barrier). Multi-process runs use the runtime's
    cross-host sync collective; a single process only needs the local
    dispatch fence."""
    jax.effects_barrier()  # flush ordered effects (host callbacks) first
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deepspeed_tpu.barrier")
        return
    x = jnp.zeros((), dtype=jnp.float32)
    jax.block_until_ready(x + 0)


# ---------------------------------------------------------------------------
# In-jit verbs (must be called under jit/shard_map with the axis bound)
# ---------------------------------------------------------------------------


def all_reduce(x, axis_name, op="sum"):
    """Reduce across *axis_name*; every shard gets the result.

    op in {sum, mean, max, min}. Reference verb: dist.all_reduce.
    """
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"Unsupported reduce op: {op}")


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards along *axis* from every member of *axis_name*.

    With ``tiled=True`` the gathered parts are concatenated along *axis*
    (the torch ``_all_gather_base`` flat behaviour used by ZeRO at
    partition_parameters.py:40-58); with ``tiled=False`` a new leading
    axis of size ``world`` is created.
    """
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    """Sum across *axis_name* then scatter slices along *scatter_dimension*.

    The ZeRO-2/3 gradient verb (reference: reduce_scatter_coalesced,
    comm/coalesced_collectives.py:43). Coalescing/flattening is unnecessary
    here: XLA fuses neighbouring reduce-scatters itself.
    """
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


def all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=True):
    """MoE dispatch/combine verb (reference: _AllToAll, moe/sharded_moe.py:84)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    """Point-to-point permutation — the pipeline p2p verb.

    (reference: runtime/pipe/p2p.py send/recv). perm is a list of
    (src, dst) pairs; shards not named as a dst receive zeros.
    """
    return lax.ppermute(x, axis_name, perm)


def send_next(x, axis_name, world):
    """Rotate shards to the next rank on the axis ring (pipeline forward)."""
    perm = [(i, (i + 1) % world) for i in range(world)]
    return lax.ppermute(x, axis_name, perm)


def send_prev(x, axis_name, world):
    """Rotate shards to the previous rank (pipeline backward)."""
    perm = [(i, (i - 1) % world) for i in range(world)]
    return lax.ppermute(x, axis_name, perm)


def broadcast(x, axis_name, root=0):
    """Every member of *axis_name* receives root's value.

    Implemented as a masked psum — XLA lowers this to a broadcast.
    Reference verb: dist.broadcast (engine._broadcast_model, engine.py:953).
    """
    idx = lax.axis_index(axis_name)
    mask = (idx == root).astype(x.dtype)
    return lax.psum(x * mask, axis_name)


def axis_index(axis_name):
    """This shard's coordinate on *axis_name* (reference: group rank)."""
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    """Size of *axis_name* (reference: group world size)."""
    return lax.axis_size(axis_name)
