"""deepspeed_tpu — a TPU-native large-scale training framework.

Public API parity with ``deepspeed/__init__.py``: ``initialize`` (:50),
``init_distributed``, ``add_config_arguments`` (:204), plus the TPU-native
module surface (``ops``, ``moe``, ``pipe`` via runtime, ``zero``).
"""

__version__ = "0.1.0"
version = __version__

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import logger, log_dist
import deepspeed_tpu.comm as comm


def init_distributed(dist_backend="xla", **kwargs):
    """Reference: deepspeed.init_distributed (utils/distributed.py:12)."""
    comm.init_distributed(dist_backend=dist_backend, **kwargs)


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               **kwargs):
    """Create a training engine (reference: deepspeed.initialize,
    deepspeed/__init__.py:50).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)`` with
    the same tuple contract as the reference. ``model`` is a flax module
    (or ``(params, apply_fn)`` protocol object); extra TPU-native kwargs:
    ``loss_fn``, ``sample_batch`` (for shape init), ``mp_rules``
    (megatron-style tensor-parallel sharding rules).
    """
    assert model is not None, "deepspeed_tpu.initialize: model is required"

    engine = DeepSpeedEngine(args=args,
                             model=model,
                             optimizer=optimizer,
                             model_parameters=model_parameters,
                             training_data=training_data,
                             lr_scheduler=lr_scheduler,
                             mpu=mpu,
                             dist_init_required=dist_init_required,
                             collate_fn=collate_fn,
                             config=config,
                             config_params=config_params,
                             **kwargs)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model, mp_size=1, mpu=None, checkpoint=None, dtype=None,
                   injection_policy=None, replace_method="auto",
                   quantization_setting=None,
                   replace_with_kernel_inject=False, **kwargs):
    """Create an inference engine (reference: deepspeed.init_inference,
    deepspeed/__init__.py:220)."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    return InferenceEngine(model, mp_size=mp_size, mpu=mpu,
                           checkpoint=checkpoint, dtype=dtype,
                           injection_dict=injection_policy,
                           replace_method=replace_method,
                           quantization_setting=quantization_setting,
                           replace_with_kernel_inject=replace_with_kernel_inject,
                           **kwargs)


def add_config_arguments(parser):
    """Reference: deepspeed.add_config_arguments (deepspeed/__init__.py:204)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag to ease transition)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated enable flag")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated config path")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI")
    return parser
