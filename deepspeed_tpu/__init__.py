"""deepspeed_tpu — a TPU-native large-scale training framework.

Public API parity with ``deepspeed/__init__.py``: ``initialize`` (:50),
``init_distributed``, ``add_config_arguments`` (:204), plus the TPU-native
module surface (``ops``, ``moe``, ``pipe`` via runtime, ``zero``).
"""

from typing import Callable  # noqa: E402

__version__ = "0.1.0"
version = __version__
__version_major__, __version_minor__, __version_patch__ = (
    int(x) for x in __version__.split("."))

import jax.numpy as jnp

from deepspeed_tpu.runtime.config import (ADAM_OPTIMIZER, LAMB_OPTIMIZER,
                                           DeepSpeedConfig,
                                           DeepSpeedConfigError)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

# reference engine.py:72-74 type aliases: a callable producing the
# optimizer (resp. scheduler) from params — same contract, torch-free
DeepSpeedOptimizerCallable = Callable
DeepSpeedSchedulerCallable = Callable
from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import logger, log_dist
import deepspeed_tpu.comm as comm


def init_distributed(dist_backend="xla", **kwargs):
    """Reference: deepspeed.init_distributed (utils/distributed.py:12)."""
    comm.init_distributed(dist_backend=dist_backend, **kwargs)


def _as_config_dict(config):
    """Raw dict view of a config given as dict, JSON/hjson path, or
    DeepSpeedConfig (for pre-engine dispatch decisions)."""
    if isinstance(config, dict):
        return config
    if isinstance(config, str):
        import json
        try:
            with open(config) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None
    if isinstance(config, DeepSpeedConfig):
        return getattr(config, "_param_dict", None)
    return None


def _make_curriculum(cfg):
    """CurriculumScheduler when the config enables curriculum learning
    (reference threads curriculum_seqlen through the pipe engine too,
    runtime/pipe/engine.py:307)."""
    if not cfg.curriculum_enabled:
        return None
    from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import \
        CurriculumScheduler
    return CurriculumScheduler(cfg.curriculum_config.params)


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               **kwargs):
    """Create a training engine (reference: deepspeed.initialize,
    deepspeed/__init__.py:50).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)`` with
    the same tuple contract as the reference. ``model`` is a flax module
    (or ``(params, apply_fn)`` protocol object); extra TPU-native kwargs:
    ``loss_fn``, ``sample_batch`` (for shape init), ``mp_rules``
    (megatron-style tensor-parallel sharding rules).
    """
    # ZeRO-3 parameter offload takes the layered host-loop engine (the
    # zero.Init remote_device=cpu/nvme path, partition_parameters.py:701):
    # params never fully materialise on device, so the monolithic-jit
    # DeepSpeedEngine cannot express it. ``model`` must then be a sequence
    # of flax layers (LayerSpec decomposition).
    _cfg_dict = _as_config_dict(config if config is not None else config_params)
    if _cfg_dict is not None:
        _zo = _cfg_dict.get("zero_optimization", {}) or {}
        _off = dict(_zo.get("offload_param", {}) or {})
        if _zo.get("cpu_offload_params") and not _off.get("device") and \
                _zo.get("stage") == 3:
            # deprecated spelling (zero/config.py:121); param offload only
            # exists at stage 3 — stage<2 configs carrying the flag keep
            # their historical no-op behavior
            _off["device"] = "cpu"
        if _off.get("device") in ("cpu", "nvme"):
            from deepspeed_tpu.runtime.zero.param_offload import \
                Zero3OffloadEngine
            assert isinstance(model, (list, tuple)), (
                "offload_param requires a layered model: pass model as a "
                "sequence of flax modules (body layers x->x, final layer "
                "(x, batch)->loss)")
            assert "sample_batch" in kwargs, (
                "offload_param requires sample_batch= for shape init")
            assert optimizer is None and lr_scheduler is None and \
                training_data is None, (
                    "offload_param drives its own host CPU-Adam; client "
                    "optimizer/lr_scheduler/training_data are unsupported")
            _opt_cfg = _cfg_dict.get("optimizer", {}) or {}
            _opt_name = str(_opt_cfg.get("type", "Adam")).lower()
            assert _opt_name in ("adam", "adamw"), (
                f"offload_param drives the host CPU-Adam; optimizer type "
                f"{_opt_cfg.get('type')!r} is unsupported on this path")
            if _off["device"] == "nvme":
                assert _off.get("nvme_path"), (
                    "offload_param.device='nvme' requires nvme_path")
            # fail LOUDLY on config keys this engine does not implement
            # (ADVICE r2: silently dropping them trains differently than
            # the reference JSON asks for)
            _unsupported = []
            if (_cfg_dict.get("scheduler", {}) or {}).get("type"):
                _unsupported.append("scheduler")
            if _cfg_dict.get("gradient_clipping", 0):
                _unsupported.append("gradient_clipping")
            if (_cfg_dict.get("fp16", {}) or {}).get("enabled"):
                _unsupported.append(
                    "fp16 dynamic loss scaling (bf16 is supported)")
            if _cfg_dict.get("sparse_gradients"):
                _unsupported.append("sparse_gradients")
            if _unsupported:
                raise DeepSpeedConfigError(
                    "the layered Zero3OffloadEngine does not implement: "
                    + ", ".join(_unsupported)
                    + "; remove these keys or use the monolithic engine "
                    "(offload_optimizer instead of offload_param)")
            opt_params = _opt_cfg.get("params", {})
            _dtype = (jnp.bfloat16
                      if (_cfg_dict.get("bf16", {}) or {}).get("enabled")
                      else jnp.float32)
            engine = Zero3OffloadEngine(
                model, kwargs["sample_batch"],
                lr=opt_params.get("lr", 1e-3),
                betas=tuple(opt_params.get("betas", (0.9, 0.999))),
                eps=opt_params.get("eps", 1e-8),
                weight_decay=opt_params.get("weight_decay", 0.0),
                nvme_path=(_off.get("nvme_path")
                           if _off.get("device") == "nvme" else None),
                compute_dtype=_dtype,
                input_fn=kwargs.get("input_fn"))
            return engine, None, None, None

    assert model is not None, "deepspeed_tpu.initialize: model is required"

    # PipelineModule → 1F1B host-loop engine (reference: initialize()
    # returns a PipelineEngine when the model is a PipelineModule,
    # deepspeed/__init__.py:116 isinstance check)
    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    if isinstance(model, PipelineModule):
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        assert _cfg_dict is not None, (
            "PipelineModule initialization needs a dict/JSON config")
        assert "sample_batch" in kwargs, (
            "PipelineModule initialization requires sample_batch=")
        assert optimizer is None and training_data is None and \
            model_parameters is None, (
                "the 1F1B PipelineEngine drives its own optimizer; client "
                "optimizer/training_data are unsupported")
        # proper triangulation + validation comes from DeepSpeedConfig;
        # dp replicates whole pipeline columns (PP x DP grid)
        _dp = int(kwargs.get("dp", 1))
        cfg = DeepSpeedConfig(_cfg_dict, data_parallel_size=_dp)
        # fail LOUDLY on config keys this engine does not implement
        # (ADVICE r2: silently dropping fp16/zero/scheduler keys trains
        # differently than the reference JSON asks for)
        if cfg.zero_optimization_stage != 0:
            raise DeepSpeedConfigError(
                f"the host-loop PipelineEngine does not implement ZeRO "
                f"(got stage {cfg.zero_optimization_stage}); use the SPMD "
                f"pipeline (GPT2Config.pp_stages) for ZeRO x PP, or stage 0")
        _opt_name = (cfg.optimizer_name or "adam").lower()
        opt_params = cfg.optimizer_params or {}
        if cfg.fp16_enabled:
            _dtype = jnp.float16
        elif cfg.bfloat16_enabled:
            _dtype = jnp.bfloat16
        else:
            _dtype = None
        sched = lr_scheduler
        if sched is None and cfg.scheduler_name is not None:
            sched = lr_schedules.get_lr_schedule(cfg.scheduler_name,
                                                 cfg.scheduler_params)
        engine = PipelineEngine(
            model, kwargs["sample_batch"],
            num_microbatches=max(1, cfg.gradient_accumulation_steps),
            lr=opt_params.get("lr", 1e-3),
            betas=tuple(opt_params.get("betas", (0.9, 0.999))),
            eps=opt_params.get("eps", 1e-8),
            weight_decay=opt_params.get("weight_decay", 0.0),
            seed=kwargs.get("seed", 0),
            dp=_dp,
            optimizer_name=_opt_name,
            compute_dtype=_dtype,
            dynamic_loss_scale=(cfg.fp16_enabled and
                                cfg.fp16.dynamic_loss_scale),
            initial_scale=(cfg.initial_dynamic_scale
                           if cfg.fp16_enabled and cfg.fp16.dynamic_loss_scale
                           else (cfg.loss_scale if cfg.fp16_enabled else 1.0)),
            scale_window=cfg.fp16.loss_scale_window,
            min_scale=cfg.fp16.min_loss_scale,
            hysteresis=cfg.fp16.hysteresis,
            lr_scheduler=sched,
            gradient_clipping=cfg.gradient_clipping,
            curriculum_scheduler=_make_curriculum(cfg))
        return engine, None, None, engine.lr_scheduler

    engine = DeepSpeedEngine(args=args,
                             model=model,
                             optimizer=optimizer,
                             model_parameters=model_parameters,
                             training_data=training_data,
                             lr_scheduler=lr_scheduler,
                             mpu=mpu,
                             dist_init_required=dist_init_required,
                             collate_fn=collate_fn,
                             config=config,
                             config_params=config_params,
                             **kwargs)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model, mp_size=1, mpu=None, checkpoint=None, dtype=None,
                   injection_policy=None, replace_method="auto",
                   quantization_setting=None,
                   replace_with_kernel_inject=False, **kwargs):
    """Create an inference engine (reference: deepspeed.init_inference,
    deepspeed/__init__.py:220)."""
    return InferenceEngine(model, mp_size=mp_size, mpu=mpu,
                           checkpoint=checkpoint, dtype=dtype,
                           injection_dict=injection_policy,
                           replace_method=replace_method,
                           quantization_setting=quantization_setting,
                           replace_with_kernel_inject=replace_with_kernel_inject,
                           **kwargs)


def init_serving(model=None, engine=None, params=None, checkpoint=None,
                 dtype=None, config=None, draft_params=None,
                 draft_scales=None, **kwargs):
    """Create a continuous-batching serving engine (serving/server.py).

    Pass an existing ``InferenceEngine`` via ``engine``, or a model (+
    ``params``/``checkpoint``/``dtype``) and one is built through
    :func:`init_inference`. ``config`` is a ds-config dict whose
    ``serving`` block sizes the paged KV cache and the slot batch.
    ``draft_params`` supplies an explicit small draft model for
    ``serving.speculative`` (omit it to self-draft from the target's
    first layers)."""
    if engine is None:
        assert model is not None, "init_serving needs a model or an engine"
        engine = init_inference(model, params=params, checkpoint=checkpoint,
                                dtype=dtype, **kwargs)
    from deepspeed_tpu.serving.server import ServingEngine
    return ServingEngine(engine, config=config, draft_params=draft_params,
                         draft_scales=draft_scales)


def add_config_arguments(parser):
    """Reference: deepspeed.add_config_arguments (deepspeed/__init__.py:204)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag to ease transition)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated enable flag")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated config path")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI")
    return parser


# public module aliases (reference: deepspeed.zero, deepspeed.checkpointing)
from deepspeed_tpu import zero  # noqa: E402,F401
from deepspeed_tpu.runtime.activation_checkpointing import \
    checkpointing  # noqa: E402,F401

# top-level class exports (reference deepspeed/__init__.py:16-25)
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine  # noqa: E402,F401
from deepspeed_tpu.runtime.pipe.module import (  # noqa: E402,F401
    LayerSpec, PipelineModule, TiedLayerSpec)
from deepspeed_tpu.inference.engine import InferenceEngine  # noqa: E402,F401
from deepspeed_tpu.ops.transformer.transformer import (  # noqa: E402,F401
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
from deepspeed_tpu.module_inject import (  # noqa: E402,F401
    replace_transformer_layer, revert_transformer_layer)
from deepspeed_tpu.runtime.lr_schedules import (  # noqa: E402,F401
    add_tuning_arguments)
