from deepspeed_tpu.elasticity.elasticity import (  # noqa: F401
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
    ElasticityConfig,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
)
