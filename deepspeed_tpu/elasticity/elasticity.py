"""Elastic training configuration math.

Faithful port of deepspeed/elasticity/elasticity.py (candidate batch-size
enumeration :63-175, ``compute_elastic_config`` :226). Pure arithmetic —
ports verbatim to the TPU build, where "GPUs" become chips. Runtime
elasticity (v0.1) is scheduling-time only in the reference too
(SURVEY.md §5.3)."""

import json
import math
import os
import re

from deepspeed_tpu.utils.logging import logger

ELASTICITY = "elasticity"
ENABLED = "enabled"
ENABLED_DEFAULT = False
LATEST_ELASTICITY_VERSION = 0.1
MINIMUM_DEEPSPEED_VERSION = "0.3.8"
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False
# Env var through which a resource scheduler communicates the elastic config
# it used when sizing the job (reference elasticity/constants.py).
DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Reference elasticity/config.py semantics."""

    def __init__(self, param_dict):
        self.enabled = param_dict.get(ENABLED, ENABLED_DEFAULT)
        if self.enabled:
            if "max_train_batch_size" not in param_dict:
                raise ElasticityConfigError(
                    "max_train_batch_size is required when elasticity is "
                    "enabled")
            if "micro_batch_sizes" not in param_dict:
                raise ElasticityConfigError(
                    "micro_batch_sizes is required when elasticity is "
                    "enabled")
        self.max_acceptable_batch_size = param_dict.get(
            "max_train_batch_size", 2000)
        self.micro_batches = param_dict.get("micro_batch_sizes",
                                            [2, 4, 6])
        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"Elasticity expected micro_batch_sizes to be a list of "
                f"micro batches, instead is: {type(self.micro_batches)}, "
                f"containing: {self.micro_batches}")
        if not all(isinstance(m, int) and not isinstance(m, bool)
                   for m in self.micro_batches):
            raise ElasticityConfigError(
                "Elasticity expected micro_batch_sizes to only contain a "
                f"list of integers, instead contains: {self.micro_batches}")
        if not all(m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                "Elasticity expected micro_batch_sizes to only contain "
                f"positive integers, instead contains: {self.micro_batches}")
        if not self.micro_batches:
            raise ElasticityConfigError(
                "Elasticity expected micro_batch_sizes to be non-empty")
        self.min_gpus = param_dict.get("min_gpus", 1)
        self.max_gpus = param_dict.get("max_gpus", 10000)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError(
                "Elasticity min/max chip counts must be > 0, "
                f"given min_gpus: {self.min_gpus}, max_gpus: {self.max_gpus}")
        self.min_time = param_dict.get("min_time", 0)
        self.version = param_dict.get("version", LATEST_ELASTICITY_VERSION)
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch",
                                                       True)
        self.ignore_non_elastic_batch_info = param_dict.get(
            "ignore_non_elastic_batch_info", False)


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """All batch sizes <= max that are a base micro-batch times a highly
    composite multiplier (reference :63)."""
    candidate_batch_size = []
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidate_batch_size.append(base)
        else:
            value = max_acceptable_batch_size // base
            index = next((i for i, x in enumerate(HCN_LIST) if x > value),
                         len(HCN_LIST)) - 1
            candidate_batch_size.append(HCN_LIST[index] * base)
    return list(set(candidate_batch_size))


HCN_LIST = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
            1260, 1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720,
            45360, 50400]


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus,
                   max_valid_gpus):
    """GPU counts that evenly divide batch/micro (reference :91)."""
    valid_gpus = []
    for micro_batch in micro_batches:
        if batch_size % micro_batch == 0:
            max_gpus = batch_size // micro_batch
            if min_valid_gpus <= max_gpus <= max_valid_gpus:
                valid_gpus.append(max_gpus)
            for i in range(1, max_gpus // 2 + 1):
                if max_gpus % i == 0 and min_valid_gpus <= i <= max_valid_gpus:
                    valid_gpus.append(i)
    return sorted(set(valid_gpus))


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus,
                        max_gpus, prefer_larger):
    """(final_batch_size, valid_gpus) maximising GPU coverage
    (reference :114)."""
    max_valid_gpus = 0
    valid_gpus = None
    final_batch_size = int(min(micro_batches))

    for batch_size in candidate_batch_sizes:
        current_valid_gpus = get_valid_gpus(batch_size, micro_batches,
                                            min_gpus, max_gpus)
        if (len(current_valid_gpus) > max_valid_gpus or
                (len(current_valid_gpus) == max_valid_gpus and
                 ((prefer_larger and batch_size > final_batch_size) or
                  (not prefer_larger and batch_size < final_batch_size)))):
            max_valid_gpus = len(current_valid_gpus)
            valid_gpus = current_valid_gpus
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                             min_gpus=None, max_gpus=None,
                             prefer_larger=True):
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ValueError(
            f"All micro batches must be <= {max_acceptable_batch_size}")
    # Bases: each micro batch AND their LCM (reference heuristic :155-160).
    lcm = micro_batches[0]
    for mb in micro_batches[1:]:
        lcm = lcm * mb // math.gcd(lcm, mb)
    base_list = list(micro_batches) + [lcm]
    candidate_batch_sizes = get_candidate_batch_sizes(
        base_list, max_acceptable_batch_size)
    return get_best_candidates(candidate_batch_sizes, micro_batches,
                               min_gpus, max_gpus, prefer_larger)


def elasticity_enabled(ds_config):
    """reference elasticity.py:187."""
    if ELASTICITY not in ds_config:
        return False
    return ds_config[ELASTICITY].get(ENABLED, ENABLED_DEFAULT)


def _version_tuple(v):
    """Leading numeric release segment of a version string; tolerates
    PEP440 suffixes ('0.3.8rc1', '0.4.0+cuda')."""
    m = re.match(r"(\d+(?:\.\d+)*)", str(v))
    if not m:
        raise ElasticityError(f"Unparseable version string: {v!r}")
    t = tuple(int(x) for x in m.group(1).split("."))
    while t and t[-1] == 0:   # 0.1.0 == 0.1
        t = t[:-1]
    return t


def _compatible_ds_version_check(target_deepspeed_version):
    """Target version must be >= MINIMUM_DEEPSPEED_VERSION
    (reference :171-185)."""
    if target_deepspeed_version is None:
        return True
    if _version_tuple(target_deepspeed_version) < \
            _version_tuple(MINIMUM_DEEPSPEED_VERSION):
        raise ElasticityError(
            f"Target deepspeed version of {target_deepspeed_version} is not "
            f"compatible with minimum version {MINIMUM_DEEPSPEED_VERSION} "
            "supporting elasticity.")
    return True


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Check the runtime elastic config matches the one the resource
    scheduler used when sizing the job (reference :193-224): the scheduler
    publishes its copy in the ``DEEPSPEED_ELASTICITY_CONFIG`` env var."""
    if DEEPSPEED_ELASTICITY_CONFIG not in os.environ:
        logger.warning(
            "Unable to find DEEPSPEED_ELASTICITY_CONFIG environment "
            "variable, cannot guarantee resource scheduler will scale this "
            "job using compatible chip counts.")
        return
    scheduler = ElasticityConfig(
        json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG]))
    runtime = ElasticityConfig(runtime_elastic_config_dict)
    for field in ("max_acceptable_batch_size", "micro_batches", "version"):
        sched_v, run_v = getattr(scheduler, field), getattr(runtime, field)
        if field == "version":
            # tolerate float-vs-string JSON representations ('0.1' vs 0.1)
            mismatch = _version_tuple(sched_v) != _version_tuple(run_v)
        else:
            mismatch = sched_v != run_v
        if mismatch:
            raise ElasticityConfigError(
                f"Elastic config '{field}={sched_v}' seen by resource "
                f"scheduler does not match config passed to runtime "
                f"{field}={run_v}")


def compute_elastic_config(ds_config, target_deepspeed_version=None,
                           world_size=0):
    """(final_batch_size, valid_gpus, micro_batch_size-for-world) —
    reference :226."""
    if isinstance(ds_config, str):
        ds_config = json.loads(ds_config)
    if not isinstance(ds_config, dict):
        raise ValueError(
            f"Expected ds_config to be a dictionary but received a "
            f"{type(ds_config)}, containing: {ds_config}")
    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(
            f"'{ELASTICITY}' is missing from config json, please add it if "
            "running an elastic training job.")
    elastic_config_dict = ds_config[ELASTICITY]
    if not elastic_config_dict.get(ENABLED, ENABLED_DEFAULT):
        raise ElasticityConfigError(
            "Elasticity is disabled, please enable it ('enabled':true) if "
            "running an elastic training job.")
    elastic_config = ElasticityConfig(elastic_config_dict)
    if _version_tuple(elastic_config.version) > \
            _version_tuple(LATEST_ELASTICITY_VERSION):
        raise ElasticityConfigError(
            f"Attempting to run elasticity version {elastic_config.version} "
            f"but runtime only supports up to {LATEST_ELASTICITY_VERSION}")
    _compatible_ds_version_check(target_deepspeed_version)

    final_batch_size, valid_gpus = _get_compatible_gpus_v01(
        micro_batches=elastic_config.micro_batches,
        max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
        min_gpus=elastic_config.min_gpus,
        max_gpus=elastic_config.max_gpus,
        prefer_larger=elastic_config.prefer_larger_batch_size)
    final_batch_size = int(final_batch_size)
    if not valid_gpus:
        raise ElasticityConfigError(
            "No valid chip counts satisfy the elasticity config "
            f"(max_train_batch_size={elastic_config.max_acceptable_batch_size}, "
            f"micro_batch_sizes={elastic_config.micro_batches}, "
            f"min_gpus={elastic_config.min_gpus}, "
            f"max_gpus={elastic_config.max_gpus})")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid set {valid_gpus}")
        micro_batch_size = None
        for mbsz in sorted(elastic_config.micro_batches, reverse=True):
            if final_batch_size // world_size % mbsz == 0:
                micro_batch_size = mbsz
                break
        assert micro_batch_size is not None
        return final_batch_size, valid_gpus, micro_batch_size

    return final_batch_size, valid_gpus
