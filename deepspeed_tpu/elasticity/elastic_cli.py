"""``ds_elastic`` CLI (reference bin/ds_elastic): preview elastic
batch-size / chip-count compatibility for a config."""

import argparse
import json

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-c", "--config", required=True,
                        help="DeepSpeed config json with an elasticity block")
    parser.add_argument("-w", "--world-size", type=int, default=0)
    args = parser.parse_args()
    with open(args.config) as f:
        ds_config = json.load(f)
    if args.world_size:
        batch, gpus, micro = compute_elastic_config(
            ds_config, world_size=args.world_size)
        print(f"world size {args.world_size}: train_batch_size={batch}, "
              f"micro_batch={micro}")
    else:
        batch, gpus = compute_elastic_config(ds_config)
        print(f"train_batch_size={batch}")
        print(f"valid chip counts: {gpus}")


if __name__ == "__main__":
    main()
