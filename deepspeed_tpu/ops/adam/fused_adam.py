"""FusedAdam — the Adam update as a single Pallas kernel per shard.

TPU-native equivalent of the reference's multi-tensor Adam
(csrc/adam/multi_tensor_adam.cu + wrapper ops/adam/fused_adam.py:16): one
elementwise kernel reads (p, g, m, v) once from HBM and writes (update, m,
v) — the fused chain the CUDA kernel hand-schedules over 512-element
chunks. Exposed two ways:

* :func:`fused_adam_update` — raw per-tensor kernel;
* :func:`fused_adam` — a runtime ``Optimizer(init, update)`` drop-in that
  the engine selects via config ``optimizer.params.fused=true``; its jnp
  twin (runtime/optim.py:adam) is the default since XLA fuses the same
  chain automatically. Both are parity-tested (test_fused_ops.py) — keep
  whichever profiles faster on your slice.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.runtime import optim as optim_lib

_BLOCK_ROWS = 256
_LANES = 128


def _interpret():
    from deepspeed_tpu.ops._platform import interpret
    return interpret()


def _adam_kernel(s_ref, p_ref, g_ref, m_ref, v_ref,
                 u_ref, mo_ref, vo_ref, *, b1, b2, eps, weight_decay,
                 adam_w_mode):
    lr, bc1, bc2 = s_ref[0, 0], s_ref[0, 1], s_ref[0, 2]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    if not adam_w_mode and weight_decay > 0.0:
        g = g + weight_decay * p
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    u = -lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode and weight_decay > 0.0:
        u = u - lr * weight_decay * p
    u_ref[:] = u.astype(u_ref.dtype)
    mo_ref[:] = m
    vo_ref[:] = v


def fused_adam_update(p, g, m, v, lr, bc1, bc2, *, b1=0.9, b2=0.999,
                      eps=1e-8, weight_decay=0.0, adam_w_mode=True):
    """One fused Adam step for a single tensor; returns (update, m, v).

    lr/bc1/bc2 are traced scalars (LR schedules stay inside jit)."""
    shape, dtype = p.shape, p.dtype
    n = p.size
    width = _BLOCK_ROWS * _LANES
    n_pad = -(-n // width) * width

    def flat(x, fill=0.0):
        xf = jnp.ravel(x)
        return jnp.pad(xf, (0, n_pad - n), constant_values=fill).reshape(
            -1, _LANES)

    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(bc1, jnp.float32),
                      jnp.asarray(bc2, jnp.float32)]).reshape(1, 3)
    rows = n_pad // _LANES
    kernel = functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps,
                               weight_decay=weight_decay,
                               adam_w_mode=adam_w_mode)
    grid = (rows // _BLOCK_ROWS,)
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    u, m_new, v_new = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, 3), lambda i: (0, 0)),
                  blk, blk, blk, blk],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), dtype),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)],
        interpret=_interpret(),
    )(scal, flat(p), flat(g), flat(m), flat(v))

    unflat = lambda x: jnp.ravel(x)[:n].reshape(shape)
    return (unflat(u).astype(dtype), unflat(m_new), unflat(v_new))


def fused_adam(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
               adam_w_mode=True, bias_correction=True):
    """Optimizer pair backed by the Pallas kernel (reference FusedAdam)."""

    def init(params):
        return optim_lib.AdamState(
            step=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            nu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        out = [fused_adam_update(p, g, m, v, lr, bc1, bc2, b1=b1, b2=b2,
                                 eps=eps, weight_decay=weight_decay,
                                 adam_w_mode=adam_w_mode)
               for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        updates = treedef.unflatten([o[0] for o in out])
        mu = treedef.unflatten([o[1] for o in out])
        nu = treedef.unflatten([o[2] for o in out])
        return updates, optim_lib.AdamState(step=step, mu=mu, nu=nu)

    return optim_lib.Optimizer(init, update)


class FusedAdam:
    """API-parity shell of the reference wrapper (ops/adam/fused_adam.py:16);
    construct and pass as ``optimizer=`` to ``initialize``."""

    def __new__(cls, params=None, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                weight_decay=0.0, adam_w_mode=True, bias_correction=True,
                **_):
        return fused_adam(b1=betas[0], b2=betas[1], eps=eps,
                          weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                          bias_correction=bias_correction)
