"""FusedAdam — the Adam update as a single Pallas kernel per shard.

TPU-native equivalent of the reference's multi-tensor Adam
(csrc/adam/multi_tensor_adam.cu + wrapper ops/adam/fused_adam.py:16): one
elementwise kernel reads (p, g, m, v) once from HBM and writes (update, m,
v) — the fused chain the CUDA kernel hand-schedules over 512-element
chunks. Exposed two ways:

* :func:`fused_adam_update` — raw per-tensor kernel;
* :func:`fused_adam` — a runtime ``Optimizer(init, update)`` drop-in that
  the engine selects via config ``optimizer.params.fused=true``; its jnp
  twin (runtime/optim.py:adam) is the default since XLA fuses the same
  chain automatically. Both are parity-tested (test_fused_ops.py) — keep
  whichever profiles faster on your slice.
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.runtime import optim as optim_lib

_BLOCK_ROWS = 256
_LANES = 128


def _interpret():
    from deepspeed_tpu.ops._platform import interpret
    return interpret()


def _adam_kernel(s_ref, p_ref, g_ref, m_ref, v_ref,
                 u_ref, mo_ref, vo_ref, *, b1, b2, eps, weight_decay,
                 adam_w_mode):
    lr, bc1, bc2 = s_ref[0, 0], s_ref[0, 1], s_ref[0, 2]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    if not adam_w_mode and weight_decay > 0.0:
        g = g + weight_decay * p
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    u = -lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode and weight_decay > 0.0:
        u = u - lr * weight_decay * p
    u_ref[:] = u.astype(u_ref.dtype)
    mo_ref[:] = m
    vo_ref[:] = v


def fused_adam_update(p, g, m, v, lr, bc1, bc2, *, b1=0.9, b2=0.999,
                      eps=1e-8, weight_decay=0.0, adam_w_mode=True):
    """One fused Adam step for a single tensor; returns (update, m, v).

    lr/bc1/bc2 are traced scalars (LR schedules stay inside jit)."""
    shape, dtype = p.shape, p.dtype
    n = p.size
    width = _BLOCK_ROWS * _LANES
    n_pad = -(-n // width) * width

    def flat(x, fill=0.0):
        xf = jnp.ravel(x)
        return jnp.pad(xf, (0, n_pad - n), constant_values=fill).reshape(
            -1, _LANES)

    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(bc1, jnp.float32),
                      jnp.asarray(bc2, jnp.float32)]).reshape(1, 3)
    rows = n_pad // _LANES
    kernel = functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps,
                               weight_decay=weight_decay,
                               adam_w_mode=adam_w_mode)
    grid = (rows // _BLOCK_ROWS,)
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    u, m_new, v_new = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, 3), lambda i: (0, 0)),
                  blk, blk, blk, blk],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), dtype),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)],
        interpret=_interpret(),
    )(scal, flat(p), flat(g), flat(m), flat(v))

    unflat = lambda x: jnp.ravel(x)[:n].reshape(shape)
    return (unflat(u).astype(dtype), unflat(m_new), unflat(v_new))


def fused_adam(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
               adam_w_mode=True, bias_correction=True):
    """Optimizer pair backed by the Pallas kernel (reference FusedAdam)."""

    def init(params):
        return optim_lib.AdamState(
            step=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            nu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        out = [fused_adam_update(p, g, m, v, lr, bc1, bc2, b1=b1, b2=b2,
                                 eps=eps, weight_decay=weight_decay,
                                 adam_w_mode=adam_w_mode)
               for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        updates = treedef.unflatten([o[0] for o in out])
        mu = treedef.unflatten([o[1] for o in out])
        nu = treedef.unflatten([o[2] for o in out])
        return updates, optim_lib.AdamState(step=step, mu=mu, nu=nu)

    return optim_lib.Optimizer(init, update)


_SWEEP_PAD = _BLOCK_ROWS * _LANES


def _adam_sweep_kernel(s_ref, p_ref, g_ref, m_ref, v_ref,
                       u_ref, mo_ref, vo_ref, *rest, b1, b2, eps,
                       weight_decay, adam_w_mode, has_cast):
    """One block of the whole-state sweep: clip (scalar coefficient) +
    Adam + optional compute-dtype cast of the updated param, all from a
    single read of (p, g, m, v)."""
    lr, bc1, bc2, cc = (s_ref[0, 0], s_ref[0, 1], s_ref[0, 2], s_ref[0, 3])
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * cc
    if not adam_w_mode and weight_decay > 0.0:
        g = g + weight_decay * p
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    u = -lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode and weight_decay > 0.0:
        u = u - lr * weight_decay * p
    u_ref[:] = u.astype(u_ref.dtype)
    mo_ref[:] = m
    vo_ref[:] = v
    if has_cast:
        c_ref = rest[0]
        c_ref[:] = (p + u).astype(c_ref.dtype)


def adam_sweep_apply(p, g, m, v, lr, bc1, bc2, clip_coef=1.0, *, b1=0.9,
                     b2=0.999, eps=1e-8, weight_decay=0.0,
                     adam_w_mode=True, cast_dtype=None, use_pallas=None):
    """ONE fused pass over the whole flattened state: global-norm clip
    (``g * clip_coef``), the Adam update, and — when ``cast_dtype`` is
    given — the fp32 -> compute-dtype cast of the updated params, from a
    single HBM read of (p, g, m, v). Inputs are FLAT fp32 vectors whose
    length is a multiple of ``_SWEEP_PAD`` (``runtime/optim.flatten_tree``
    with ``pad_to=fused_adam.sweep_pad()`` produces them); lr/bc1/bc2/
    clip_coef are traced scalars. Returns ``(u, m_new, v_new, cast)``
    with ``cast = (p + u).astype(cast_dtype)`` or ``None``.

    ``use_pallas=None`` auto-selects: the Pallas kernel on TPU, the
    bit-identical jnp chain elsewhere — interpreted Pallas is a
    correctness emulator, not a perf path, and XLA fuses the flat chain
    into one loop over contiguous state anyway (which is the whole
    point: the per-tensor :func:`fused_adam_update` lost to XLA as a
    per-bucket dispatch — one launch per leaf)."""
    if use_pallas is None:
        use_pallas = not _interpret()
    cc = jnp.asarray(clip_coef, jnp.float32)
    if not use_pallas:
        gg = g.astype(jnp.float32) * cc
        # p is only touched for weight decay / the cast output — with
        # both off the sweep never reads the params at all (callers may
        # pass a placeholder; see fused_adam_sweep)
        if not adam_w_mode and weight_decay > 0.0:
            gg = gg + weight_decay * p.astype(jnp.float32)
        m_new = b1 * m + (1.0 - b1) * gg
        v_new = b2 * v + (1.0 - b2) * gg * gg
        u = -lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if adam_w_mode and weight_decay > 0.0:
            u = u - lr * weight_decay * p.astype(jnp.float32)
        cast = (p.astype(jnp.float32) + u).astype(cast_dtype) \
            if cast_dtype is not None else None
        return u.astype(p.dtype), m_new, v_new, cast

    n = p.size
    assert n % _SWEEP_PAD == 0, (
        f"adam_sweep_apply: flat length {n} must be a multiple of "
        f"{_SWEEP_PAD} (flatten_tree(pad_to=sweep_pad()))")
    rows = n // _LANES
    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(bc1, jnp.float32),
                      jnp.asarray(bc2, jnp.float32), cc]).reshape(1, 4)
    kernel = functools.partial(
        _adam_sweep_kernel, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, adam_w_mode=adam_w_mode,
        has_cast=cast_dtype is not None)
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    out_shape = [jax.ShapeDtypeStruct((rows, _LANES), p.dtype),
                 jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                 jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)]
    out_specs = [blk, blk, blk]
    if cast_dtype is not None:
        out_shape.append(jax.ShapeDtypeStruct((rows, _LANES), cast_dtype))
        out_specs.append(blk)
    two_d = lambda x: x.reshape(-1, _LANES)
    out = pl.pallas_call(
        kernel,
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[pl.BlockSpec((1, 4), lambda i: (0, 0)),
                  blk, blk, blk, blk],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(scal, two_d(p), two_d(g), two_d(m), two_d(v))
    flat = lambda x: jnp.ravel(x)
    cast = flat(out[3]) if cast_dtype is not None else None
    return flat(out[0]), flat(out[1]), flat(out[2]), cast


def sweep_pad():
    """Flat-vector padding quantum the sweep kernel's blocking needs."""
    return _SWEEP_PAD


class AdamSweepState(NamedTuple):
    """Whole-state sweep moments: ONE contiguous fp32 vector each, padded
    to the kernel's block quantum — the layout that makes the optimizer
    step a single pass instead of a per-leaf dispatch. ZeRO-1 shards the
    flat vectors over the data axis with perfect balance."""
    step: jnp.ndarray
    mu: jnp.ndarray
    nu: jnp.ndarray


def fused_adam_sweep(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                     adam_w_mode=True, bias_correction=True,
                     use_pallas=None):
    """Adam as ONE whole-state sweep (config ``optimizer.params.sweep``:
    true). The per-tensor Pallas :func:`fused_adam` measured SLOWER than
    XLA's fused jnp chain because it dispatches one kernel per leaf;
    this variant flattens params/grads/moments into single contiguous
    vectors (``runtime/optim.flatten_tree``) and fuses global-norm clip
    (``clip_coef`` from the engine's epilogue) + Adam into one pass.
    ``fuses_clip`` is set so the engine skips its separate clip sweep
    over the grad tree. The kernel's fused fp32 -> compute-dtype cast
    output (:func:`adam_sweep_apply` ``cast_dtype=``) is NOT exposed
    here: the ``Optimizer(init, update)`` contract has no consumer for
    it — wiring it through the engine's forward means a TrainState /
    custom_vjp refactor (PERF.md), and computing an output nothing
    reads would be a wasted HBM write per step.

    Parity: bit-identical moments/updates vs :func:`optim_lib.adam` up
    to the association of the flatten (same fp32 chain, same constants);
    pinned in tests/unit/test_fused_ops.py and engine-level at
    fp32/bf16/fp16 in tests/unit/test_comm_overlap.py."""

    def init(params):
        vec, _ = optim_lib.flatten_tree(params, pad_to=_SWEEP_PAD)
        zeros = jnp.zeros_like(vec, jnp.float32)
        return AdamSweepState(step=jnp.zeros([], jnp.int32),
                              mu=zeros, nu=zeros)

    def update(grads, state, params, lr, clip_coef=None):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        flat_g, spec = optim_lib.flatten_tree(grads, pad_to=_SWEEP_PAD)
        # the params only feed weight decay; with it off, skip their
        # whole flatten pass (the grads stand in as a never-read
        # placeholder — DCE'd by XLA)
        flat_p = (optim_lib.flatten_tree(params, pad_to=_SWEEP_PAD)[0]
                  if weight_decay > 0.0 else flat_g)
        cc = jnp.float32(1.0) if clip_coef is None else clip_coef
        u, mu, nu, _ = adam_sweep_apply(
            flat_p, flat_g, state.mu, state.nu, lr, bc1, bc2, cc,
            b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
            adam_w_mode=adam_w_mode, use_pallas=use_pallas)
        updates = optim_lib.unflatten_tree(u, spec)
        return updates, AdamSweepState(step=step, mu=mu, nu=nu)

    return optim_lib.Optimizer(init, update, fuses_clip=True)


class FusedAdam:
    """API-parity shell of the reference wrapper (ops/adam/fused_adam.py:16);
    construct and pass as ``optimizer=`` to ``initialize``."""

    def __new__(cls, params=None, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                weight_decay=0.0, adam_w_mode=True, bias_correction=True,
                **_):
        return fused_adam(b1=betas[0], b2=betas[1], eps=eps,
                          weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                          bias_correction=bias_correction)
