"""DeepSpeedCPUAdam — host-memory Adam for ZeRO-Offload.

Rebuild of ops/adam/cpu_adam.py:13 over the AVX C++ kernel
(csrc/cpu_adam.cpp, reference csrc/adam/cpu_adam.cpp). Operates on numpy
fp32 buffers that live in host RAM (the offloaded optimizer partition);
the swap layer (runtime/swap_tensor/) moves them against device HBM.
"""

import itertools

import numpy as np

from deepspeed_tpu.ops.op_builder.builder import CPUAdamBuilder

_ids = itertools.count()


def _ptr(a: np.ndarray):
    import ctypes
    assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    """step() fuses the whole Adam update in one native call per tensor.

    Matches the reference wrapper surface: construct with param buffers
    (numpy fp32), call ``step(grads)``; state lives host-side."""

    def __init__(self, params, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True, bias_correction=True,
                 fp32_optimizer_states=True):
        self.lib = CPUAdamBuilder().load()
        self.opt_id = next(_ids)
        self.lr = lr
        self.bias_correction = bias_correction
        self.lib.ds_adam_create(self.opt_id, betas[0], betas[1], eps,
                                weight_decay, 1 if adamw_mode else 0)
        self.params = [np.ascontiguousarray(p, dtype=np.float32)
                       for p in params]
        self.exp_avg = [np.zeros_like(p) for p in self.params]
        self.exp_avg_sq = [np.zeros_like(p) for p in self.params]
        self.step_count = 0

    def step(self, grads, lr=None):
        """grads: list of numpy fp32 arrays matching params."""
        self.step_count += 1
        for i, g in enumerate(grads):
            self.step_single(i, g, lr=lr, step_no=self.step_count)
        return self.params

    def step_single(self, idx, grad, lr=None, step_no=None):
        """One tensor's update — the unit the pipelined optimizer swapper
        interleaves with NVMe reads/writes (reference
        pipelined_optimizer_swapper.py)."""
        lr = self.lr if lr is None else lr
        step_no = self.step_count if step_no is None else step_no
        p, m, v = self.params[idx], self.exp_avg[idx], self.exp_avg_sq[idx]
        g = np.ascontiguousarray(grad, dtype=np.float32)
        rc = self.lib.ds_adam_step(self.opt_id, step_no, lr,
                                   _ptr(p), _ptr(g), _ptr(m), _ptr(v),
                                   p.size)
        assert rc == 0, f"ds_adam_step failed ({rc})"

    def __del__(self):
        try:
            self.lib.ds_adam_destroy(self.opt_id)
        except Exception:
            pass


class DeepSpeedCPUAdagrad:
    """ops/adagrad/cpu_adagrad.py equivalent over ds_adagrad_step."""

    def __init__(self, params, lr=1e-2, eps=1e-10, weight_decay=0.0):
        self.lib = CPUAdamBuilder().load()
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.params = [np.ascontiguousarray(p, dtype=np.float32)
                       for p in params]
        self.exp_avg_sq = [np.zeros_like(p) for p in self.params]

    def step(self, grads, lr=None):
        lr = self.lr if lr is None else lr
        for p, g, v in zip(self.params, grads, self.exp_avg_sq):
            g = np.ascontiguousarray(g, dtype=np.float32)
            rc = self.lib.ds_adagrad_step(lr, self.eps, self.weight_decay,
                                          _ptr(p), _ptr(g), _ptr(v), p.size)
            assert rc == 0
        return self.params
