"""Sequence / context parallelism: ring attention and Ulysses.

The reference snapshot's long-sequence story is block-sparse attention
only (SURVEY.md §5.7 — ring attention and DeepSpeed-Ulysses arrive in
later versions); this module builds both as first-class TPU citizens so
the framework covers the scale the lineage grows into:

* :func:`ring_attention` — the sequence dim is sharded over a mesh axis;
  K/V chunks rotate around the ring via ``lax.ppermute`` (ICI
  neighbour-to-neighbour, bandwidth-optimal) while each device's Q stays
  resident. Per-chunk partial results merge by the online-softmax rule
  using each chunk's log-sum-exp, so the math is EXACTLY full attention.
  Causal runs skip chunks entirely above the diagonal via their -inf lse.
* :func:`ulysses_attention` — DeepSpeed-Ulysses: ``all_to_all`` swaps the
  sharded dim from sequence to heads, full-sequence flash attention runs
  per head group, and a second all-to-all swaps back. Requires
  num_heads % axis_size == 0.

Both are pure collectives + the Pallas flash kernel, differentiable end to
end (ppermute/all_to_all transpose to themselves under AD).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.transformer import flash
from deepspeed_tpu.ops.transformer.attention import mha_reference


def _attend_with_lse(q, k, v, causal, sm_scale, use_flash):
    """(out, lse) — lse is [B, H, Sq] fp32."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if use_flash:
        # custom-VJP form: grads flow through BOTH out and lse (the merge
        # weights), so jax.grad of ring attention is exact on TPU
        return flash.flash_attention_with_lse(q, k, v, causal, sm_scale)
    # jnp fallback (CPU tests): replicate the flash math
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        cm = (jnp.arange(sk)[None, :] <=
              jnp.arange(sq)[:, None] + (sk - sq))
        logits = jnp.where(cm[None, None], logits, flash.NEG_INF)
    m = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhqk,bhkd->bhqd", (p / l_safe[..., None]).astype(
        v.dtype), v)
    return out, m + jnp.log(l_safe)


def _merge(o1, lse1, o2, lse2):
    """Online-softmax merge of two partial attention results."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = w1 + w2
    denom = jnp.where(denom == 0.0, 1.0, denom)
    out = (o1.astype(jnp.float32) * w1[..., None] +
           o2.astype(jnp.float32) * w2[..., None]) / denom[..., None]
    return out.astype(o1.dtype), m + jnp.log(denom)


def _ring_attention_local(q, k, v, axis_name, causal, sm_scale, use_flash):
    """Per-device body (inside shard_map): q,k,v are the LOCAL seq chunk
    [B, H, S_local, D]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(s, carry):
        o_acc, lse_acc, kc, vc = carry
        src = (my_idx - s) % axis_size           # owner of current kv chunk

        # chunk relation under causal: src < me → full, == me → causal
        # diagonal, src > me → skipped (lse = -inf zeroes its weight)
        o_s, lse_s = _attend_with_lse(q, kc, vc, False, sm_scale, use_flash)
        if causal:
            o_diag, lse_diag = _attend_with_lse(q, kc, vc, True, sm_scale,
                                                use_flash)
            is_diag = src == my_idx
            skip = src > my_idx
            o_s = jnp.where(is_diag, o_diag, o_s)
            lse_s = jnp.where(is_diag, lse_diag, lse_s)
            lse_s = jnp.where(skip, flash.NEG_INF, lse_s)

        o_acc, lse_acc = _merge(o_acc, lse_acc, o_s, lse_s)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return o_acc, lse_acc, kc, vc

    B, H, S, D = q.shape
    o0 = jnp.zeros((B, H, S, D), q.dtype)
    lse0 = jnp.full((B, H, S), flash.NEG_INF, jnp.float32)
    o, lse, _, _ = jax.lax.fori_loop(0, axis_size, step, (o0, lse0, k, v))
    return o


def ring_attention(q, k, v, mesh: Mesh, axis_name: str, causal=True,
                   sm_scale=None, use_flash=None):
    """Exact attention over a sequence sharded on ``axis_name``.

    q,k,v: GLOBAL [B, H, S, D] arrays (sharded or not — shard_map splits
    the seq dim over the axis). Returns the global [B, H, S, D] output
    with the same sharding."""
    if use_flash is None:
        from deepspeed_tpu.ops._platform import effective_platform
        use_flash = effective_platform() == "tpu"
    spec = P(None, None, axis_name, None)
    fn = functools.partial(_ring_attention_local, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale,
                           use_flash=use_flash)
    return jax.shard_map(
        lambda q, k, v: fn(q, k, v),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)(q, k, v)


def _ulysses_local(q, k, v, axis_name, causal, sm_scale, use_flash):
    """Inside shard_map: [B, H, S_local, D] per device; all-to-all to
    [B, H_local, S, D], attend, all-to-all back."""
    # split heads across the axis, gather sequence
    def a2a_fwd(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def a2a_bwd(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = a2a_fwd(q), a2a_fwd(k), a2a_fwd(v)
    if use_flash:
        out = flash.flash_attention(qh, kh, vh, causal, sm_scale)
    else:
        out = mha_reference(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return a2a_bwd(out)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str, causal=True,
                      sm_scale=None, use_flash=None):
    """DeepSpeed-Ulysses sequence parallelism: all-to-all seq↔heads."""
    if use_flash is None:
        from deepspeed_tpu.ops._platform import effective_platform
        use_flash = effective_platform() == "tpu"
    H = q.shape[1]
    axis_size = mesh.shape[axis_name]
    assert H % axis_size == 0, (
        f"ulysses needs heads ({H}) divisible by axis size ({axis_size})")
    spec = P(None, None, axis_name, None)
    fn = functools.partial(_ulysses_local, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale,
                           use_flash=use_flash)
    return jax.shard_map(
        lambda q, k, v: fn(q, k, v),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)(q, k, v)
