"""Decode attention over a KV cache — the generative-inference hot op.

TPU-native equivalent of the reference's fused KV-cache attention
(`softmax_context_*` in csrc/transformer/inference/csrc/pt_binding.cpp:829
and the attention core of csrc/transformer/inference/csrc/softmax.cu): one
query token per sequence attends to a linear KV cache of valid length
``cache_len``. The reference hand-manages a global KV workspace
(inference/includes/context.h); here the cache is a pair of [B, H, T, D]
jax arrays owned by the model's flax "cache" collection, and this kernel
only reads them.

Design notes (TPU):
* grid over B*H; the single query row is replicated to an (8, D) tile so
  the score GEMM is MXU/VPU tile-aligned (one wasted factor of 8 on a
  bandwidth-bound op — the kernel streams K/V once, which is the actual
  cost at decode time).
* ``cache_len`` arrives in SMEM; the kv loop runs ``cdiv(len, block_k)``
  iterations, so per-token work scales with the *live* cache length, not
  the allocated cache size.
* off-TPU the mathematically identical masked jnp path runs (also the
  parity oracle in tests/unit/test_inference.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.ops._platform import interpret as _interpret
from deepspeed_tpu.ops.transformer.attention import mha_reference

try:  # pltpu imports on TPU-enabled jaxlibs; interpret mode needs no TPU
    from jax.experimental.pallas import tpu as pltpu
    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover
    pltpu = None
    _SMEM = None

NEG_INF = -1e30
QROWS = 8  # sublane tile height; the 1 live query row is replicated into it
BLOCK_K = 512  # kv tile length (sublane dim of the K/V blocks)


def aligned_cache_len(n_positions: int) -> int:
    """Cache allocation size that avoids the per-step pad copy in
    decode_attention: a BLOCK_K multiple when larger than one block, else
    a 16-multiple (one whole block of any sublane-tileable size)."""
    if n_positions > BLOCK_K:
        return -(-n_positions // BLOCK_K) * BLOCK_K
    return -(-n_positions // 16) * 16


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, sm_scale,
                   block_k, quantized=False, ks_ref=None, vs_ref=None):
    length = len_ref[0]
    q = q_ref[0]  # [QROWS, D]

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k.astype(q.dtype),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if quantized:
            # int8 cache: one absmax scale per cached row (the reference's
            # int8 dequant, csrc/transformer/inference/csrc/dequantize.cu)
            # folds into the score/value matmuls column-wise. Scales ride
            # the LANE dim ([1, 1, T] blocks): a [T, 1] layout pads each
            # row to 128 lanes and streams 128x the scale bytes.
            ks = ks_ref[0, 0, pl.ds(j * block_k, block_k)]      # [BK]
            s = s * ks[None, :]
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (QROWS, block_k), 1)
        s = jnp.where(cols < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        if quantized:
            vs = vs_ref[0, 0, pl.ds(j * block_k, block_k)]      # [BK]
            # int8 magnitudes (≤127) are exact in bf16, so the value
            # matmul runs at full bf16 MXU rate like the fp path
            pv = (p * vs[None, :]).astype(jnp.bfloat16)
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                pv, v.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    d = q.shape[-1]
    acc = jnp.zeros((QROWS, d), jnp.float32)
    m = jnp.full((QROWS,), NEG_INF, jnp.float32)
    l = jnp.zeros((QROWS,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, pl.cdiv(length, block_k), body,
                                  (acc, m, l))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, cache_len, *, k_scale=None,
                     v_scale=None, sm_scale=None, use_flash=None):
    """softmax(q·K[:len]ᵀ)·V[:len] for one decode step.

    q: [B, H, 1, D]; k_cache/v_cache: [B, H, T, D] (T = allocated cache);
    cache_len: int32 scalar — or a [B] vector of PER-SEQUENCE valid
    lengths, the continuous-batching form where every slot of the static
    batch sits at its own position (serving/ gathers each slot's pages
    into the contiguous [B, H, T, D] view this op reads). The current
    token's K/V must already be written. With ``k_scale``/``v_scale``
    ([B, H, T] fp32 per-row scales) the caches are int8 and dequant folds
    into the kernel's matmuls (the reference's int8 path,
    csrc/transformer/inference/csrc/dequantize.cu). Returns [B, H, 1, D].
    """
    B, H, Sq, D = q.shape
    assert Sq == 1, f"decode_attention takes one query token, got {Sq}"
    quantized = k_scale is not None
    assert quantized == (v_scale is not None)
    T = k_cache.shape[2]
    lens = jnp.asarray(cache_len, jnp.int32)
    assert lens.ndim in (0, 1), (
        f"cache_len must be a scalar or a [B] vector, got {lens.shape}")
    if lens.ndim == 1:
        assert lens.shape[0] == B, (
            f"per-sequence cache_len has {lens.shape[0]} entries for "
            f"batch {B}")
    if sm_scale is None:
        sm_scale = D ** -0.5
    if use_flash is None:
        from deepspeed_tpu.ops.transformer.attention import _flash_available
        use_flash = _flash_available()
    if not use_flash:
        k, v = k_cache, v_cache
        if quantized:
            k = (k.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
            v = (v.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
        if lens.ndim == 1:
            mask = jnp.arange(T)[None, None, None, :] \
                < lens[:, None, None, None]
        else:
            mask = (jnp.arange(T) < lens)[None, None, None, :]
        return mha_reference(q, k, v, causal=False,
                             sm_scale=sm_scale, mask=mask)

    # pad the cache dim to a block multiple rather than shrinking the
    # block (a tiny divisor of an odd T would serialise the kv loop);
    # padded columns sit beyond cache_len, so the mask already kills them.
    # This copies the whole cache — callers on the hot path should allocate
    # aligned_cache_len(T) so Tp == T and the pad is a no-op (the model's
    # flax cache does; see models/gpt2.py).
    block_k = min(T, BLOCK_K)
    Tp = -(-T // block_k) * block_k
    if Tp != T:
        pad = [(0, 0), (0, 0), (0, Tp - T), (0, 0)]
        k_cache = jnp.pad(k_cache, pad)
        v_cache = jnp.pad(v_cache, pad)
        if quantized:
            pad2 = [(0, 0), (0, 0), (0, Tp - T)]
            k_scale = jnp.pad(k_scale, pad2)
            v_scale = jnp.pad(v_scale, pad2)
    qf = jnp.broadcast_to(q.reshape(B * H, 1, D), (B * H, QROWS, D))
    kf = k_cache.reshape(B * H, Tp, D)
    vf = v_cache.reshape(B * H, Tp, D)
    # one length per (b, h) program: a scalar broadcasts to every program,
    # a [B] vector repeats per head — the kernel body reads len_ref[0]
    # either way, so the per-sequence path costs nothing extra
    if lens.ndim == 1:
        len_arr = jnp.broadcast_to(lens[:, None], (B, H)).reshape(B * H)
    else:
        len_arr = jnp.broadcast_to(lens, (B * H,))

    cache_spec = pl.BlockSpec((1, Tp, D), lambda b: (b, 0, 0))
    scale_spec = pl.BlockSpec((1, 1, Tp), lambda b: (b, 0, 0))
    in_specs = [pl.BlockSpec((1,), lambda b: (b,), memory_space=_SMEM),
                pl.BlockSpec((1, QROWS, D), lambda b: (b, 0, 0)),
                cache_spec, cache_spec]
    operands = [len_arr, qf, kf, vf]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale.reshape(B * H, 1, Tp).astype(jnp.float32),
                     v_scale.reshape(B * H, 1, Tp).astype(jnp.float32)]

        def kernel(len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref):
            _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                           sm_scale=sm_scale, block_k=block_k,
                           quantized=True, ks_ref=ks_ref, vs_ref=vs_ref)
    else:
        kernel = functools.partial(_decode_kernel, sm_scale=sm_scale,
                                   block_k=block_k)

    out = pl.pallas_call(
        kernel,
        grid=(B * H,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, QROWS, D), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, QROWS, D), q.dtype),
        interpret=_interpret(),
    )(*operands)
    return out[:, :1, :].reshape(B, H, 1, D)


# ------------------------------------------------------- int8 KV cache path
def quantize_kv(kv):
    """Per-row absmax int8 quantization of new K/V entries: [B, H, S, D]
    -> (int8 values, fp32 scales [B, H, S]). The reference stores fp16
    KV and int8 weights; an int8 KV cache is the TPU-side extension that
    halves cache HBM (dequant folds into the decode matmuls)."""
    absmax = jnp.max(jnp.abs(kv.astype(jnp.float32)), axis=-1)
    scale = absmax / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(kv.astype(jnp.float32) / safe[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, jnp.where(scale == 0.0, 0.0, safe)


def decode_attention_quantized(q, k_int, k_scale, v_int, v_scale, cache_len,
                               *, sm_scale=None, use_flash=None):
    """softmax(q·dequant(K)[:len]ᵀ)·dequant(V)[:len] over an int8 cache —
    the named entry point for the int8 form of :func:`decode_attention`."""
    return decode_attention(q, k_int, v_int, cache_len, k_scale=k_scale,
                            v_scale=v_scale, sm_scale=sm_scale,
                            use_flash=use_flash)
