"""Multi-head attention ops — dispatcher between the Pallas flash kernel
and a jnp reference.

This is the TPU-native replacement for the reference's fused attention
paths: the softmax/transform kernels inside the training transformer
(``csrc/transformer/softmax_kernels.cu``, ``transform_kernels.cu``) and the
strided-batch-gemm attention core (``csrc/includes/strided_batch_gemm.h``).
On TPU the entire attention block is ONE flash-attention Pallas kernel
(O(seq) memory, online softmax); off-TPU (CPU tests) the mathematically
identical jnp path runs.

Layout convention: ``[batch, heads, seq, head_dim]`` throughout.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def mha_reference(q, k, v, *, causal=True, sm_scale=None, bias=None,
                  mask=None):
    """Plain-XLA attention: the parity oracle and the CPU fallback.

    q,k,v: [B, H, S, D]; bias broadcastable to [B, H, Sq, Sk]; mask is a
    boolean tensor broadcastable to the same (True = keep).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        # offset handles decode where q is a suffix of the kv sequence
        causal_mask = (jnp.arange(sk)[None, :] <=
                       jnp.arange(sq)[:, None] + (sk - sq))
        logits = jnp.where(causal_mask[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


@functools.lru_cache(maxsize=None)
def _flash_importable():
    try:
        from deepspeed_tpu.ops.transformer import flash  # noqa: F401
        return True
    except Exception:
        return False


def _flash_available():
    # effective_platform (not default_backend): code hosted onto the CPU
    # device of a TPU process — e.g. the layered-offload zero_init — must
    # not pick TPU Pallas lowering
    from deepspeed_tpu.ops._platform import effective_platform
    return effective_platform() == "tpu" and _flash_importable()


def _want_flash(seq_k: int, has_bias: bool, has_mask: bool) -> bool:
    """Default impl choice, measured on one v5e-class chip (PERF.md):
    at seq 128 the flash grid degenerates to one tiny block per (b, h)
    program and XLA's fused O(S^2) attention is 1.35x faster end-to-end
    (BERT-large 211 -> 156 ms/step); at seq 1024 flash wins (GPT-2
    headline). Crossover set at 512 where the fp32 logits buffer also
    starts to matter. ``DS_ATTN_IMPL=flash|xla`` overrides."""
    import os
    impl = os.environ.get("DS_ATTN_IMPL", "").lower()
    if impl == "xla":
        return False
    if impl == "flash":
        return True
    return seq_k >= 512 and not has_bias and not has_mask


def attention(q, k, v, *, causal=True, sm_scale=None, bias=None, mask=None,
              use_flash: Optional[bool] = None):
    """Dispatch: Pallas flash kernel on TPU (long seq), jnp/XLA reference
    otherwise.

    ``use_flash`` forces one path (tests use False for the oracle); env
    ``DS_ATTN_IMPL=flash|xla`` overrides the measured default in
    :func:`_want_flash`."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if use_flash is None:
        use_flash = _flash_available() and _want_flash(
            k.shape[2], bias is not None, mask is not None)
    if use_flash:
        if bias is not None or mask is not None:
            raise ValueError(
                "the flash kernel has no bias/mask input; drop "
                "DS_ATTN_IMPL=flash / use_flash=True for masked attention")
        from deepspeed_tpu.ops.transformer import flash
        return flash.flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                         bias=bias, mask=mask)
