"""Rotary position embeddings (RoPE).

TPU-native equivalent of the reference inference kernel
``apply_rotary_pos_emb`` (csrc/transformer/inference/csrc/
apply_rotary_pos_emb.cu, binding pt_binding.cpp:829 surface): rotates
each (even, odd) feature pair of Q and K by a position-dependent angle.
Pure jnp — the op is elementwise + a tiny trig table, which XLA fuses
into the surrounding QKV projection; a bespoke kernel would only add
launch overhead on TPU.

Layout: [B, H, S, D] (D even); ``offset`` positions the block inside a
longer sequence (the decode case: offset = cache length so generated
tokens continue the rotation).
"""

import jax.numpy as jnp


def rotary_tables(seq_len, dim, base=10000.0, offset=0, dtype=jnp.float32):
    """(cos, sin) tables [S, D/2] for positions offset..offset+S.
    ``offset`` may be a traced scalar (decode: the live cache length)."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, dim, 2,
                                          dtype=jnp.float32) / dim))
    pos = jnp.arange(seq_len, dtype=jnp.float32) + offset
    ang = pos[:, None] * inv_freq[None, :]               # [S, D/2]
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def apply_rotary_pos_emb(q, k, offset=0, base=10000.0, rotary_dim=None):
    """Rotate q and k (reference apply_rotary_pos_emb).

    Uses the INTERLEAVED-pair convention of original RoPE / GPT-J: pairs
    are (x[2i], x[2i+1]). GPT-NeoX's half-split layout (x[i], x[i+D/2])
    requires a feature permutation before/after. With ``rotary_dim`` only
    the leading features rotate (partial rotary). Returns (q_rot, k_rot)
    in the input dtype."""
    B, H, S, D = q.shape
    rd = rotary_dim or D
    assert rd % 2 == 0, f"rotary dim must be even, got {rd}"
    cos, sin = rotary_tables(S, rd, base=base, offset=offset)

    def rot(x):
        xr, rest = x[..., :rd], x[..., rd:]
        x1 = xr[..., 0::2].astype(jnp.float32)
        x2 = xr[..., 1::2].astype(jnp.float32)
        c = cos[None, None]
        s = sin[None, None]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        out = jnp.stack([o1, o2], axis=-1).reshape(x1.shape[:-1] +
                                                   (rd,)).astype(x.dtype)
        return jnp.concatenate([out, rest], axis=-1) if rest.shape[-1] \
            else out

    return rot(q), rot(k)
