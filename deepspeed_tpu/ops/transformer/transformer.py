"""DeepSpeedTransformerLayer — the fused BERT-style encoder layer.

Rebuild of the reference's flagship training kernel: ops/transformer/
transformer.py (``DeepSpeedTransformerConfig`` :39,
``DeepSpeedTransformerLayer`` :460) over csrc/transformer/
ds_transformer_cuda.cpp (templated BertTransformerLayer: cublas GEMMs +
fused LN/softmax/dropout/gelu kernels, pre/post-LN variants,
attn-dropout checkpointing, stochastic rounding mode). On TPU the layer
composes the Pallas ops (flash attention, fused_layer_norm,
fused_bias_gelu) and lets XLA fuse the rest; `normalize_invertible`/
`attn_dropout_checkpoint`/`gelu_checkpoint` memory knobs map onto a
``jax.checkpoint`` wrapper over the layer.

Numerically parity-tested against a plain flax encoder layer
(tests/unit/test_transformer_layer.py — the analogue of
test_cuda_forward/backward.py's DeepSpeedTransformerLayer-vs-HF sweep).
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer.int8_linear import (QuantDense,
                                                     int8_matmul)
from deepspeed_tpu.ops.transformer.attention import attention
from deepspeed_tpu.ops.transformer.fused import (fused_bias_gelu,
                                                 fused_layer_norm)


@dataclasses.dataclass(frozen=True)
class DeepSpeedTransformerConfig:
    """Reference config surface (ops/transformer/transformer.py:39)."""
    batch_size: int = -1
    hidden_size: int = 768
    intermediate_size: int = -1          # -1 → 4*hidden
    heads: int = 12
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False   # memory knob → remat
    gelu_checkpoint: bool = False        # memory knob → remat
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True

    @property
    def intermediate(self):
        return (self.intermediate_size if self.intermediate_size > 0
                else 4 * self.hidden_size)

    @property
    def wants_remat(self):
        return (self.normalize_invertible or self.gelu_checkpoint or
                self.attn_dropout_checkpoint)


class DeepSpeedTransformerLayer(nn.Module):
    """One fused encoder layer (reference :460): self-attention + MLP with
    pre- or post-LN, fused kernels on the elementwise hot spots."""
    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 deterministic=True):
        cfg = self.config

        def layer(x, mask):
            H = cfg.hidden_size
            nh = cfg.heads
            hd = H // nh
            B, S, _ = x.shape
            init = nn.initializers.normal(cfg.initializer_range)

            ln1_g = self.param("attn_ln_gamma", nn.initializers.ones, (H,))
            ln1_b = self.param("attn_ln_beta", nn.initializers.zeros, (H,))
            ln2_g = self.param("ln_gamma", nn.initializers.ones, (H,))
            ln2_b = self.param("ln_beta", nn.initializers.zeros, (H,))

            inp = x
            if cfg.pre_layer_norm:
                attn_in = fused_layer_norm(x, ln1_g, ln1_b,
                                           cfg.layer_norm_eps)
            else:
                attn_in = x

            qkv = QuantDense(3 * H, name="attn_qkv",
                             kernel_init=init)(attn_in)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
            k = k.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
            v = v.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
            ctx = attention(q, k, v, causal=False, mask=mask)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, H)
            attn_out = QuantDense(H, name="attn_out", kernel_init=init)(ctx)
            if cfg.attn_dropout_ratio > 0:
                attn_out = nn.Dropout(cfg.attn_dropout_ratio)(
                    attn_out, deterministic=deterministic)

            x = inp + attn_out
            if not cfg.pre_layer_norm:
                x = fused_layer_norm(x, ln1_g, ln1_b, cfg.layer_norm_eps)

            mlp_in = (fused_layer_norm(x, ln2_g, ln2_b, cfg.layer_norm_eps)
                      if cfg.pre_layer_norm else x)
            inter_kernel = self.param("inter_w", init,
                                      (H, cfg.intermediate))
            inter_bias = self.param("inter_b", nn.initializers.zeros,
                                    (cfg.intermediate,))
            if inter_kernel.dtype == jnp.int8:
                # module_quantize stored inter_w as int8 with its
                # per-column scale at this module's scope (raw param, so
                # the scale leaf lands beside it as 'kernel_scale')
                if not self.has_variable("quant_scales", "kernel_scale"):
                    raise ValueError(
                        "DeepSpeedTransformerLayer: int8 inter_w but no "
                        "'quant_scales'/'kernel_scale' variable — pass the "
                        "scales tree from module_quantize alongside params")
                inter_scale = self.get_variable("quant_scales",
                                                "kernel_scale")
                h = fused_bias_gelu(
                    int8_matmul(mlp_in, inter_kernel, inter_scale),
                    inter_bias)
            else:
                h = fused_bias_gelu(mlp_in @ inter_kernel, inter_bias)
            out = QuantDense(H, name="output_w", kernel_init=init)(h)
            if cfg.hidden_dropout_ratio > 0:
                out = nn.Dropout(cfg.hidden_dropout_ratio)(
                    out, deterministic=deterministic)
            x = x + out
            if not cfg.pre_layer_norm:
                x = fused_layer_norm(x, ln2_g, ln2_b, cfg.layer_norm_eps)
            return x

        if cfg.wants_remat:
            layer = nn.remat(layer)
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] padding mask → broadcastable [B, 1, 1, S] boolean
            attention_mask = attention_mask[:, None, None, :].astype(bool)
        return layer(hidden_states, attention_mask)


def transformer_tp_rules(prefix=r".*"):
    """Megatron TP rules for this layer's params."""
    from jax.sharding import PartitionSpec as P
    return [
        (prefix + r"attn_qkv/kernel", P(None, "model")),
        (prefix + r"attn_qkv/bias", P("model",)),
        (prefix + r"attn_out/kernel", P("model", None)),
        (prefix + r"inter_w", P(None, "model")),
        (prefix + r"inter_b", P("model",)),
        (prefix + r"output_w/kernel", P("model", None)),
    ]
