"""Flash attention — Pallas TPU kernels with a custom VJP.

The TPU-native replacement for the reference's attention core: the
softmax kernels (csrc/transformer/softmax_kernels.cu), the attention-score
strided-batch GEMMs (csrc/includes/strided_batch_gemm.h) and the attn
``attn_dropout_checkpoint`` memory knobs of the fused transformer layer
(csrc/transformer/ds_transformer_cuda.cpp). Online-softmax tiling keeps
memory O(seq) instead of O(seq^2) — the kernel never materialises the
[S, S] score matrix, which is what lets the TPU build run the long-context
configs (SURVEY.md §5.7) densely where the reference needed block-sparsity.

Layout: [batch, heads, seq, head_dim]; fp32 accumulators in VMEM. TWO
kernel forms per pass, dispatched on sequence length (_use_streaming):
resident (≤ 4096: full K/V staged per program, causal skip via the loop
bound — ~11% faster at 1024) and streaming (beyond: K/V blocks stream
through the innermost grid axis with scratch accumulators — O(block)
VMEM, unbounded seq; the resident form VMEM-OOMs at 8192).

All kernels run in interpret mode off-TPU so CPU tests exercise the same
code path bit-for-bit (tests/unit/test_flash.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlibs; interpret mode needs no
    # TPU — only the STREAMING kernels (VMEM scratch) require it
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def _require_pltpu():
    if pltpu is None:  # pragma: no cover — guarded import above
        raise RuntimeError(
            "the streaming flash kernels (seq > 4096) need "
            "jax.experimental.pallas.tpu for VMEM scratch accumulators; "
            "this jaxlib cannot import it")


from deepspeed_tpu.ops._platform import interpret as _interpret

NEG_INF = -1e30
LANES = 8  # replication width for per-row stats (lse/delta) — see _fwd_kernel


def _apply_causal_mask(s, row0, col0, block_q, block_k, offset):
    """Mask score block s ([BQ, BK] at rows row0.., cols col0..) so row r
    only attends keys <= r + offset (offset = Sk - Sq, decode suffix)."""
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return jnp.where(cols <= rows + offset, s, NEG_INF)


# --------------------------------------------------------------------- forward
#
# All three kernels STREAM their long axis through the grid (kv blocks
# for fwd/dq, q blocks for dkv) with fp32 VMEM scratch accumulators that
# persist across the innermost grid axis — so per-program VMEM is
# O(block), independent of sequence length. The previous design staged
# the full K/V (resp. Q) per program, which VMEM-OOMed at seq 8192.
# Causal blocks entirely above the diagonal skip their compute via
# pl.when (the block fetch still pipelines — bandwidth, not FLOPs).
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale, causal, block_q, block_k, num_kv, offset):
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: kv block j intersects rows [qi*BQ, (qi+1)*BQ) only if its
    # first key column is <= the block's last row + offset
    live = (j * block_k <= (qi + 1) * block_q - 1 + offset) \
        if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # [BQ, D] native dtype — bf16 operands keep the MXU
        # at full rate; accumulation is f32 via preferred_element_type
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qi * block_q, j * block_k,
                                   block_q, block_k, offset)

        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(j == num_kv - 1)
    def _finalize():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse is replicated over LANES trailing lanes so the 2D-per-row
        # value satisfies the TPU (8, 128)-tile constraint (same trick as
        # jax's own flash kernel, which pads to 128; 8 keeps it small)
        lse_ref[0] = jnp.broadcast_to(
            (m_ref[:, 0] + jnp.log(l_safe))[:, None], (block_q, LANES))


# -------------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc_ref, *, sm_scale, causal, block_q, block_k, num_kv,
               offset):
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    live = (j * block_k <= (qi + 1) * block_q - 1 + offset) \
        if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0:1]      # [BQ, 1] (lane-replicated stats)
        delta = delta_ref[0, :, 0:1]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qi * block_q, j * block_k,
                                   block_q, block_k, offset)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, sm_scale, causal,
                block_q, block_k, num_q, offset):
    kj = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # causal: q block i reaches kv block kj only if its last row + offset
    # is >= the kv block's first key column
    live = ((i + 1) * block_q - 1 + offset >= kj * block_k) \
        if causal else True

    @pl.when(live)
    def _compute():
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0:1]      # [BQ, 1]
        delta = delta_ref[0, :, 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, i * block_q, kj * block_k,
                                   block_q, block_k, offset)
        p = jnp.exp(s - lse)                                # [BQ, BK]
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


# ---------------- resident variants (seq <= _RESIDENT_MAX_SEQ) -----------
# The full K/V (resp. Q) is staged in VMEM per program and the kv loop
# runs inside the kernel with the causal loop-bound skip. ~11% faster
# than the streaming form at seq 1024 (no revisit bubbles, true FLOP
# skip), but VMEM is O(seq) so it caps out; measured good through 4096.

def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_q, block_k, seq_k, offset):
    qi = pl.program_id(1)
    q = q_ref[0]  # [BQ, D] native dtype — bf16 operands keep the MXU at
    # full rate; accumulation is f32 via preferred_element_type

    num_kv = pl.cdiv(seq_k, block_k)
    if causal:
        # last kv block that intersects rows [qi*BQ, (qi+1)*BQ) after the
        # decode suffix offset (q rows map to keys [0, row + offset])
        num_kv = jnp.minimum(num_kv,
                             pl.cdiv((qi + 1) * block_q + offset, block_k))

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qi * block_q, j * block_k,
                                   block_q, block_k, offset)

        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    d = q.shape[-1]
    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc, m, l))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse is replicated over LANES trailing lanes so the 2D-per-row value
    # satisfies the TPU (8, 128)-tile constraint (same trick as jax's own
    # flash kernel, which pads to 128; 8 keeps the buffer small)
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l_safe))[:, None],
                                  (block_q, LANES))


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               sm_scale, causal, block_q, block_k, seq_k, offset):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0:1]      # [BQ, 1] (lane-replicated stats)
    delta = delta_ref[0, :, 0:1]

    num_kv = pl.cdiv(seq_k, block_k)
    if causal:
        num_kv = jnp.minimum(num_kv,
                             pl.cdiv((qi + 1) * block_q + offset, block_k))

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qi * block_q, j * block_k,
                                   block_q, block_k, offset)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k,
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jnp.zeros(q.shape, jnp.float32)
    dq = jax.lax.fori_loop(0, num_kv, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, sm_scale, causal, block_q, block_k, seq_q,
                offset):
    kj = pl.program_id(1)
    k = k_ref[0]  # [BK, D]
    v = v_ref[0]

    num_q = pl.cdiv(seq_q, block_q)
    start_q = jnp.int32(0)
    if causal:
        # first q block whose last key index (row + offset) reaches kj*BK
        start_q = jnp.maximum(kj * block_k - offset, 0) // block_q

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), 0:1]      # [BQ, 1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, i * block_q, kj * block_k,
                                   block_q, block_k, offset)
        p = jnp.exp(s - lse)                                # [BQ, BK]
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(ds.astype(q.dtype), q,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ------------------------------------------------------------------ dispatch
def _pick_block(seq, streaming=False, target=None):
    if target is None:
        import os
        # measured defaults: 512 for the resident kernels (round-2
        # sweep), 1024 for streaming — bigger blocks amortise the
        # revisit bubbles (seq 8192: 68 -> 90.9 TFLOPS; 2048 VMEM-OOMs).
        # DS_FLASH_BLOCK overrides for sweeps.
        target = int(os.environ.get("DS_FLASH_BLOCK",
                                    "1024" if streaming else "512"))
    b = min(seq, target)
    while seq % b:
        b //= 2
    return max(b, 1)


# Above this many keys/queries the resident kernels' O(seq) VMEM staging
# no longer fits (measured: 4096 good, 8192 OOMs the 16 MB VMEM) and the
# O(block)-VMEM streaming kernels take over (~11% slower at 1024, but
# unbounded in seq). DS_FLASH_STREAM=1 forces streaming everywhere.
_RESIDENT_MAX_SEQ = 4096


def _use_streaming(Sq, Sk):
    import os
    if os.environ.get("DS_FLASH_STREAM", "") == "1":
        return True
    return max(Sq, Sk) > _RESIDENT_MAX_SEQ


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=True, sm_scale=None):
    out, _ = _flash_fwd(q, k, v, causal, sm_scale)
    return out


def _flash_fwd(q, k, v, causal, sm_scale):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    stream = _use_streaming(Sq, Sk)
    bq, bk = _pick_block(Sq, stream), _pick_block(Sk, stream)
    qf = q.reshape(B * H, Sq, D)
    kf = k.reshape(B * H, Sk, D)
    vf = v.reshape(B * H, Sk, D)

    if not stream:
        kernel = functools.partial(
            _fwd_kernel_resident, sm_scale=sm_scale, causal=causal,
            block_q=bq, block_k=bk, seq_k=Sk, offset=Sk - Sq)
        o, lse = pl.pallas_call(
            kernel,
            grid=(B * H, Sq // bq),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, bq, LANES), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
                jax.ShapeDtypeStruct((B * H, Sq, LANES), jnp.float32),
            ],
            interpret=_interpret(),
        )(qf, kf, vf)
        out = o.reshape(B, H, Sq, D)
        return out, (q, k, v, out, lse)

    _require_pltpu()
    num_kv = Sk // bk
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=bq, block_k=bk, num_kv=num_kv,
                               offset=Sk - Sq)
    o, lse = pl.pallas_call(
        kernel,
        # kv blocks stream through the innermost grid axis; the scratch
        # accumulators carry across it and the output block (same (b, i)
        # for every j) is written on the last visit
        grid=(B * H, Sq // bq, num_kv),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(qf, kf, vf)
    out = o.reshape(B, H, Sq, D)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, res, g, g_lse=None):
    q, k, v, out, lse = res
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    stream = _use_streaming(Sq, Sk)
    bq, bk = _pick_block(Sq, stream), _pick_block(Sk, stream)

    qf = q.reshape(B * H, Sq, D)
    kf = k.reshape(B * H, Sk, D)
    vf = v.reshape(B * H, Sk, D)
    dof = g.reshape(B * H, Sq, D)
    # delta = rowsum(do * o): the softmax-jacobian correction term,
    # lane-replicated like lse. A direct lse cotangent (ring attention's
    # merge weights differentiate through lse) folds in exactly here:
    # dL/ds_ij = p_ij (dp_ij - delta_i + g_lse_i), since dlse_i/ds_ij=p_ij.
    delta_rows = jnp.sum(
        dof.astype(jnp.float32) *
        out.reshape(B * H, Sq, D).astype(jnp.float32),
        axis=-1, keepdims=True)
    if g_lse is not None:
        delta_rows = delta_rows - g_lse.reshape(B * H, Sq, 1)
    delta = jnp.broadcast_to(delta_rows, (B * H, Sq, LANES))

    if not stream:
        dq = pl.pallas_call(
            functools.partial(
                _dq_kernel_resident, sm_scale=sm_scale, causal=causal,
                block_q=bq, block_k=bk, seq_k=Sk, offset=Sk - Sq),
            grid=(B * H, Sq // bq),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, bq, LANES), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, bq, LANES), lambda b, i: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            interpret=_interpret(),
        )(qf, kf, vf, dof, lse, delta)
        dk, dv = pl.pallas_call(
            functools.partial(
                _dkv_kernel_resident, sm_scale=sm_scale, causal=causal,
                block_q=bq, block_k=bk, seq_q=Sq, offset=Sk - Sq),
            grid=(B * H, Sk // bk),
            in_specs=[
                pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, Sq, D), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, Sq, LANES), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, Sq, LANES), lambda b, j: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
                jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype),
            ],
            interpret=_interpret(),
        )(qf, kf, vf, dof, lse, delta)
        return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
                dv.reshape(B, H, Sk, D))

    _require_pltpu()
    num_kv = Sk // bk
    num_q = Sq // bq
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=bq, block_k=bk, num_kv=num_kv,
                          offset=Sk - Sq),
        grid=(B * H, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=bq, block_k=bk, num_q=num_q,
                          offset=Sk - Sq),
        # q blocks stream through the innermost axis per kv block
        grid=(B * H, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(qf, kf, vf, dof, lse, delta)

    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


flash_attention.defvjp(lambda q, k, v, causal, sm_scale:
                       _flash_fwd(q, k, v, causal, sm_scale),
                       _flash_bwd)


# ------------------------------------------- (out, lse) differentiable form
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_with_lse(q, k, v, causal=True, sm_scale=None):
    """Flash attention returning ``(out, lse)`` with lse [B, H, Sq] fp32,
    differentiable in BOTH outputs — the building block ring attention's
    online-softmax merge needs (its chunk weights are functions of lse)."""
    (out, lse), _ = _flash_fwd_lse(q, k, v, causal, sm_scale)
    return out, lse


def _flash_fwd_lse(q, k, v, causal, sm_scale):
    out, res = _flash_fwd(q, k, v, causal, sm_scale)
    B, H, Sq, _ = q.shape
    lse = res[4][:, :, 0].reshape(B, H, Sq)
    return (out, lse), res


def _flash_bwd_lse(causal, sm_scale, res, g):
    g_out, g_lse = g
    return _flash_bwd(causal, sm_scale, res, g_out, g_lse=g_lse)


flash_attention_with_lse.defvjp(
    lambda q, k, v, causal, sm_scale: _flash_fwd_lse(q, k, v, causal,
                                                     sm_scale),
    _flash_bwd_lse)
