"""Fused elementwise transformer kernels — LayerNorm and bias-GeLU.

TPU-native equivalents of the reference's fused CUDA elementwise kernels:
LayerNorm fwd/bwd (csrc/transformer/normalize_kernels.cu, 2121 LoC),
fused bias+GeLU (csrc/transformer/gelu_kernels.cu) and the bias+dropout+
residual kernels (dropout_kernels.cu). On GPU these exist to avoid extra
HBM round-trips between elementwise stages; XLA already fuses elementwise
chains into neighbouring ops, so the honest TPU design is: provide the
kernels as explicit Pallas ops for the `deepspeed.ops` API-parity surface
AND as the building blocks the DeepSpeedTransformerLayer uses, while the
flax model path simply relies on XLA fusion. Both paths are parity-tested
against each other (tests/unit/test_fused_ops.py).

Row layout: inputs are [..., hidden]; kernels grid over row blocks.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


from deepspeed_tpu.ops._platform import interpret as _interpret


def _row_block(n_rows, hidden, budget_bytes=2 << 20):
    """Rows per block, bounded so one fp32 block stays within a VMEM
    budget — Pallas double-buffers every in/out block, so unbounded
    (rows, hidden) tiles blow the ~16 MiB scoped VMEM at large hidden
    (e.g. the 4096-wide BERT-large MLP)."""
    target = max(1, budget_bytes // (4 * hidden))
    # floor to a power of two so power-of-two row counts divide cleanly
    # (682 -> 512, not a halving cascade down to 2)
    target = 1 << (target.bit_length() - 1)
    b = min(n_rows, target)
    while n_rows % b:
        b //= 2
    return max(b, 1)


# ------------------------------------------------------------------ layer norm
def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rs_ref, *, eps, lanes):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mu) * rstd * g_ref[0].astype(jnp.float32) \
        + b_ref[0].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mu_ref[:] = jnp.broadcast_to(mu, (x.shape[0], lanes))
    rs_ref[:] = jnp.broadcast_to(rstd, (x.shape[0], lanes))


def _ln_bwd_kernel(x_ref, g_ref, mu_ref, rs_ref, dy_ref, dx_ref, *, lanes):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    mu = mu_ref[:, 0:1]
    rstd = rs_ref[:, 0:1]
    xhat = (x - mu) * rstd
    wdy = dy * g
    c1 = jnp.mean(xhat * wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy, axis=-1, keepdims=True)
    dx_ref[:] = ((wdy - c1 * xhat - c2) * rstd).astype(dx_ref.dtype)


LANES = 8


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, gamma, beta, eps=1e-5):
    """LayerNorm over the last dim as one Pallas kernel (reference
    normalize_kernels.cu fused LN). Differentiable via custom VJP."""
    return _ln_fwd(x, gamma, beta, eps)[0]


def _ln_fwd(x, gamma, beta, eps):
    orig_shape = x.shape
    h = orig_shape[-1]
    xf = x.reshape(-1, h)
    n = xf.shape[0]
    bn = _row_block(n, h)
    y, mu, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps, lanes=LANES),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x.dtype),
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(xf, gamma.reshape(1, h), beta.reshape(1, h))
    return y.reshape(orig_shape), (xf, gamma, mu, rstd, orig_shape)


def _ln_fwd_vjp(x, gamma, beta, eps):
    y, res = _ln_fwd(x, gamma, beta, eps)
    return y, res


def _ln_bwd(eps, res, dy):
    xf, gamma, mu, rstd, orig_shape = res
    h = xf.shape[-1]
    dyf = dy.reshape(-1, h)
    n = xf.shape[0]
    bn = _row_block(n, h)
    dx = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, lanes=LANES),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((bn, LANES), lambda i: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i: (i, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), xf.dtype),
        interpret=_interpret(),
    )(xf, gamma.reshape(1, h), mu, rstd, dyf)

    # param grads are plain reductions — XLA fuses them with the kernel's
    # consumers; no bespoke kernel needed (they're bandwidth-trivial)
    xf32 = xf.astype(jnp.float32)
    xhat = (xf32 - mu[:, 0:1]) * rstd[:, 0:1]
    dyf32 = dyf.astype(jnp.float32)
    dgamma = jnp.sum(dyf32 * xhat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(dyf32, axis=0).astype(gamma.dtype)
    return dx.reshape(orig_shape), dgamma, dbeta


fused_layer_norm.defvjp(_ln_fwd_vjp, _ln_bwd)


# ------------------------------------------------------------------- bias gelu
def _bias_gelu_kernel(x_ref, b_ref, y_ref):
    x = x_ref[:].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    # tanh-approx gelu — matches the reference gelu_kernels.cu polynomial
    y = 0.5 * x * (1.0 + jnp.tanh(0.7978845608028654 *
                                  (x + 0.044715 * x * x * x)))
    y_ref[:] = y.astype(y_ref.dtype)


def _bias_gelu_fwd_impl(x, bias):
    orig_shape = x.shape
    h = orig_shape[-1]
    xf = x.reshape(-1, h)
    n = xf.shape[0]
    bn = _row_block(n, h)
    y = pl.pallas_call(
        _bias_gelu_kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
        interpret=_interpret(),
    )(xf, bias.reshape(1, h))
    return y.reshape(orig_shape)


@jax.custom_vjp
def fused_bias_gelu(x, bias):
    """gelu(x + bias) as one kernel (reference gelu_kernels.cu)."""
    return _bias_gelu_fwd_impl(x, bias)


def _bias_gelu_fwd(x, bias):
    return _bias_gelu_fwd_impl(x, bias), (x, bias)


def _bias_gelu_bwd(res, dy):
    x, bias = res
    xb = x.astype(jnp.float32) + bias.astype(jnp.float32)
    t = jnp.tanh(0.7978845608028654 * (xb + 0.044715 * xb ** 3))
    dg = 0.5 * (1.0 + t) + 0.5 * xb * (1.0 - t * t) * \
        0.7978845608028654 * (1.0 + 3 * 0.044715 * xb * xb)
    dx = (dy.astype(jnp.float32) * dg).astype(x.dtype)
    reduce_axes = tuple(range(x.ndim - 1))
    dbias = jnp.sum(dy.astype(jnp.float32) * dg,
                    axis=reduce_axes).astype(bias.dtype)
    return dx, dbias


fused_bias_gelu.defvjp(_bias_gelu_fwd, _bias_gelu_bwd)


# ----------------------------------- small fused inference ops (API parity)
def bias_residual_add(x, bias, residual):
    """x + bias + residual (reference ``bias_residual_*``,
    pt_binding.cpp:829 surface). Elementwise — XLA fuses it into the
    producing matmul; exposed for the deepspeed.ops parity surface."""
    return x + bias + residual


def residual_add(hidden, residual, attention_output=None, mp_size=1):
    """The injected-inference residual merge (reference ``residual_add``):
    hidden + residual (+ attention_output/mp_size when the tensor-sliced
    layer defers the attention branch's allreduce)."""
    out = hidden + residual
    if attention_output is not None:
        out = out + attention_output / mp_size
    return out


def moe_res_matmul(residual, coef, output):
    """MoS residual mixing (reference ``moe_res_matmul``): out = output *
    coef2 + residual * coef1 with coef [..., 2]."""
    return output * coef[..., 1:2] + residual * coef[..., 0:1]


# ------------------------------------------------- fused softmax (API parity)
def _softmax_kernel(x_ref, y_ref, *, scale):
    x = x_ref[:].astype(jnp.float32) * scale
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    y_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def fused_softmax(x, scale=1.0):
    """Scaled softmax over the last dim (reference softmax_kernels.cu).
    The training path uses flash attention instead; this op exists for the
    `deepspeed.ops` parity surface and the injected inference layer."""
    orig_shape = x.shape
    h = orig_shape[-1]
    xf = x.reshape(-1, h)
    n = xf.shape[0]
    bn = _row_block(n, h)
    y = pl.pallas_call(
        functools.partial(_softmax_kernel, scale=scale),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bn, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
        interpret=_interpret(),
    )(xf)
    return y.reshape(orig_shape)
