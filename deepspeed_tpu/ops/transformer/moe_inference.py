"""MoE inference transformer layer.

API-parity surface for the reference's
deepspeed/ops/transformer/inference/moe_inference.py
(``DeepSpeedMoEInference``, 468 LoC): one decoder layer whose MLP is a
mixture of experts, usable with a KV cache at generation time. On TPU the
fused-CUDA plumbing (cublas workspaces, softmax_context kernels,
moe_res_matmul) is replaced by this package's compiled layer stack:
Pallas decode attention + the GShard MoE layer sharded over the mesh
expert axis — the same modules the MoE-GPT2 flagship trains with, so
injected inference layers load training checkpoints directly.
"""

import dataclasses
from typing import Optional

import flax.linen as nn


@dataclasses.dataclass(frozen=True)
class DeepSpeedMoEInferenceConfig:
    """Reference moe_inference.py config surface (the knobs that exist on
    TPU; fp16/q_int8 become the engine-level dtype/quantization)."""
    hidden_size: int
    heads: int
    num_experts: int = 1
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    moe_type: str = "standard"     # "residual" = MoS residual MoE
    max_out_tokens: int = 2048     # KV-cache ceiling (reference knob)
    epsilon: float = 1e-5
    n_layer_for_init: int = 12     # proj init scale denominator
    kv_cache_dtype: str = "auto"
    use_flash: bool = True


class DeepSpeedMoEInference(nn.Module):
    """Decoder layer: ln -> (KV-cache) causal attention -> ln -> MoE FFN,
    with residuals. ``decode=True`` enables the flax cache-collection
    protocol (prefill + one-token steps), matching the reference's
    softmax_context KV-cache attention path."""
    config: DeepSpeedMoEInferenceConfig

    @nn.compact
    def __call__(self, x, deterministic=True, decode=False):
        from deepspeed_tpu.models.gpt2 import (CausalSelfAttention,
                                               GPT2Config)
        from deepspeed_tpu.moe.layer import MoE
        cfg = self.config
        # the attention block reuses the flagship implementation; only the
        # fields it reads are populated
        attn_cfg = GPT2Config(
            vocab_size=1, n_positions=cfg.max_out_tokens,
            n_embd=cfg.hidden_size,
            n_layer=cfg.n_layer_for_init, n_head=cfg.heads,
            kv_cache_dtype=cfg.kv_cache_dtype, use_flash=cfg.use_flash)
        x = x + CausalSelfAttention(attn_cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.epsilon, name="ln_1")(x),
            deterministic, decode)
        h = nn.LayerNorm(epsilon=cfg.epsilon, name="ln_2")(x)
        B, S, E = h.shape
        out, l_aux, _ = MoE(
            hidden_size=E,
            num_experts=cfg.num_experts,
            k=cfg.k,
            capacity_factor=cfg.capacity_factor,
            eval_capacity_factor=cfg.eval_capacity_factor,
            min_capacity=cfg.min_capacity,
            noisy_gate_policy=cfg.noisy_gate_policy,
            drop_tokens=cfg.drop_tokens,
            use_rts=cfg.use_rts,
            use_residual=(cfg.moe_type == "residual"),
            name="moe")(h.reshape(B * S, E), train=not deterministic)
        return x + out.reshape(B, S, E)
