"""Fused block-sparse flash attention — LUT-driven streaming Pallas kernels.

The reference shipped block-sparse attention as a *performance* feature —
up to 6.3x faster and 10-16x longer sequences than its dense attention
(docs/_posts/2020-09-09-sparse-attention.md:28-33, triton LUT kernels in
deepspeed/ops/sparse_attention/matmul.py:13) — while this repo's first two
TPU strategies (predicated sweep, gather-then-dense) ran 2-3x SLOWER than
the repo's own dense flash. This third strategy fuses the static layout
LUT into the streaming flash pipeline:

- each (batch*head) program walks a FLATTENED work list of live
  (q-tile, kv-tile) pairs; the tile indices come from scalar-prefetched
  SMEM LUTs read inside the BlockSpec index_maps, so the pipeline's DMA
  engine fetches exactly the live blocks from HBM — no packed K/V
  materialisation (the gathered impl's cost), no dead-block fetches (the
  predicated impl's cost), and no per-row padding steps (the work list
  is exactly the live pairs, plus one dummy item per empty row so every
  output tile is written);
- compute tiles are MXU-sized (bq x bkc, default 512 x 1024 — the
  measured optimum at block 128, PERF.md) regardless
  of the layout's fine block size; fine-block liveness inside a coarse
  tile is a bit-packed int32 per work item, expanded in-register to a
  score mask (<= 32 fine blocks per coarse tile by construction);
- per-program VMEM is O(tile) via scratch accumulators that reset at
  each q-tile run boundary (begin/end flags), so sequence length is
  unbounded;
- "global" kv columns — attended by (nearly) every row, the killer of
  coarse-tile sparsity in Fixed/BigBird/Longformer layouts — are
  gathered into a contiguous packed region appended after the real
  sequence and fed through the SAME kernel as coarse-dense tiles (the
  per-head bit-masks carry partial liveness; causality in the packed
  region is exact at block level because its diagonal blocks stay in
  the real region). Global ROWS (few) are computed densely in XLA and
  overwrite their output rows.

Wall-clock therefore scales with the layout's live-pair count. Backward
runs the same scheme: dq sweeps the row-major work list, dk/dv sweep the
column-major (transposed) one. The compiler stores per-step block
indices in SMEM (~1 MB), which bounds TOTAL work items per kernel to
~10-20k — the flattened list keeps real layouts far under that.
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

try:  # pltpu imports on TPU-enabled jaxlibs; interpret mode still uses the
    # same code path on CPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from deepspeed_tpu.ops._platform import interpret as _interpret

NEG_INF = -1e30
LANES = 8
_MAX_BITS = 32   # fine blocks per (q-tile, kv-tile) pair — one int32 word
_F_LIVE = 1      # flags: this step does real work
_F_BEGIN = 2     # flags: first step of its output-tile run (reset scratch)
_F_END = 4       # flags: last step of its run (write the output tile)


def _require_pltpu():
    if pltpu is None:  # pragma: no cover — guarded import above
        raise RuntimeError(
            "fused block-sparse attention needs jax.experimental.pallas.tpu "
            "(scalar prefetch + VMEM scratch); this jaxlib cannot import it")


# ---------------------------------------------------------------- LUT builder
def _largest_divisor_leq(n, x):
    for d in range(min(n, max(x, 1)), 0, -1):
        if n % d == 0:
            return d
    return 1


def _tile_geometry(nq, nk, blk):
    """Pick (rq, c): fine blocks per compute tile in the q / kv dims.

    bq = rq*blk must divide Sq, bkc = c*blk must divide Skv, and
    rq*c <= 32 so the fine mask of one (q-tile, kv-tile) pair packs into
    one int32."""
    # measured on one v5e chip at seq 8192 blk 128 (PERF.md): (512, 1024)
    # = 1.4-1.5x over dense flash; the rq*c <= 32 budget loop shrinks the
    # kv tile automatically for smaller fine blocks
    bq_target = int(os.environ.get("DS_SPARSE_BQ", "512"))
    bkc_target = int(os.environ.get("DS_SPARSE_BKC", "1024"))
    rq = _largest_divisor_leq(nq, max(1, bq_target // blk))
    c = _largest_divisor_leq(nk, max(1, bkc_target // blk))
    while rq * c > _MAX_BITS:
        if rq >= c and rq > 1:
            rq = _largest_divisor_leq(nq, rq // 2)
        elif c > 1:
            c = _largest_divisor_leq(nk, c // 2)
        else:  # pragma: no cover — rq == c == 1 satisfies the budget
            break
    return rq, c


def _pack_bits(fm, rq, c):
    """[rq, c] bool fine-mask -> one uint32 (bit r*c+cc = fm[r, cc])."""
    b = 0
    for r in range(rq):
        for cc in range(c):
            if fm[r, cc]:
                b |= 1 << (r * c + cc)
    return np.uint32(b)


def _flatten_work(layv, transpose):
    """Build the flattened per-head work list.

    layv: [H, nqc, rq, nkc, c] bool fine layout viewed at tile
    granularity. Returns (own, other, bits, flags, W): own[h, w] is the
    OUTPUT tile index (q tile for fwd/dq, kv tile for dkv), other[h, w]
    the streamed tile; runs over the same output tile are consecutive
    and bracketed by BEGIN/END flags. Output tiles with no live pair get
    one dummy non-LIVE item so their (zero) output is still written.
    Heads with fewer items are padded with non-LIVE repeats of their
    last item (repeat indices = no data movement)."""
    H, nqc, rq, nkc, c = layv.shape
    clive = layv.any(axis=(2, 4))                    # [H, nqc, nkc]
    if transpose:
        clive = clive.transpose(0, 2, 1)             # [H, nkc, nqc]
    n_own = clive.shape[1]
    per_head = []
    for h in range(H):
        items = []                                   # (own, other, bits)
        for i in range(n_own):
            js = np.nonzero(clive[h, i])[0]
            if len(js) == 0:
                items.append((i, 0, np.uint32(0), _F_BEGIN | _F_END))
                continue
            for t, j in enumerate(js):
                fm = (layv[h, j, :, i, :] if transpose
                      else layv[h, i, :, j, :])
                fl = _F_LIVE
                if t == 0:
                    fl |= _F_BEGIN
                if t == len(js) - 1:
                    fl |= _F_END
                items.append((i, j, _pack_bits(fm, rq, c), fl))
        per_head.append(items)
    W = max(len(it) for it in per_head)
    own = np.zeros((H, W), np.int32)
    other = np.zeros((H, W), np.int32)
    bits = np.zeros((H, W), np.uint32)
    flags = np.zeros((H, W), np.int32)
    for h, items in enumerate(per_head):
        for w, (i, j, bb, fl) in enumerate(items):
            own[h, w], other[h, w], bits[h, w], flags[h, w] = i, j, bb, fl
        for w in range(len(items), W):               # tail padding
            own[h, w] = items[-1][0]
            other[h, w] = items[-1][1]
    return own, other, bits.view(np.int32), flags, W


# ------------------------------------------------------------------- kernels
def _fine_mask(s, bits, blk, c, bq, bkc):
    """Apply the bit-packed fine-block mask to score tile s [bq, bkc]."""
    if bq == blk and bkc == blk:
        return s  # one fine block per tile — tile liveness IS the work list
    rows_f = jax.lax.broadcasted_iota(jnp.int32, (bq, bkc), 0)
    cols_f = jax.lax.broadcasted_iota(jnp.int32, (bq, bkc), 1)
    shift = (rows_f // blk) * c + (cols_f // blk)
    live = (jnp.right_shift(bits, shift) & 1) == 1
    return jnp.where(live, s, NEG_INF)


def _scores(q, k, qi, kj, bits, *, sm_scale, causal, blk, c, bq, bkc,
            causal_ntiles):
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    s = _fine_mask(s, bits, blk, c, bq, bkc)
    if causal:
        # packed global-column tiles (kj >= causal_ntiles) carry their
        # causality at block level in the work-list bits — the positional
        # triangle only applies to real-sequence tiles
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkc), 0)
        cols = kj * bkc + jax.lax.broadcasted_iota(jnp.int32, (bq, bkc), 1)
        s = jnp.where((cols <= rows) | (kj >= causal_ntiles), s, NEG_INF)
    return s


def _fwd_kernel(qi_ref, kj_ref, bits_ref, flags_ref, kpm_ref, q_ref, k_ref,
                v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *, sm_scale,
                causal, blk, c, bq, bkc, H, has_bias, causal_ntiles):
    b = pl.program_id(0)
    w = pl.program_id(1)
    h = b % H
    fl = flags_ref[h, w]

    @pl.when(fl & _F_BEGIN != 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(fl & _F_LIVE != 0)
    def _compute():
        s = _scores(q_ref[0], k_ref[0], qi_ref[h, w], kj_ref[h, w],
                    bits_ref[h, w], sm_scale=sm_scale, causal=causal,
                    blk=blk, c=c, bq=bq, bkc=bkc,
                    causal_ntiles=causal_ntiles)
        if has_bias:
            s = s + kpm_ref[0:1, :]
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        # rows whose every key so far is layout/causal-masked keep
        # m_new == NEG_INF; exp(s - m_new) would be exp(0) == 1 there,
        # so clamp their weights to zero explicitly
        p = jnp.where((m_new <= NEG_INF / 2)[:, None], 0.0,
                      jnp.exp(s - m_new[:, None]))
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(fl & _F_END != 0)
    def _finalize():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            (m_ref[:, 0] + jnp.log(l_safe))[:, None], (bq, LANES))


def _dq_kernel(qi_ref, kj_ref, bits_ref, flags_ref, kpm_ref, q_ref, k_ref,
               v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref, *,
               sm_scale, causal, blk, c, bq, bkc, H, has_bias,
               causal_ntiles):
    b = pl.program_id(0)
    w = pl.program_id(1)
    h = b % H
    fl = flags_ref[h, w]

    @pl.when(fl & _F_BEGIN != 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    @pl.when(fl & _F_LIVE != 0)
    def _compute():
        do = do_ref[0]
        lse = lse_ref[0, :, 0:1]
        delta = delta_ref[0, :, 0:1]
        k = k_ref[0]
        s = _scores(q_ref[0], k, qi_ref[h, w], kj_ref[h, w],
                    bits_ref[h, w], sm_scale=sm_scale, causal=causal,
                    blk=blk, c=c, bq=bq, bkc=bkc,
                    causal_ntiles=causal_ntiles)
        if has_bias:
            s = s + kpm_ref[0:1, :]
        # rows with NO live key have lse == NEG_INF; exp(s - lse) would be
        # exp(0) for their masked scores — clamp to zero
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(fl & _F_END != 0)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(kj_ref, qi_ref, bits_ref, flags_ref, kpm_ref, q_ref, k_ref,
                v_ref, do_ref, lse_ref, delta_ref, *refs, sm_scale, causal,
                blk, c, bq, bkc, H, has_bias, causal_ntiles):
    if has_bias:
        # the additive key-padding bias is a differentiable input: emit
        # its per-(batch*head, key) cotangent as a third output
        (dk_ref, dv_ref, dkpb_ref,
         dk_acc_ref, dv_acc_ref, dkpb_acc_ref) = refs
    else:
        dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = refs
        dkpb_acc_ref = None
    b = pl.program_id(0)
    w = pl.program_id(1)
    h = b % H
    fl = flags_ref[h, w]

    @pl.when(fl & _F_BEGIN != 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)
        if has_bias:
            dkpb_acc_ref[...] = jnp.zeros_like(dkpb_acc_ref)

    @pl.when(fl & _F_LIVE != 0)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0:1]
        delta = delta_ref[0, :, 0:1]
        s = _scores(q, k_ref[0], qi_ref[h, w], kj_ref[h, w],
                    bits_ref[h, w], sm_scale=sm_scale, causal=causal,
                    blk=blk, c=c, bq=bq, bkc=bkc,
                    causal_ntiles=causal_ntiles)
        if has_bias:
            s = s + kpm_ref[0:1, :]
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dsig = p * (dp - delta)     # dL/d(score incl bias): the bias grad
        ds = dsig * sm_scale
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if has_bias:
            dkpb_acc_ref[0, :] = dkpb_acc_ref[0, :] + jnp.sum(dsig, axis=0)

    @pl.when(fl & _F_END != 0)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)
        if has_bias:
            dkpb_ref[0] = dkpb_acc_ref[0, :]


# ---------------------------------------------------------------- public API
class _FusedSparse:
    """One compiled strategy for one (layout, block, causal, tiles) key.

    Holds the numpy work lists and exposes ``attend(q, k, v, kpb)`` — a
    custom-VJP function whose forward/backward all run the LUT-driven
    streaming kernels."""

    def __init__(self, lay, blk, causal, sm_scale, causal_nblocks=None):
        """lay [H, nq, nk] may be RECTANGULAR (nk > nq): kv columns past
        ``causal_nblocks`` fine blocks are packed global columns whose
        causality is already encoded at block level in the layout (the
        positional triangle only applies to the real-sequence prefix)."""
        H, nq, nk = lay.shape
        Sq, Skv = nq * blk, nk * blk
        self.blk, self.causal, self.sm_scale = blk, causal, sm_scale
        self.H, self.Sq, self.Skv = H, Sq, Skv
        rq, c = _tile_geometry(nq, nk, blk)
        if causal_nblocks is None:
            causal_nblocks = nk
        if causal_nblocks != nk:
            # the real/packed boundary must fall on a coarse-tile edge
            c = _largest_divisor_leq(math.gcd(nk, causal_nblocks), c)
        self.bq, self.bkc = rq * blk, c * blk
        self.rq, self.c = rq, c
        assert causal_nblocks % c == 0, (causal_nblocks, c)
        self.causal_ntiles = causal_nblocks // c
        layv = lay.reshape(H, nq // rq, rq, nk // c, c)
        self.nqc, self.nkc = nq // rq, nk // c
        # work lists stay NUMPY: converting here under an active jit trace
        # would cache tracers in this (trace-outliving) object; numpy
        # operands are staged fresh at each pallas_call instead
        (self.qi, self.kj, self.bits,
         self.flags, self.W) = _flatten_work(layv, transpose=False)
        (self.tkj, self.tqi, self.tbits,
         self.tflags, self.Wt) = _flatten_work(layv, transpose=True)
        clive = layv.any(axis=(2, 4))
        self.coarse_density = float(clive.mean())
        self._warned_steps = False

        @jax.custom_vjp
        def attend(q, k, v, kpb):
            out, _ = self._fwd(q, k, v, kpb)
            return out

        attend.defvjp(lambda q, k, v, kpb: self._fwd_res(q, k, v, kpb),
                      functools.partial(self._bwd_impl, with_lse=False))
        self.attend = attend

        @jax.custom_vjp
        def attend_lse(q, k, v, kpb):
            out, lse = self._fwd(q, k, v, kpb)
            B = q.shape[0]
            return out, lse[:, :, 0].reshape(B, self.H, self.Sq)

        def _fwd_res_lse(q, k, v, kpb):
            out, lse = self._fwd(q, k, v, kpb)
            B = q.shape[0]
            pub = lse[:, :, 0].reshape(B, self.H, self.Sq)
            return (out, pub), (q, k, v, kpb, out, lse)

        attend_lse.defvjp(_fwd_res_lse,
                          functools.partial(self._bwd_impl, with_lse=True))
        self.attend_lse = attend_lse

    # kpm helper: the bias block rides the SAME dynamic index as k/v.
    # Prefetch-ref argument order at the index_map is (own, other, bits,
    # flags) = (qi, kj, ...) for fwd/dq and (kj, qi, ...) for dkv — the
    # STREAMED tile is ref index `stream_ref` in both.
    def _kpm(self, kpb, B, kv_is_stream):
        if kpb is None:
            arr = jnp.zeros((1, self.bkc), jnp.float32)
            spec = pl.BlockSpec((1, self.bkc), lambda b, w, *refs: (0, 0))
            return arr, spec, False
        arr = jnp.asarray(kpb, jnp.float32)
        assert arr.shape == (B, self.Skv), (arr.shape, (B, self.Skv))
        H = self.H
        if kv_is_stream:
            spec = pl.BlockSpec(
                (1, self.bkc),
                lambda b, w, own, other, bits, flags:
                (b // H, other[b % H, w]))
        else:
            spec = pl.BlockSpec(
                (1, self.bkc),
                lambda b, w, own, other, bits, flags:
                (b // H, own[b % H, w]))
        return arr, spec, True

    def _specs(self):
        """BlockSpecs shared by the kernels: `own`-indexed q-side tiles
        and `other`-indexed streamed tiles (fwd/dq), or vice versa."""
        H, bq, bkc, D = self.H, self.bq, self.bkc, self._D
        own_q = pl.BlockSpec(
            (1, bq, D),
            lambda b, w, own, other, bits, flags: (b, own[b % H, w], 0))
        own_qstat = pl.BlockSpec(
            (1, bq, LANES),
            lambda b, w, own, other, bits, flags: (b, own[b % H, w], 0))
        own_kv = pl.BlockSpec(
            (1, bkc, D),
            lambda b, w, own, other, bits, flags: (b, own[b % H, w], 0))
        oth_kv = pl.BlockSpec(
            (1, bkc, D),
            lambda b, w, own, other, bits, flags: (b, other[b % H, w], 0))
        oth_q = pl.BlockSpec(
            (1, bq, D),
            lambda b, w, own, other, bits, flags: (b, other[b % H, w], 0))
        oth_qstat = pl.BlockSpec(
            (1, bq, LANES),
            lambda b, w, own, other, bits, flags: (b, other[b % H, w], 0))
        return own_q, own_qstat, own_kv, oth_kv, oth_q, oth_qstat

    def _fwd(self, q, k, v, kpb):
        _require_pltpu()
        B, H, Sq, D = q.shape
        Skv = k.shape[2]
        assert (H, Sq, Skv) == (self.H, self.Sq, self.Skv), (
            (H, Sq, Skv), (self.H, self.Sq, self.Skv))
        self._D = D
        # Mosaic stores per-step block indices in SMEM (~1 MB): a work
        # list past ~20k total steps will die inside the compiler with an
        # opaque SMEM OOM — explain it here first
        total = B * H * (2 * self.W + self.Wt)
        if total > 20000 and not self._warned_steps:
            self._warned_steps = True
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "fused block-sparse attention: %d total grid steps "
                "(batch %d x heads %d x work lists %d/%d) may exceed the "
                "~1 MB SMEM budget for pipeline block indices; if compile "
                "fails with 'Ran out of memory in memory space smem', use "
                "a denser tile geometry (DS_SPARSE_BQ/DS_SPARSE_BKC), a "
                "bigger sparse block, or DS_SPARSE_IMPL=gathered",
                total, B, H, self.W, self.Wt)
        sm_scale = self.sm_scale if self.sm_scale is not None else D ** -0.5
        bq, bkc = self.bq, self.bkc
        qf = q.reshape(B * H, Sq, D)
        kf = k.reshape(B * H, Skv, D)
        vf = v.reshape(B * H, Skv, D)
        kpm, kpm_spec, has_bias = self._kpm(kpb, B, kv_is_stream=True)
        own_q, own_qstat, _, oth_kv, _, _ = self._specs()
        kernel = functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=self.causal,
            blk=self.blk, c=self.c, bq=bq, bkc=bkc, H=H,
            has_bias=has_bias, causal_ntiles=self.causal_ntiles)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(B * H, self.W),
            in_specs=[kpm_spec, own_q, oth_kv, oth_kv],
            out_specs=[own_q, own_qstat],
            scratch_shapes=[
                pltpu.VMEM((bq, D), jnp.float32),
                pltpu.VMEM((bq, LANES), jnp.float32),
                pltpu.VMEM((bq, LANES), jnp.float32),
            ],
        )
        o, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
                jax.ShapeDtypeStruct((B * H, Sq, LANES), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=_interpret(),
        )(self.qi, self.kj, self.bits, self.flags, kpm, qf, kf, vf)
        return o.reshape(B, H, Sq, D), lse

    def _fwd_res(self, q, k, v, kpb):
        out, lse = self._fwd(q, k, v, kpb)
        return out, (q, k, v, kpb, out, lse)

    def _bwd_impl(self, res, g, with_lse=False):
        if with_lse:
            g, g_lse = g
        else:
            g_lse = None
        _require_pltpu()
        q, k, v, kpb, out, lse = res
        B, H, Sq, D = q.shape
        Skv = k.shape[2]
        self._D = D
        sm_scale = self.sm_scale if self.sm_scale is not None else D ** -0.5
        bq, bkc = self.bq, self.bkc
        qf = q.reshape(B * H, Sq, D)
        kf = k.reshape(B * H, Skv, D)
        vf = v.reshape(B * H, Skv, D)
        dof = g.reshape(B * H, Sq, D)
        # softmax-jacobian correction; a direct lse cotangent folds in
        # exactly here (dL/ds_ij = p_ij (dp_ij - delta_i + g_lse_i)),
        # same identity flash.py's _flash_bwd uses
        delta_rows = jnp.sum(
            dof.astype(jnp.float32) *
            out.reshape(B * H, Sq, D).astype(jnp.float32),
            axis=-1, keepdims=True)
        if g_lse is not None:
            delta_rows = delta_rows - g_lse.reshape(B * H, Sq, 1)
        delta = jnp.broadcast_to(delta_rows, (B * H, Sq, LANES))

        own_q, own_qstat, own_kv, oth_kv, oth_q, oth_qstat = self._specs()
        kpm, kpm_spec, has_bias = self._kpm(kpb, B, kv_is_stream=True)
        dq_kernel = functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=self.causal,
            blk=self.blk, c=self.c, bq=bq, bkc=bkc, H=H,
            has_bias=has_bias, causal_ntiles=self.causal_ntiles)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(B * H, self.W),
            in_specs=[kpm_spec, own_q, oth_kv, oth_kv, own_q, own_qstat,
                      own_qstat],
            out_specs=own_q,
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        )
        dq = pl.pallas_call(
            dq_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=_interpret(),
        )(self.qi, self.kj, self.bits, self.flags, kpm, qf, kf, vf, dof,
          lse, delta)

        kpm2, kpm2_spec, _ = self._kpm(kpb, B, kv_is_stream=False)
        dkv_kernel = functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=self.causal,
            blk=self.blk, c=self.c, bq=bq, bkc=bkc, H=H,
            has_bias=has_bias, causal_ntiles=self.causal_ntiles)
        H_ = H
        own_bias = pl.BlockSpec(
            (1, bkc),
            lambda b, w, own, other, bits, flags: (b, own[b % H_, w]))
        out_specs = [own_kv, own_kv] + ([own_bias] if has_bias else [])
        out_shape = [
            jax.ShapeDtypeStruct((B * H, Skv, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Skv, D), v.dtype),
        ] + ([jax.ShapeDtypeStruct((B * H, Skv), jnp.float32)]
             if has_bias else [])
        scratch = [
            pltpu.VMEM((bkc, D), jnp.float32),
            pltpu.VMEM((bkc, D), jnp.float32),
        ] + ([pltpu.VMEM((LANES, bkc), jnp.float32)] if has_bias else [])
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(B * H, self.Wt),
            in_specs=[kpm2_spec, oth_q, own_kv, own_kv, oth_q, oth_qstat,
                      oth_qstat],
            out_specs=out_specs,
            scratch_shapes=scratch,
        )
        outs = pl.pallas_call(
            dkv_kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=_interpret(),
        )(self.tkj, self.tqi, self.tbits, self.tflags, kpm2, qf, kf, vf,
          dof, lse, delta)
        if has_bias:
            dk, dv, dkpb_bh = outs
            # the bias is shared across the heads of a batch element
            dkpb = dkpb_bh.reshape(B, H, Skv).sum(axis=1).astype(kpb.dtype)
        else:
            dk, dv = outs
            dkpb = None
        return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Skv, D),
                dv.reshape(B, H, Skv, D), dkpb)


_strategy_cache = {}
_replicate_warned = set()


def _get_strategy(layout, block, causal, sm_scale, causal_nblocks=None):
    import hashlib
    lay = np.asarray(layout) != 0
    # digest, not raw bytes: sweeps over seq lengths / configs would
    # otherwise retain multi-MB layout keys for the process lifetime
    key = (hashlib.sha256(lay.tobytes()).digest(), lay.shape, block,
           causal, sm_scale, causal_nblocks,
           os.environ.get("DS_SPARSE_BQ", ""),
           os.environ.get("DS_SPARSE_BKC", ""))
    if key not in _strategy_cache:
        _strategy_cache[key] = _FusedSparse(lay, block, causal, sm_scale,
                                            causal_nblocks=causal_nblocks)
    return _strategy_cache[key]


# -------------------------------------------------- layout decomposition
#
# Real layouts (Fixed/BigBird/BSLongformer) are "band + global": a few kv
# columns attended by (nearly) every row and a few q rows attending
# (nearly) everything, over a local band. The global columns make every
# COARSE kv tile live, which erases the kernel's sparsity win (and blows
# the SMEM work-list budget). So the split path PACKS the global columns
# after the real sequence (a few-MB gather) and feeds them through the
# SAME kernel as coarse-dense tiles; global ROWS (few) are computed
# densely in XLA and overwrite their output rows. The decomposition is
# exact for ANY choice of global sets because every part carries its own
# block mask.

def _decompose_layout(lay, causal, col_thresh=0.75, row_thresh=0.75):
    """lay [H, nq, nk] bool -> (gr rows, gc cols, remainder layout).

    A column j is global when its mean liveness over the rows causality
    permits (r >= j when causal) exceeds col_thresh IN ANY HEAD; rows
    symmetrically. Remainder = lay with global rows/cols zeroed."""
    H, nq, nk = lay.shape
    if causal:
        tri = np.tril(np.ones((nq, nk), bool))          # r >= j
        denom_c = np.maximum(tri.sum(axis=0), 1)        # rows >= j
        colness = (lay & tri).sum(axis=1) / denom_c     # [H, nk]
        denom_r = np.maximum(tri.sum(axis=1), 1)        # cols <= r
        rowness = (lay & tri).sum(axis=2) / denom_r     # [H, nq]
    else:
        colness = lay.mean(axis=1)
        rowness = lay.mean(axis=2)
    gc = np.nonzero((colness >= col_thresh).any(axis=0))[0]
    gr = np.nonzero((rowness >= row_thresh).any(axis=0))[0]
    rem = lay.copy()
    rem[:, :, gc] = False
    rem[:, gr, :] = False
    return gr, gc, rem


def _masked_dense_part(q, kg, vg, block_mask, col_ids, row_ids, causal,
                       kpb, sm_scale):
    """Dense masked attention of q rows vs a gathered key subset, with
    per-part normalization: returns (out, lse).

    q [B,H,R,D]; kg/vg [B,H,G,D]; block_mask [H,R,G] bool (element-
    expanded layout); col_ids/row_ids [G]/[R] original token positions
    (causal masking); kpb [B,G] additive bias or None."""
    s = jnp.einsum("bhrd,bhgd->bhrg", q, kg,
                   preferred_element_type=jnp.float32) * sm_scale
    mask = jnp.asarray(block_mask)[None]
    if causal:
        cm = np.asarray(col_ids)[None, :] <= np.asarray(row_ids)[:, None]
        mask = mask & jnp.asarray(cm)[None, None]
    s = jnp.where(mask, s, NEG_INF)
    if kpb is not None:
        s = s + kpb[:, None, None, :]
    m = jnp.max(s, axis=-1)
    # fully-masked rows: zero weights, lse stays NEG_INF
    p = jnp.where((m <= NEG_INF / 2)[..., None], 0.0,
                  jnp.exp(s - m[..., None]))
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhrg,bhgd->bhrd", (p / l_safe[..., None]).astype(
        vg.dtype), vg, preferred_element_type=jnp.float32).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return out, lse


def _expand_mask(bm, blk):
    """[H, nq, g] block mask -> [H, nq*blk, g*blk] element mask."""
    H, nq, g = bm.shape
    return np.broadcast_to(
        bm[:, :, None, :, None], (H, nq, blk, g, blk)).reshape(
            H, nq * blk, g * blk)


def parse_sparse_mode(mode):
    """'sparse' or 'sparse:<window_tokens>/<block>' -> (window, block).

    ONE home for the defaults (1024/128 — the measured long-seq optimum,
    PERF.md) so the model wiring and bench flop accounting can never
    disagree on what layout a mode string means."""
    bad = ValueError(
        f"sparse attention mode {mode!r}: expected 'sparse' or "
        "'sparse:<window_tokens>/<block>' (e.g. 'sparse:1024/128')")
    if mode == "sparse":
        return 1024, 128
    if not mode.startswith("sparse:"):
        raise bad
    parts = mode.split(":", 1)[1].split("/")
    if len(parts) != 2:
        raise bad
    try:
        win, blk = int(parts[0]), int(parts[1])
    except ValueError:
        raise bad from None
    if blk <= 0 or win <= 0 or win % blk:
        raise ValueError(
            f"sparse attention mode {mode!r}: window {win} must be a "
            f"positive multiple of block {blk}")
    return win, blk


def sparse_mode_layout(mode, num_heads, seq_len):
    """The CAUSAL layout a mode string means — unidirectional Fixed with
    ``window//block`` local blocks + 1 global. Shared by the GPT-2 model
    wiring AND bench.py's flop accounting, so a layout retune can never
    silently desynchronize the two. Returns (layout, block)."""
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import \
        get_layout
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import \
        FixedSparsityConfig
    win, blk = parse_sparse_mode(mode)
    if seq_len % blk:
        raise ValueError(
            f"sparse attention mode {mode!r}: sequence length {seq_len} "
            f"must be a multiple of block {blk}")
    layout = get_layout(FixedSparsityConfig(
        num_heads=num_heads, block=blk, num_local_blocks=win // blk,
        num_global_blocks=1, attention="unidirectional"), seq_len)
    return layout, blk


def block_sparse_attention_fused(q, k, v, layout, key_padding_bias=None,
                                 block=None, causal=False, sm_scale=None):
    """LUT-driven streaming block-sparse attention (band + global split).

    Same semantics as ``block_sparse_attention`` (q,k,v [B,H,S,D]; layout
    [H, S//block, S//block]; optional [B,S] ADDITIVE key-padding bias) —
    different execution strategy: see module docstring. The layout must
    be CONCRETE (numpy) — the work lists are built at trace time."""
    if isinstance(layout, jax.core.Tracer):
        raise TypeError(
            "block_sparse_attention_fused needs a CONCRETE layout (numpy) "
            "— the live-block LUTs are built at trace time; pass the "
            "sparsity config's numpy layout, not a traced array")
    B, H, S, D = q.shape
    lay = np.asarray(layout) != 0
    if block is None:
        block = S // lay.shape[-1]
    if sm_scale is None:
        sm_scale = D ** -0.5
    gr, gc, rem = _decompose_layout(lay, causal)
    kpb = (None if key_padding_bias is None
           else jnp.asarray(key_padding_bias, jnp.float32))
    nq = lay.shape[1]

    if len(gc) == 0 and len(gr) == 0:
        strat = _get_strategy(rem, block, causal, sm_scale)
        return _map_over_data_axis(strat.attend, B)(q, k, v, kpb)

    if len(gc):
        # pack the global columns after the real sequence: per-head
        # liveness (and block-level causality — strictly-below-diagonal
        # blocks only; the diagonal blocks r == j stay in the real
        # region for the positional triangle) rides the work-list bits
        _, c0 = _tile_geometry(nq, nq, block)
        g_pad = -(-len(gc) // c0) * c0
        packed = np.zeros((H, nq, g_pad), bool)
        for t, j in enumerate(gc):
            packed[:, :, t] = lay[:, :, j]
            if causal:
                # rows r < j are fully causal-masked, row r == j needs
                # the positional triangle (stays in the real region)
                packed[:, :j + 1, t] = False
                rem[:, j, j] = lay[:, j, j]
        packed[:, gr, :] = False
        lay2 = np.concatenate([rem, packed], axis=2)
        col_ids = (np.asarray(gc)[:, None] * block
                   + np.arange(block)).reshape(-1)           # [G]
        pad_tok = (g_pad - len(gc)) * block
        strat = _get_strategy(lay2, block, causal, sm_scale,
                              causal_nblocks=nq)
    else:
        strat = _get_strategy(rem, block, causal, sm_scale)
        col_ids, pad_tok = None, 0

    def _attend(q, k, v, kpb):
        if col_ids is not None:
            def _pack(x):
                return jnp.concatenate(
                    [x, x[:, :, col_ids]] +
                    ([jnp.zeros(x.shape[:2] + (pad_tok, x.shape[3]),
                                x.dtype)] if pad_tok else []), axis=2)
            k2, v2 = _pack(k), _pack(v)
            kpb2 = kpb
            if kpb is not None:
                kpb2 = jnp.concatenate(
                    [kpb, kpb[:, col_ids]] +
                    ([jnp.zeros((kpb.shape[0], pad_tok), kpb.dtype)]
                     if pad_tok else []), axis=1)
        else:
            k2, v2, kpb2 = k, v, kpb
        out = strat.attend(q, k2, v2, kpb2)
        if len(gr):
            # the few global rows attend (nearly) everything — dense XLA
            row_ids = (np.asarray(gr)[:, None] * block
                       + np.arange(block)).reshape(-1)       # [R]
            qg = q[:, :, row_ids]
            bm = _expand_mask(lay[:, gr, :], block)           # [H, R, S]
            gout, _ = _masked_dense_part(
                qg, k, v, bm, np.arange(S), row_ids, causal, kpb, sm_scale)
            out = out.at[:, :, row_ids].set(gout)
        return out

    # the dense global-row part's [B,H,R,S] fp32 score tensor must not be
    # saved for backward across every layer — recompute, like flash
    return _map_over_data_axis(jax.checkpoint(_attend), B)(q, k, v, kpb)


def _map_over_data_axis(fn, batch):
    """shard_map ``fn(q, k, v, kpb)`` over the mesh data axis when one is
    active: GSPMD cannot partition a pallas_call, so under a dp mesh the
    unwrapped kernel would silently REPLICATE — every chip all-gathering
    the batch and computing all of it. The kernel is independent per
    (batch, head), so batch sharding maps exactly. No-op without a mesh,
    with a 1-wide data axis, or when the batch does not divide (e.g.
    sequence-parallel configs that borrow the data axis)."""
    from deepspeed_tpu.utils import groups
    from deepspeed_tpu.utils.jax_compat import (get_shard_map,
                                                under_manual_sharding)
    if not groups.mesh_is_initialized() or under_manual_sharding():
        # already inside a shard_map body (1-bit / sparse-grad step fns
        # shard the whole model over the data axis themselves): a nested
        # shard_map over the same axes crashes at trace time
        return fn
    mesh = groups.get_mesh()
    axes = tuple(a for a in groups.data_parallel_axes()
                 if mesh.shape[a] > 1)
    dp = 1
    for a in axes:
        dp *= mesh.shape[a]
    if dp <= 1:
        return fn
    if batch % dp:
        key = ("nondivisible", batch, dp)
        if key not in _replicate_warned:
            _replicate_warned.add(key)
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "fused block-sparse attention: batch %d does not divide "
                "the data-parallel world %d — the pallas kernel will run "
                "REPLICATED (every device computes the full batch); size "
                "the per-device batch to a multiple of dp", batch, dp)
        return fn
    shard_map, smap_kw = get_shard_map()
    spec4 = P(axes, None, None, None)
    spec2 = P(axes, None)

    def wrapped(q, k, v, kpb):
        in_specs = (spec4, spec4, spec4,
                    None if kpb is None else spec2)
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=spec4, **smap_kw)(q, k, v, kpb)

    return wrapped
