"""Block-sparse attention Pallas kernels.

TPU-native replacement for the reference's triton block-sparse stack
(ops/sparse_attention/matmul.py ``_kernel`` :13 — SDD/DSD matmuls,
softmax.py, and the csrc/sparse_attention/utils.cpp LUT builder). The
layout [H, nq, nk] gates a flash-style online-softmax sweep: the kv loop
visits every block but the whole block body is predicated on
``layout[qi, j]``, so Mosaic skips the MXU work for absent blocks — the
TPU analogue of triton's LUT-driven launch. Memory stays O(seq) (no dense
[S, S] scores), which is where the reference's 10-16× longer-sequence
claim comes from (BASELINE.md sparse attention rows).

Backward reuses the same predication with the transposed layout for
dk/dv. All kernels run in interpret mode off-TPU (CPU tests).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # needed for SMEM layout residency on TPU; interpret mode works without
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from deepspeed_tpu.ops._platform import interpret as _interpret

NEG_INF = -1e30
LANES = 8


def _fwd_kernel(layout_ref, kpm_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale, causal, block, seq, has_bias):
    qi = pl.program_id(1)
    q = q_ref[0]
    num_kv = seq // block

    def body(j, carry):
        acc, m, l = carry

        def attend(carry):
            acc, m, l = carry
            k = k_ref[0, pl.ds(j * block, block), :]
            v = v_ref[0, pl.ds(j * block, block), :]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) \
                * sm_scale
            if has_bias:
                # key-padding bias (0 = attend, ~-1e9 = masked): the
                # online softmax self-corrects — masked contributions get
                # weight exp(-1e9 - m_final) == 0 once a valid key raises m
                s = s + kpm_ref[0:1, pl.ds(j * block, block)]
            if causal:
                rows = qi * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 0)
                cols = j * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 1)
                s = jnp.where(cols <= rows, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=1)
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return acc, m_new, l_new

        return jax.lax.cond(layout_ref[0, qi, j] != 0, attend,
                            lambda c: c, carry)

    d = q.shape[-1]
    acc = jnp.zeros((block, d), jnp.float32)
    m = jnp.full((block,), NEG_INF, jnp.float32)
    l = jnp.zeros((block,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc, m, l))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    empty = l == 0.0  # rows with no attended block at all → zero output
    o_ref[0] = jnp.where(empty[:, None], 0.0, o_ref[0]).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(
        (m + jnp.log(l_safe))[:, None], (block, LANES))


def _dq_kernel(layout_ref, kpm_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, *, sm_scale, causal, block, seq,
               has_bias):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0:1]
    delta = delta_ref[0, :, 0:1]
    num_kv = seq // block

    def body(j, dq):
        def attend(dq):
            k = k_ref[0, pl.ds(j * block, block), :]
            v = v_ref[0, pl.ds(j * block, block), :]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) \
                * sm_scale
            if has_bias:
                s = s + kpm_ref[0:1, pl.ds(j * block, block)]
            if causal:
                rows = qi * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 0)
                cols = j * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 1)
                s = jnp.where(cols <= rows, s, NEG_INF)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * sm_scale
            return dq + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        return jax.lax.cond(layout_ref[0, qi, j] != 0, attend,
                            lambda d: d, dq)

    dq = jnp.zeros(q.shape, jnp.float32)
    dq = jax.lax.fori_loop(0, num_kv, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(layout_ref, kpm_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, *, sm_scale, causal, block, seq,
                has_bias):
    kj = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    num_q = seq // block

    def body(i, carry):
        def attend(carry):
            dk, dv = carry
            q = q_ref[0, pl.ds(i * block, block), :]
            do = do_ref[0, pl.ds(i * block, block), :]
            lse = lse_ref[0, pl.ds(i * block, block), 0:1]
            delta = delta_ref[0, pl.ds(i * block, block), 0:1]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) \
                * sm_scale
            if has_bias:
                s = s + kpm_ref[0:1, pl.ds(kj * block, block)]
            if causal:
                rows = i * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 0)
                cols = kj * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 1)
                s = jnp.where(cols <= rows, s, NEG_INF)
            p = jnp.exp(s - lse)
            dv = dv + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * sm_scale
            dk = dk + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk, dv

        # transposed gating: kv block kj is touched by q block i
        return jax.lax.cond(layout_ref[0, i, kj] != 0, attend,
                            lambda c: c, carry)

    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    dk, dv = jax.lax.fori_loop(0, num_q, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def block_sparse_attention(q, k, v, layout, key_padding_bias=None,
                           block=None, causal=False, sm_scale=None):
    """Attention restricted to the block layout.

    q,k,v: [B, H, S, D]; layout: [H, S//block, S//block] int;
    key_padding_bias: optional [B, S] ADDITIVE fp32 score bias
    (0 = attend, ~-1e9 = masked key — the reference's
    key_padding_mask_mode='add')."""
    out, _ = _bs_fwd(q, k, v, layout, key_padding_bias, block, causal,
                     sm_scale)
    return out


def block_sparse_attention_gathered(q, k, v, layout, key_padding_bias=None,
                                    block=None, causal=False, sm_scale=None):
    """Gather-then-dense block-sparse attention — same semantics as
    :func:`block_sparse_attention`, different execution strategy.

    The layout is STATIC, so each q-row-block's live kv blocks are known
    at trace time: a static ``jnp.take`` packs only the live K/V blocks
    into ``[nq, max_live, block, D]`` and dense MXU-shaped einsums run
    over the packed keys — compute and memory scale with the layout
    density (× the per-row ragged-padding to ``max_live``), NOT with
    S². Backward falls out of autodiff (the gather's transpose is the
    scatter-add), so numerics match the predicated-sweep kernel path to
    rounding. Memory: packed K/V is ``density·nq`` × a kv copy — fine for
    the local+global layouts this exists for."""
    B, H, S, D = q.shape
    if block is None:
        block = S // layout.shape[-1]
    if sm_scale is None:
        sm_scale = D ** -0.5
    if isinstance(layout, jax.core.Tracer):
        raise TypeError(
            "block_sparse_attention_gathered needs a CONCRETE layout "
            "(numpy) — the live-block LUT is built at trace time; pass "
            "the sparsity config's numpy layout, not a traced array")
    lay = np.asarray(layout) != 0
    Hh, nq, nk = lay.shape
    assert nq * block == S, (lay.shape, block, S)
    max_live = max(int(lay.sum(axis=-1).max()), 1)
    # static LUT: idx[h, i, t] = t-th live kv block of q-row-block i
    idx = np.zeros((Hh, nq, max_live), np.int32)
    valid = np.zeros((Hh, nq, max_live), bool)
    for h in range(Hh):
        for i in range(nq):
            live = np.nonzero(lay[h, i])[0]
            idx[h, i, :len(live)] = live
            valid[h, i, :len(live)] = True
    idx_j = jnp.asarray(idx)
    # gathered key COLUMN ids per (h, i, t, c): for causal + padding masks
    cols = idx[..., None] * block + np.arange(block)    # [H,nq,L,blk]
    col_ok = np.broadcast_to(valid[..., None], cols.shape)

    def _attend(q, k, v, kpb):
        return _gathered_attend(q, k, v, kpb, idx_j=idx_j, cols=cols,
                                col_ok=col_ok, block=block, causal=causal,
                                sm_scale=sm_scale, max_live=max_live)

    kpb_in = (None if key_padding_bias is None
              else jnp.asarray(key_padding_bias, jnp.float32))
    # remat: the packed [B,H,nq,blk,L,blk] score/weight tensors would
    # otherwise be SAVED for backward across every layer (OOMed at
    # BERT-large seq 2048); recompute-in-backward keeps residency at the
    # inputs, the same trade flash attention makes
    return jax.checkpoint(_attend)(q, k, v, kpb_in)


def _gathered_attend(q, k, v, kpb, *, idx_j, cols, col_ok, block, causal,
                     sm_scale, max_live):
    B, H, S, D = q.shape
    Hh, nq, _ = idx_j.shape
    nk = S // block
    kb = k.reshape(B, H, nk, block, D)
    vb = v.reshape(B, H, nk, block, D)
    # pack live kv blocks: [B, H, nq, L, blk, D] (static gather per head)
    kg = jnp.take_along_axis(
        kb[:, :, None], idx_j[None, :, :, :, None, None], axis=3)
    vg = jnp.take_along_axis(
        vb[:, :, None], idx_j[None, :, :, :, None, None], axis=3)
    qb = q.reshape(B, H, nq, block, D)

    s = jnp.einsum("bhipd,bhilcd->bhiplc", qb, kg,
                   preferred_element_type=jnp.float32) * sm_scale
    neg = jnp.float32(NEG_INF)
    mask = jnp.asarray(col_ok)[None, :, :, None]          # [1,H,nq,1,L,blk]
    if causal:
        rows = (np.arange(nq)[:, None] * block
                + np.arange(block)[None, :])              # [nq, blk]
        cmask = cols[:, :, None, :, :] <= rows[None, :, :, None, None]
        mask = mask & jnp.asarray(cmask)[None]            # [1,H,nq,blk,L,blk]
    s = jnp.where(mask, s, neg)
    if kpb is not None:
        kpb_g = kpb[:, jnp.asarray(cols.reshape(Hh, -1))] \
            .reshape(B, Hh, nq, max_live, block)
        s = s + kpb_g[:, :, :, None]
    sf = s.reshape(B, H, nq, block, max_live * block)
    m = jnp.max(sf, axis=-1, keepdims=True)
    # rows with NO live key (fully masked) must output zeros, not NaN
    p = jnp.exp(sf - jnp.maximum(m, neg / 2))
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhiplc,bhilcd->bhipd",
                     p.reshape(B, H, nq, block, max_live, block)
                     .astype(vg.dtype), vg,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, H, S, D).astype(q.dtype)


def _specs(H, block, nq, D, S):
    # the layout LUT lives in SMEM: the kernels read layout[0, qi, j] at a
    # DYNAMIC j, and Mosaic only allows unaligned dynamic scalar loads
    # from scalar memory (a VMEM i32 load must be 128-lane aligned —
    # failed to compile at seq 512). nq^2 i32 is a few KB.
    lay = pl.BlockSpec((1, nq, nq), lambda b, i: (b % H, 0, 0),
                       memory_space=(pltpu.SMEM if pltpu else None))
    qb = pl.BlockSpec((1, block, D), lambda b, i: (b, i, 0))
    full = pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0))
    stat = pl.BlockSpec((1, block, LANES), lambda b, i: (b, i, 0))
    statf = pl.BlockSpec((1, S, LANES), lambda b, i: (b, 0, 0))
    return lay, qb, full, stat, statf


def _kpm_arr(key_padding_bias, B, H, S):
    """[B, S] additive bias -> ([B, S] array, spec, has_bias).
    Kept 2D at its natural width — the (8,128) HBM tiling stores it dense,
    and the kernels slice a (1, block) row per key block instead of
    streaming a LANES-wide broadcast (128x the mask bytes). The spec
    shares one bias row across all H heads of a batch (b // H); without a
    mask, a 1-row dummy (never read: the kernels compile the add out when
    has_bias is False) keeps the pallas signature static."""
    if key_padding_bias is None:
        arr = jnp.zeros((1, S), jnp.float32)
        spec = pl.BlockSpec((1, S), lambda b, i: (0, 0))
        return arr, spec, False
    kpb = jnp.asarray(key_padding_bias, jnp.float32)
    assert kpb.shape == (B, S), (kpb.shape, (B, S))
    spec = pl.BlockSpec((1, S), lambda b, i: (b // H, 0))
    return kpb, spec, True


def _bs_fwd(q, k, v, layout, key_padding_bias, block, causal, sm_scale):
    B, H, S, D = q.shape
    if block is None:
        block = S // layout.shape[-1]
    assert layout.shape[-1] * block == S, (layout.shape, block, S)
    if sm_scale is None:
        sm_scale = D ** -0.5
    nq = S // block
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    layout = jnp.asarray(layout, jnp.int32)
    kpm, kpm_spec, has_bias = _kpm_arr(key_padding_bias, B, H, S)

    lay, qb, full, stat, _ = _specs(H, block, nq, D, S)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block=block, seq=S, has_bias=has_bias),
        grid=(B * H, nq),
        in_specs=[lay, kpm_spec, qb, full, full],
        out_specs=[qb, stat],
        out_shape=[jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
                   jax.ShapeDtypeStruct((B * H, S, LANES), jnp.float32)],
        interpret=_interpret(),
    )(layout, kpm, qf, kf, vf)
    return o.reshape(B, H, S, D), (q, k, v, layout, key_padding_bias,
                                   o.reshape(B, H, S, D), lse)


def _bs_bwd(block, causal, sm_scale, res, g):
    q, k, v, layout, key_padding_bias, out, lse = res
    B, H, S, D = q.shape
    if block is None:
        block = S // layout.shape[-1]
    if sm_scale is None:
        sm_scale = D ** -0.5
    nq = S // block
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    dof = g.reshape(B * H, S, D)
    kpm, kpm_spec, has_bias = _kpm_arr(key_padding_bias, B, H, S)
    delta = jnp.broadcast_to(
        jnp.sum(dof.astype(jnp.float32) *
                out.reshape(B * H, S, D).astype(jnp.float32),
                axis=-1, keepdims=True), (B * H, S, LANES))

    lay, qb, full, stat, statf = _specs(H, block, nq, D, S)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block=block, seq=S, has_bias=has_bias),
        grid=(B * H, nq),
        in_specs=[lay, kpm_spec, qb, full, full, qb, stat, stat],
        out_specs=qb,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=_interpret(),
    )(layout, kpm, qf, kf, vf, dof, lse, delta)

    kb = pl.BlockSpec((1, block, D), lambda b, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block=block, seq=S, has_bias=has_bias),
        grid=(B * H, nq),
        in_specs=[lay, kpm_spec, full, kb, kb, full, statf, statf],
        out_specs=[kb, kb],
        out_shape=[jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, S, D), v.dtype)],
        interpret=_interpret(),
    )(layout, kpm, qf, kf, vf, dof, lse, delta)

    return (dq.reshape(B, H, S, D), dk.reshape(B, H, S, D),
            dv.reshape(B, H, S, D), None, None)


block_sparse_attention.defvjp(
    lambda q, k, v, layout, kpb, block, causal, sm_scale:
    _bs_fwd(q, k, v, layout, kpb, block, causal, sm_scale),
    _bs_bwd)


def layout_to_dense_mask(layout, block, seq):
    """Expand a block layout to an element mask [H, S, S] (the oracle)."""
    lay = np.asarray(layout)
    return np.kron(lay, np.ones((block, block), dtype=bool))
