"""SparseSelfAttention module.

Rebuild of deepspeed/ops/sparse_attention/sparse_self_attention.py:13
(and BertSparseSelfAttention): applies block-sparse attention under a
SparsityConfig. Layouts are built once per (config, seq_len) and cached —
the analogue of the reference's master-layout caching
(sparse_self_attention.py:42).
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.kernels import (
    block_sparse_attention, block_sparse_attention_gathered)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig, SparsityConfig)

_layout_cache = {}
_density_warned = set()


def _config_key(cfg: SparsityConfig):
    """Content-based cache key: id()-keyed caching is unsafe when configs
    are constructed per call (a freed id can be reused by a DIFFERENT
    config, serving a stale layout). List-valued geometry (variable /
    longformer block indices) participates via tuple conversion."""
    def norm(v):
        if isinstance(v, (list, tuple)):
            return tuple(norm(x) for x in v)
        if isinstance(v, (int, float, str, bool)) or v is None:
            return v
        return repr(v)
    return (type(cfg).__name__,
            tuple(sorted((k, norm(v)) for k, v in vars(cfg).items())))


def get_layout(sparsity_config: SparsityConfig, seq_len: int):
    key = (_config_key(sparsity_config), seq_len)
    if key not in _layout_cache:
        _layout_cache[key] = sparsity_config.make_layout(seq_len)
    return _layout_cache[key]


class SparseSelfAttention(nn.Module):
    """q,k,v [B, H, S, D] → context [B, H, S, D] under the sparse layout
    (reference forward, sparse_self_attention.py:117)."""
    sparsity_config: SparsityConfig = None
    key_padding_mask_mode: str = "add"
    attn_mask_mode: str = "mul"
    max_seq_length: int = 2048

    def _config(self):
        return self.sparsity_config or FixedSparsityConfig(num_heads=4)

    @nn.compact
    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        assert query.dtype == key.dtype == value.dtype
        if attn_mask is not None:
            raise NotImplementedError(
                "SparseSelfAttention: full [S, S] attn_mask is not "
                "supported by the TPU block-sparse kernel; use "
                "key_padding_mask (per-key) or a causal sparsity config")
        S = query.shape[2]
        kpb = None
        if key_padding_mask is not None:
            kpm = jnp.asarray(key_padding_mask)
            if jnp.issubdtype(kpm.dtype, jnp.floating):
                # reference key_padding_mask_mode: 'add' means the float
                # mask IS the additive score bias (callers with 1.0/0.0
                # validity masks must convert to bool first — see
                # BertSparseSelfAttention)
                if self.key_padding_mask_mode != "add":
                    raise NotImplementedError(
                        f"key_padding_mask_mode="
                        f"{self.key_padding_mask_mode!r}; only 'add' is "
                        "implemented for float masks")
                kpb = kpm.astype(jnp.float32)
            else:
                # bool/int: True/1 = attend, False/0 = padding
                kpb = jnp.where(kpm.astype(bool), 0.0, -1e9
                                ).astype(jnp.float32)
        cfg = self._config()
        layout = get_layout(cfg, S)
        causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
        import os
        # 'fused' (default): LUT-driven streaming flash kernels — the
        # work list walks only live tiles, global columns are packed
        # (fused_kernels.py) — the round-5 strategy that finally BEATS
        # dense flash at long seq (PERF.md). 'gathered': static-LUT
        # jnp.take packing + dense einsums (oracle-exact, portable).
        # 'predicated': the in-kernel online sweep over all blocks.
        # NOTE: read at TRACE time — changing the env after a jitted
        # call reuses the cached trace
        impl = os.environ.get("DS_SPARSE_IMPL", "fused")
        if impl not in ("fused", "gathered", "predicated"):
            raise ValueError(
                f"DS_SPARSE_IMPL must be 'fused', 'gathered' or "
                f"'predicated', got {impl!r}")
        if impl == "fused":
            from deepspeed_tpu.ops.sparse_attention.fused_kernels import \
                block_sparse_attention_fused
            return block_sparse_attention_fused(
                query, key, value, layout,
                key_padding_bias=kpb, block=cfg.block, causal=causal)
        if impl == "gathered":
            # the gathered form packs max_live kv blocks PER q-row-block:
            # for dense-ish layouts (max_live -> nk) that is near-O(S^2)
            # packed K/V memory with ragged padding — warn once per
            # layout so the degradation is not silent (round-4 advisory)
            wkey = (_config_key(cfg), S)
            if wkey not in _density_warned:
                _density_warned.add(wkey)
                import numpy as _np
                _lay = _np.asarray(layout)
                max_live = int(_lay.sum(axis=-1).max())
                nk = max(1, _lay.shape[-1])
                if max_live >= 0.75 * nk:
                    from deepspeed_tpu.utils.logging import logger
                    logger.warning(
                        "SparseSelfAttention: layout density %.2f (max %d "
                        "live of %d kv blocks) — the gathered impl packs "
                        "near-full K/V copies at this density; dense flash "
                        "attention or DS_SPARSE_IMPL=predicated will use "
                        "less memory", max_live / nk, max_live, nk)
            # the layout stays CONCRETE numpy: the live-block LUT is
            # built at trace time
            return block_sparse_attention_gathered(
                query, key, value, layout,
                key_padding_bias=kpb, block=cfg.block, causal=causal)
        return block_sparse_attention(
            query, key, value, jnp.asarray(layout),
            key_padding_bias=kpb, block=cfg.block, causal=causal)


class BertSparseSelfAttention(nn.Module):
    """Reference bert_sparse_self_attention.py: BERT-shaped wrapper."""
    hidden_size: int
    num_attention_heads: int
    sparsity_config: SparsityConfig = None

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None):
        B, S, H = hidden_states.shape
        nh = self.num_attention_heads
        hd = H // nh
        qkv = nn.Dense(3 * H, name="qkv")(hidden_states)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        if attention_mask is not None:
            # HF-style validity mask (possibly float 1.0/0.0): force the
            # boolean reading so a float-typed mask can't be misread as
            # an additive bias (the dense leg does the same .astype(bool))
            attention_mask = jnp.asarray(attention_mask).astype(bool)
        ctx = SparseSelfAttention(
            sparsity_config=self.sparsity_config or
            FixedSparsityConfig(num_heads=nh), name="sparse_attn")(
                q, k, v, key_padding_mask=attention_mask)
        return ctx.transpose(0, 2, 1, 3).reshape(B, S, H)
