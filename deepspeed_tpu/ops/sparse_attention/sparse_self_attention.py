"""SparseSelfAttention module.

Rebuild of deepspeed/ops/sparse_attention/sparse_self_attention.py:13
(and BertSparseSelfAttention): applies block-sparse attention under a
SparsityConfig. Layouts are built once per (config, seq_len) and cached —
the analogue of the reference's master-layout caching
(sparse_self_attention.py:42).
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.kernels import block_sparse_attention
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig, SparsityConfig)

_layout_cache = {}


def _config_key(cfg: SparsityConfig):
    """Content-based cache key: id()-keyed caching is unsafe when configs
    are constructed per call (a freed id can be reused by a DIFFERENT
    config, serving a stale layout)."""
    return (type(cfg).__name__,
            tuple(sorted((k, v) for k, v in vars(cfg).items()
                         if isinstance(v, (int, float, str, bool)))))


def get_layout(sparsity_config: SparsityConfig, seq_len: int):
    key = (_config_key(sparsity_config), seq_len)
    if key not in _layout_cache:
        _layout_cache[key] = sparsity_config.make_layout(seq_len)
    return _layout_cache[key]


class SparseSelfAttention(nn.Module):
    """q,k,v [B, H, S, D] → context [B, H, S, D] under the sparse layout
    (reference forward, sparse_self_attention.py:117)."""
    sparsity_config: SparsityConfig = None
    key_padding_mask_mode: str = "add"
    attn_mask_mode: str = "mul"
    max_seq_length: int = 2048

    def _config(self):
        return self.sparsity_config or FixedSparsityConfig(num_heads=4)

    @nn.compact
    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        assert query.dtype == key.dtype == value.dtype
        if key_padding_mask is not None or attn_mask is not None:
            # the Pallas kernel has no mask input yet; silently attending
            # padding would be worse than failing
            raise NotImplementedError(
                "SparseSelfAttention: key_padding_mask/attn_mask are not "
                "supported by the TPU block-sparse kernel; drop padding "
                "host-side or use dense attention for padded batches")
        S = query.shape[2]
        cfg = self._config()
        layout = get_layout(cfg, S)
        causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
        return block_sparse_attention(
            query, key, value, jnp.asarray(layout), cfg.block, causal,
            None)


class BertSparseSelfAttention(nn.Module):
    """Reference bert_sparse_self_attention.py: BERT-shaped wrapper."""
    hidden_size: int
    num_attention_heads: int
    sparsity_config: SparsityConfig = None

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None):
        B, S, H = hidden_states.shape
        nh = self.num_attention_heads
        hd = H // nh
        qkv = nn.Dense(3 * H, name="qkv")(hidden_states)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        ctx = SparseSelfAttention(
            sparsity_config=self.sparsity_config or
            FixedSparsityConfig(num_heads=nh), name="sparse_attn")(
                q, k, v, key_padding_mask=attention_mask)
        return ctx.transpose(0, 2, 1, 3).reshape(B, S, H)
