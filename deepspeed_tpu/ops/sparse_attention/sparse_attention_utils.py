"""SparseAttentionUtils — wire the ``"sparse_attention"`` JSON block to
models.

Rebuild of deepspeed/ops/sparse_attention/sparse_attention_utils.py:13 and
the config extraction at deepspeed/runtime/config.py:345-529. The
reference walks an HF module tree swapping self-attention instances; flax
modules are config-built, so the substitution happens at MODEL-CONFIG
level: :func:`apply_to_bert_config` maps the JSON block onto the
BertConfig fields that select :class:`models.bert.BertSparseLayer`.
"""

import dataclasses

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, VariableSparsityConfig)

_MODES = {
    "dense": DenseSparsityConfig,
    "fixed": FixedSparsityConfig,
    "variable": VariableSparsityConfig,
    "bigbird": BigBirdSparsityConfig,
    "bslongformer": BSLongformerSparsityConfig,
}


def get_sparse_attention_config(ds_config_dict, num_heads):
    """JSON ``sparse_attention`` block -> SparsityConfig instance
    (reference runtime/config.py:345 ``get_sparse_attention``). Returns
    None when the block is absent; an EMPTY block enables fixed-mode
    defaults (reference behavior); unknown keys raise from the sparsity
    config constructor."""
    block_cfg = (ds_config_dict or {}).get("sparse_attention")
    if block_cfg is None:
        return None
    if not isinstance(block_cfg, dict):
        raise ValueError(
            f"'sparse_attention' must be a dict block, got "
            f"{block_cfg!r} (use {{}} for fixed-mode defaults)")
    mode = block_cfg.get("mode", "fixed")
    if mode not in _MODES:
        raise NotImplementedError(
            f"Given sparsity mode, {mode}, has not been implemented yet!")
    kwargs = {k: v for k, v in block_cfg.items() if k != "mode"}
    return _MODES[mode](num_heads=num_heads, **kwargs)


class SparseAttentionUtils:
    """Reference class surface (sparse_attention_utils.py:13)."""

    # the JSON keys BertConfig can represent, per mode (beyond "mode")
    _BERT_FIELDS = {
        "fixed": {"block", "num_local_blocks", "num_global_blocks"},
        "dense": {"block"},
        "bigbird": {"block"},
        "bslongformer": {"block"},
        "variable": {"block"},
    }

    @staticmethod
    def apply_to_bert_config(bert_config, ds_config_dict):
        """Return a BertConfig whose layers use block-sparse attention per
        the ds_config ``sparse_attention`` block — the flax analogue of
        ``replace_model_self_attention_with_sparse_self_attention``.

        Validates the WHOLE block through
        :func:`get_sparse_attention_config` first (so typo'd keys raise),
        then refuses keys BertConfig cannot carry instead of silently
        training a different pattern than configured."""
        sc = get_sparse_attention_config(
            ds_config_dict, bert_config.num_attention_heads)
        if sc is None:
            return bert_config
        block_cfg = ds_config_dict["sparse_attention"]
        mode = block_cfg.get("mode", "fixed")
        extra = (set(block_cfg) - {"mode"}
                 - SparseAttentionUtils._BERT_FIELDS[mode])
        if extra:
            raise ValueError(
                f"sparse_attention keys {sorted(extra)} are valid for "
                f"mode {mode!r} but not representable in BertConfig; "
                "construct BertSparseLayer with a custom SparsityConfig "
                "instead")
        updates = {"sparse_attention_mode": mode, "sparse_block": sc.block}
        if mode == "fixed":
            updates["sparse_num_local_blocks"] = sc.num_local_blocks
            updates["sparse_num_global_blocks"] = sc.num_global_blocks
        return dataclasses.replace(bert_config, **updates)

    @staticmethod
    def pad_to_block_size(block_size, input_ids, attention_mask=None,
                          pad_token_id=0):
        """Pad the sequence dim up to a multiple of the sparsity block
        (reference :151); returns (pad_len, input_ids, attention_mask)."""
        import jax.numpy as jnp
        S = input_ids.shape[1]
        pad_len = (-S) % block_size
        if attention_mask is None:
            # always return a mask: a data-dependent None would flip the
            # caller's types on input length
            attention_mask = jnp.ones(input_ids.shape, jnp.int32)
        if pad_len == 0:
            return 0, input_ids, attention_mask
        ids = jnp.pad(input_ids, ((0, 0), (0, pad_len)),
                      constant_values=pad_token_id)
        mask = jnp.pad(attention_mask, ((0, 0), (0, pad_len)),
                       constant_values=0)
        return pad_len, ids, mask

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """reference :210 — strip the block padding again."""
        if pad_len == 0:
            return sequence_output
        return sequence_output[:, :-pad_len]
