"""Block-sparsity layout generators.

Rebuild of deepspeed/ops/sparse_attention/sparsity_config.py
(``SparsityConfig`` :25, ``DenseSparsityConfig`` :63, ``FixedSparsityConfig``
:94, ``VariableSparsityConfig`` :243, ``BigBirdSparsityConfig`` :421,
``BSLongformerSparsityConfig`` :544). A layout is an int tensor
``[num_heads, num_blocks, num_blocks]`` marking which (q_block, k_block)
tiles attend; the math here is a faithful port (it is pure index algebra)
and the kernels (sparse_self_attention.py) consume the same layouts the
reference's triton kernels did.
"""

import random

import numpy as np


class SparsityConfig:
    """Base: block size + head layout sharing (reference :25)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block "
                f"{self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks),
                        dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attend (reference :63): the degenerate oracle config."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed local+global pattern (reference :94): local windows of
    ``num_local_blocks``; the last ``num_global_blocks`` of each window
    attend globally; 'unidirectional' restricts to the causal half."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention {attention}")
        self.attention = attention
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError(
                "horizontal global attention requires bidirectional")
        self.horizontal_global_attention = horizontal_global_attention
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be a multiple "
                f"of num_global_blocks {num_global_blocks}")
        self.num_global_blocks = num_global_blocks
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "different global patterns require different_layout_per_head")
        max_patterns = num_local_blocks // num_global_blocks
        if num_different_global_patterns > max_patterns:
            raise ValueError(
                f"num_different_global_patterns "
                f"{num_different_global_patterns} exceeds "
                f"num_local/num_global {max_patterns}")
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        for i in range(0, num_blocks, self.num_local_blocks):
            end = min(i + self.num_local_blocks, num_blocks)
            for row in range(i, end):
                for col in range(i, (row + 1 if self.attention ==
                                     "unidirectional" else end)):
                    layout[h, row, col] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        first_global_block_idx = (
            self.num_local_blocks - (1 + h % self.num_different_global_patterns)
            * self.num_global_blocks)

        end_block_idx = num_blocks if self.attention == "bidirectional" else \
            num_blocks  # causal masking handled per row below
        for i in range(first_global_block_idx, num_blocks,
                       self.num_local_blocks):
            # vertical global columns
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + self.num_global_blocks] = 1
            if self.attention == "unidirectional":
                # zero the upper triangle the vertical stripe created
                for row in range(num_blocks):
                    for col in range(i, min(i + self.num_global_blocks,
                                            num_blocks)):
                        if col > row:
                            layout[h, row, col] = 0
            if self.horizontal_global_attention:
                layout[h, i:i + self.num_global_blocks, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local windows + random + custom global blocks
    (reference :243)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional",
                 horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention {attention}")
        self.attention = attention
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError(
                "horizontal global attention requires bidirectional")
        self.horizontal_global_attention = horizontal_global_attention
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices "
                    "must have equal length")
        self.global_block_end_indices = global_block_end_indices

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks {self.num_random_blocks} exceeds "
                f"{num_blocks}")
        for row in range(num_blocks):
            sample = random.sample(range(num_blocks), self.num_random_blocks)
            if self.attention == "unidirectional":
                sample = [s for s in sample if s <= row]
            layout[h, row, sample] = 1
        return layout

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        start = 0
        while start < num_blocks:
            for w in self.local_window_blocks:
                end = min(start + w, num_blocks)
                for row in range(start, end):
                    for col in range(start, (row + 1 if self.attention ==
                                             "unidirectional" else end)):
                        layout[h, row, col] = 1
                start = end
                if start >= num_blocks:
                    break
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx >= num_blocks:
                    continue
                first_row = 0 if self.attention == "bidirectional" else idx
                layout[h, first_row:, idx] = 1
                if self.horizontal_global_attention:
                    layout[h, idx, :] = 1
        else:
            for start, end in zip(self.global_block_indices,
                                  self.global_block_end_indices):
                end = min(end, num_blocks)
                first_row = 0 if self.attention == "bidirectional" else start
                layout[h, first_row:, start:end] = 1
                if self.horizontal_global_attention:
                    layout[h, start:end, :] = 1
        if self.attention == "unidirectional":
            tri = np.tril(np.ones((num_blocks, num_blocks), dtype=np.int64))
            layout[h] &= tri
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding window + global (reference :421)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention {attention}")
        self.attention = attention

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        for row in range(num_blocks):
            hi = (row + 1) if self.attention == "unidirectional" \
                else num_blocks
            n = min(self.num_random_blocks, hi)
            sample = random.sample(range(hi), n)
            layout[h, row, sample] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for row in range(num_blocks):
            lo = max(0, row - w)
            hi = min(row + w + 1, num_blocks)
            if self.attention == "unidirectional":
                hi = min(hi, row + 1)
            layout[h, row, lo:hi] = 1
        return layout

    def set_global_layout_itc(self, h, layout):
        num_blocks = layout.shape[1]
        g = min(self.num_global_blocks, num_blocks)
        layout[h, 0:g, :] = 1
        layout[h, :, 0:g] = 1
        if self.attention == "unidirectional":
            tri = np.tril(np.ones((num_blocks, num_blocks), dtype=np.int64))
            layout[h] &= tri
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + symmetric global attention (reference :544)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        if global_block_end_indices is not None and \
                len(self.global_block_indices) != len(global_block_end_indices):
            raise ValueError("index list lengths must match")

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for row in range(num_blocks):
            lo = max(0, row - w)
            hi = min(row + w + 1, num_blocks)
            if self.attention == "unidirectional":
                hi = min(hi, row + 1)
            layout[h, row, lo:hi] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start, end in spans:
            end = min(end, num_blocks)
            layout[h, :, start:end] = 1
            layout[h, start:end, :] = 1
        if self.attention == "unidirectional":
            tri = np.tril(np.ones((num_blocks, num_blocks), dtype=np.int64))
            layout[h] &= tri
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)
