"""ops.adagrad (reference deepspeed/ops/adagrad/): the host CPU-Adagrad
shares the AVX C library with CPU-Adam (csrc/cpu_adam.cpp
ds_adagrad_step), so the class lives beside it."""

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdagrad  # noqa: F401
