"""Reference module path ops/adagrad/cpu_adagrad.py."""

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdagrad  # noqa: F401
