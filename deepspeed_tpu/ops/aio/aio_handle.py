"""Python surface of the async IO engine.

Mirrors the reference binding (csrc/aio/py_ds_aio.cpp:12 ``aio_handle``
with block_size/queue_depth/single_submit/overlap_events/thread_count;
sync/async pread/pwrite) over the C ABI in csrc/aio.cpp.
"""

import numpy as np

from deepspeed_tpu.ops.op_builder.builder import AsyncIOBuilder


def _buf(a: np.ndarray):
    import ctypes
    assert a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.c_char_p)


class AsyncIOHandle:
    def __init__(self, block_size=1 << 20, queue_depth=8,
                 single_submit=False, overlap_events=True, thread_count=1):
        self.lib = AsyncIOBuilder().load()
        self.handle = self.lib.aio_handle_create(
            block_size, queue_depth, int(single_submit), int(overlap_events),
            thread_count)
        assert self.handle > 0, "aio handle creation failed"
        self._block_size = block_size
        self._thread_count = thread_count

    # reference getters (deepspeed_py_aio_handle.cpp)
    def get_block_size(self):
        return self._block_size

    def get_thread_count(self):
        return self._thread_count

    def kernel_aio_available(self, probe_dir=None):
        """True when transfers (for files under ``probe_dir``) run through
        the kernel io_submit engine (csrc/aio.cpp kernel_aio_rw); False =
        thread-pool pread/pwrite fallback. Probes BOTH io_setup and an
        O_DIRECT open in probe_dir (tmpfs/overlayfs reject O_DIRECT even
        where io_setup works); probe_dir=None checks io_setup only."""
        d = probe_dir.encode() if probe_dir is not None else None
        return bool(self.lib.aio_kernel_available(d))

    def max_inflight(self):
        """High-water mark of simultaneously in-flight kernel-AIO
        requests since the last reset (0 = fallback path only) — the
        cache-independent proof the queue-depth engine overlaps I/O."""
        return int(self.lib.aio_max_inflight())

    def reset_max_inflight(self):
        self.lib.aio_reset_max_inflight()

    def sync_pread(self, buffer: np.ndarray, path: str, offset=0):
        n = self.lib.aio_sync_pread(self.handle, _buf(buffer),
                                    path.encode(), buffer.nbytes, offset)
        assert n >= 0, f"pread failed ({n})"
        return n

    def sync_pwrite(self, buffer: np.ndarray, path: str, offset=0):
        n = self.lib.aio_sync_pwrite(self.handle, _buf(buffer),
                                     path.encode(), buffer.nbytes, offset)
        assert n == buffer.nbytes, f"pwrite failed ({n})"
        return n

    def async_pread(self, buffer: np.ndarray, path: str, offset=0):
        req = self.lib.aio_async_pread(self.handle, _buf(buffer),
                                       path.encode(), buffer.nbytes, offset)
        assert req > 0, f"async pread submit failed ({req})"
        return req

    def async_pwrite(self, buffer: np.ndarray, path: str, offset=0):
        req = self.lib.aio_async_pwrite(self.handle, _buf(buffer),
                                        path.encode(), buffer.nbytes, offset)
        assert req > 0, f"async pwrite submit failed ({req})"
        return req

    def wait(self, request_id):
        return self.lib.aio_wait(self.handle, request_id)

    def __del__(self):
        try:
            self.lib.aio_handle_destroy(self.handle)
        except Exception:
            pass
