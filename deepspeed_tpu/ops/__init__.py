"""Public ``deepspeed_tpu.ops`` surface (reference deepspeed/ops/
__init__.py): the op family submodules plus the fused transformer layer
re-exports. Submodules load lazily — adam/lamb pull in the JIT builder
machinery, which top-level ``import deepspeed_tpu`` should not pay for."""

import importlib

_SUBMODULES = ("adam", "adagrad", "lamb", "aio", "quantizer",
               "sparse_attention", "transformer", "op_builder")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"{__name__}.{name}")
    if name in ("DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig"):
        mod = importlib.import_module(f"{__name__}.transformer.transformer")
        return getattr(mod, name)
    raise AttributeError(name)
