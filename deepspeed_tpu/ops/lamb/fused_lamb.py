"""FusedLamb — two-pass LAMB as Pallas kernels.

TPU-native equivalent of csrc/lamb/fused_lamb_cuda_kernel.cu (reference
wrapper ops/lamb/fused_lamb.py:12): pass 1 computes the Adam-style update
direction and accumulates ||w|| / ||u|| partial sums; the trust ratio is a
scalar combine; pass 2 scales. Here pass 1 is the fused Pallas kernel
emitting per-block partial norms, and the scalar combine + scale stay in
XLA (they fuse into neighbouring ops).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.runtime import optim as optim_lib

_BLOCK_ROWS = 256
_LANES = 128


def _interpret():
    from deepspeed_tpu.ops._platform import interpret
    return interpret()


def _lamb_pass1_kernel(s_ref, p_ref, g_ref, m_ref, v_ref,
                       u_ref, mo_ref, vo_ref, wn_ref, un_ref, *,
                       b1, b2, eps, weight_decay):
    bc1, bc2 = s_ref[0, 0], s_ref[0, 1]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if weight_decay > 0.0:
        u = u + weight_decay * p
    u_ref[:] = u
    mo_ref[:] = m
    vo_ref[:] = v
    # norm partial sums accumulate across the sequential TPU grid into one
    # (1, 1) output block (resident across iterations); stores must be 2D
    # slices — scalar stores to VMEM are rejected by Mosaic
    @pl.when(pl.program_id(0) == 0)
    def _():
        wn_ref[:, :] = jnp.zeros((1, 1), jnp.float32)
        un_ref[:, :] = jnp.zeros((1, 1), jnp.float32)
    wn_ref[:, :] += jnp.sum(p * p).reshape(1, 1)
    un_ref[:, :] += jnp.sum(u * u).reshape(1, 1)


def fused_lamb_update(p, g, m, v, lr, bc1, bc2, *, b1=0.9, b2=0.999,
                      eps=1e-6, weight_decay=0.0, min_coeff=0.01,
                      max_coeff=10.0):
    """One fused LAMB step for a single tensor; returns (update, m, v)."""
    shape, dtype = p.shape, p.dtype
    n = p.size
    width = _BLOCK_ROWS * _LANES
    n_pad = -(-n // width) * width

    def flat(x):
        xf = jnp.ravel(x)
        return jnp.pad(xf, (0, n_pad - n)).reshape(-1, _LANES)

    scal = jnp.stack([jnp.asarray(bc1, jnp.float32),
                      jnp.asarray(bc2, jnp.float32)]).reshape(1, 2)
    rows = n_pad // _LANES
    nblocks = rows // _BLOCK_ROWS
    kernel = functools.partial(_lamb_pass1_kernel, b1=b1, b2=b2, eps=eps,
                               weight_decay=weight_decay)
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    scalblk = pl.BlockSpec((1, 1), lambda i: (0, 0))
    u, m_new, v_new, wn, un = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((1, 2), lambda i: (0, 0)),
                  blk, blk, blk, blk],
        out_specs=[blk, blk, blk, scalblk, scalblk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((1, 1), jnp.float32),
                   jax.ShapeDtypeStruct((1, 1), jnp.float32)],
        interpret=_interpret(),
    )(scal, flat(p), flat(g), flat(m), flat(v))

    w_norm = jnp.sqrt(wn[0, 0])
    u_norm = jnp.sqrt(un[0, 0])
    ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                      jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
                      jnp.float32(1.0))
    unflat = lambda x: jnp.ravel(x)[:n].reshape(shape)
    upd = (-lr * ratio * unflat(u)).astype(dtype)
    return upd, unflat(m_new), unflat(v_new)


def fused_lamb(b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.0, min_coeff=0.01,
               max_coeff=10.0, bias_correction=True):
    """Optimizer pair backed by the Pallas kernels (reference FusedLamb)."""

    def init(params):
        return optim_lib.LambState(
            step=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            nu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        out = [fused_lamb_update(p, g, m, v, lr, bc1, bc2, b1=b1, b2=b2,
                                 eps=eps, weight_decay=weight_decay,
                                 min_coeff=min_coeff, max_coeff=max_coeff)
               for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        updates = treedef.unflatten([o[0] for o in out])
        mu = treedef.unflatten([o[1] for o in out])
        nu = treedef.unflatten([o[2] for o in out])
        return updates, optim_lib.LambState(step=step, mu=mu, nu=nu)

    return optim_lib.Optimizer(init, update)


class FusedLamb:
    """API-parity shell of the reference wrapper (ops/lamb/fused_lamb.py:12)."""

    def __new__(cls, params=None, lr=1e-3, betas=(0.9, 0.999), eps=1e-6,
                weight_decay=0.0, min_coeff=0.01, max_coeff=10.0,
                bias_correction=True, **_):
        return fused_lamb(b1=betas[0], b2=betas[1], eps=eps,
                          weight_decay=weight_decay, min_coeff=min_coeff,
                          max_coeff=max_coeff, bias_correction=bias_correction)
