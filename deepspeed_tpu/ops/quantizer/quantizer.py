"""Grouped quantization kernels.

TPU-native equivalent of csrc/quantization/quantizer.cu (pybind surface
``ds_quantize_fp{32,16}``, ``ds_sr_quantize_*``, asymmetric variants —
csrc/quantization/pt_binding.cpp:62-76) used by MoQ quantize-aware
training (runtime/quantize.py) and the module-quantize injection.

Semantics (matching the CUDA kernel): the tensor is viewed as ``groups``
equal rows; each row is quantized to ``num_bits`` symmetrically (scale =
max|x| / qmax, zero-point-free) or asymmetrically (min/max affine), then
IMMEDIATELY dequantized in place — the reference returns fake-quantized
values in the original dtype, which is what QAT consumes. Stochastic
rounding uses the TPU PRNG (pltpu.prng_random_bits); the CPU fallback uses
counter-based uniforms so tests are deterministic per seed.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def _on_tpu():
    from deepspeed_tpu.ops._platform import effective_platform
    return effective_platform() == "tpu"


def _qrange(num_bits, symmetric):
    if symmetric:
        return float(2 ** (num_bits - 1) - 1)
    return float(2 ** num_bits - 1)


def _quantize_rows(x, num_bits, symmetric, stochastic, noise):
    """Shared math: x is [groups, row]; noise in [0,1) same shape or None."""
    xf = x.astype(jnp.float32)
    if symmetric:
        qmax = _qrange(num_bits, True)
        scale = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / qmax
        scale = jnp.where(scale == 0.0, 1.0, scale)
        q = xf / scale
        if stochastic:
            q = jnp.floor(q + noise)
        else:
            q = jnp.round(q)
        q = jnp.clip(q, -qmax - 1, qmax)
        return q * scale
    qmax = _qrange(num_bits, False)
    lo = jnp.min(xf, axis=-1, keepdims=True)
    hi = jnp.max(xf, axis=-1, keepdims=True)
    scale = (hi - lo) / qmax
    scale = jnp.where(scale == 0.0, 1.0, scale)
    q = (xf - lo) / scale
    if stochastic:
        q = jnp.floor(q + noise)
    else:
        q = jnp.round(q)
    q = jnp.clip(q, 0, qmax)
    return q * scale + lo


def _quant_kernel(seed_ref, x_ref, y_ref, *, num_bits, symmetric, stochastic):
    if stochastic:
        pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
        bits = pltpu.prng_random_bits(x_ref.shape)
        noise = (pltpu.bitcast(bits, jnp.uint32) >> 8).astype(jnp.float32) \
            * (1.0 / (1 << 24))
    else:
        noise = None
    y_ref[:] = _quantize_rows(x_ref[:], num_bits, symmetric, stochastic,
                              noise).astype(y_ref.dtype)


_SR_COUNTER = [0]  # fresh noise per call (reference: evolving curand state)


def quantize(x, num_bits=8, groups=1, symmetric=True, stochastic=False,
             seed=None):
    """Fake-quantize ``x`` in-place-semantics (returns same shape/dtype).

    Mirrors ds_[sr_]quantize[_asym]_fp{32,16}: view as [groups, -1] rows,
    per-row scale, round (optionally stochastic), dequantize. When *seed*
    is None, each call draws a fresh seed so stochastic rounding stays
    unbiased across repeated calls."""
    if seed is None:
        _SR_COUNTER[0] += 1
        seed = _SR_COUNTER[0]
    shape, dtype = x.shape, x.dtype
    n = x.size
    assert n % groups == 0, f"numel {n} not divisible by groups {groups}"
    row = n // groups
    xg = x.reshape(groups, row)

    if _on_tpu() and row % 128 == 0 and groups >= 1:
        bg = 1
        while groups % (bg * 2) == 0 and bg * 2 * row <= (1 << 20):
            bg *= 2
        kernel = functools.partial(_quant_kernel, num_bits=num_bits,
                                   symmetric=symmetric, stochastic=stochastic)
        y = pl.pallas_call(
            kernel,
            grid=(groups // bg,),
            in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0),
                                   memory_space=pltpu.SMEM),
                      pl.BlockSpec((bg, row), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((bg, row), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((groups, row), dtype),
        )(jnp.asarray(seed, jnp.int32).reshape(1, 1), xg)
        return y.reshape(shape)

    # CPU / fallback path: identical math, jax.random noise
    noise = None
    if stochastic:
        noise = jax.random.uniform(jax.random.PRNGKey(seed), (groups, row))
    return _quantize_rows(xg, num_bits, symmetric, stochastic,
                          noise).astype(dtype).reshape(shape)


class Quantizer:
    """API-parity shell of ops/quantizer/quantizer.py:32."""

    def __init__(self, q_int8=True):
        self.num_bits = 8 if q_int8 else 16

    def quantize(self, x, groups=1, symmetric=True, stochastic=False,
                 seed=None):
        return quantize(x, num_bits=self.num_bits, groups=groups,
                        symmetric=symmetric, stochastic=stochastic, seed=seed)
