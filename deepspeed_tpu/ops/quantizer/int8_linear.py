"""True int8 weight storage with dequant-in-matmul.

The inference half of MoQ (reference module_inject/module_quantize.py:6
casts transformer layer weights to int8 in place;
csrc/transformer/inference/csrc/dequantize.cu dequantizes inside the
GEMM). TPU form: weights live in HBM as int8 (4x smaller than fp32) with
one fp32 scale per OUTPUT column; the matmul upcasts the int8 block to
the activation dtype on the fly — int8 magnitudes (<=127) are exact in
bfloat16, so ``(x @ w_int8) * scale`` loses nothing over dequantizing
first, and the MXU sees its native bf16 operands. The per-column scale
folds into the matmul epilogue (one multiply per output element, fused
by XLA).
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp


def quantize_weight_int8(w):
    """[in, out] float weight -> (int8 weight, fp32 [out] scales).

    Per-output-column absmax: column j is stored as
    round(w[:, j] / scale_j) with scale_j = absmax_j / 127. Column-wise
    (not row/group-wise) so the scale applies AFTER the contraction."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    wq = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                  -127, 127).astype(jnp.int8)
    return wq, scale


def dequantize_weight_int8(wq, scale, dtype=jnp.float32):
    return (wq.astype(jnp.float32) * scale).astype(dtype)


def int8_matmul(x, w_int8, scale, bias=None):
    """x @ dequant(w_int8) with the dequant folded into the matmul."""
    y = x @ w_int8.astype(x.dtype)
    y = y * scale.astype(y.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


class QuantDense(nn.Module):
    """Drop-in ``nn.Dense`` that transparently consumes int8 kernels.

    Param tree is identical to nn.Dense (kernel/bias under the module
    name), so swapping the class changes no checkpoints. When the kernel
    leaf has been replaced post-load by ``module_quantize`` (dtype int8)
    the per-column scale is read from the sibling ``quant_scales``
    collection and the forward runs dequant-in-matmul; float kernels take
    the ordinary path."""
    features: int
    use_bias: bool = True
    dtype: Optional[Any] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features))
        bias = (self.param("bias", self.bias_init, (self.features,))
                if self.use_bias else None)
        if kernel.dtype == jnp.int8:
            if not self.has_variable("quant_scales", "kernel_scale"):
                raise ValueError(
                    f"QuantDense {self.name}: int8 kernel but no "
                    "'quant_scales'/'kernel_scale' variable — pass the "
                    "scales tree from module_quantize alongside params")
            scale = self.get_variable("quant_scales", "kernel_scale")
            return int8_matmul(x, kernel, scale, bias)
        if self.dtype is not None:
            x = x.astype(self.dtype)
            kernel = kernel.astype(self.dtype)
        y = x @ kernel
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y
