"""Effective-platform query for kernel dispatch.

``jax.default_backend()`` reports the process-global backend and ignores
an active ``jax.default_device(...)`` context — so on a TPU host, code
hosted onto the CPU device (e.g. the layered-offload engine's zero_init)
would still pick TPU Pallas lowering and crash with "Only interpret mode
is supported on CPU backend". Every ``interpret=`` / flash-availability
decision routes through here instead.
"""

import jax


def effective_platform() -> str:
    dd = jax.config.jax_default_device
    if dd is not None:
        return dd.platform
    return jax.default_backend()


def interpret() -> bool:
    """True when Pallas kernels must run in interpret mode (not on TPU)."""
    return effective_platform() != "tpu"
