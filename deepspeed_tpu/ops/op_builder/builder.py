"""JIT build system for native host ops.

Rebuild of op_builder/builder.py (``OpBuilder`` :119, ``jit_load`` :405):
compiles csrc/*.cpp into shared libraries with g++ on first use, caches by
source mtime, and loads them via ctypes (the reference uses torch
cpp_extension + pybind11; this build is torch-free so the ABI is plain C).
SIMD width is whatever -march=native provides (reference simd_width
detection, builder.py:318); ops degrade to scalar loops when AVX2 is
absent.
"""

import ctypes
import os
import subprocess
from pathlib import Path

from deepspeed_tpu.utils.logging import logger

CSRC = Path(__file__).resolve().parents[3] / "csrc"
BUILD_DIR = Path(os.environ.get(
    "DS_BUILD_DIR", Path.home() / ".cache" / "deepspeed_tpu" / "build"))


class OpBuilderError(RuntimeError):
    pass


class CPUOpBuilder:
    """One native op = one .cpp file compiled to one .so."""

    NAME = None
    SOURCE = None            # filename under csrc/
    EXTRA_FLAGS = []

    def source_path(self) -> Path:
        return CSRC / self.SOURCE

    def lib_path(self) -> Path:
        return BUILD_DIR / f"{self.NAME}.so"

    def is_compatible(self) -> bool:
        return self.source_path().exists() and _has_compiler()

    def needs_build(self) -> bool:
        lib, src = self.lib_path(), self.source_path()
        return (not lib.exists() or
                src.stat().st_mtime > lib.stat().st_mtime)

    def build(self) -> Path:
        BUILD_DIR.mkdir(parents=True, exist_ok=True)
        src, lib = self.source_path(), self.lib_path()
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
               "-march=native", "-fopenmp", "-pthread",
               str(src), "-o", str(lib)] + list(self.EXTRA_FLAGS)
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:  # fall back: no -march
            cmd = [c for c in cmd if c != "-march=native"]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True)
            except subprocess.CalledProcessError as e2:
                raise OpBuilderError(
                    f"building {self.NAME} failed:\n{e2.stderr}") from e2
            logger.warning(f"{self.NAME}: built without -march=native "
                           f"({e.stderr.splitlines()[-1] if e.stderr else ''})")
        return lib

    def load(self) -> ctypes.CDLL:
        """jit_load (builder.py:405): build if stale, dlopen, memoise."""
        if self.NAME in _LOADED:
            return _LOADED[self.NAME]
        if not self.is_compatible():
            raise OpBuilderError(
                f"op {self.NAME} unavailable (missing source or compiler)")
        if self.needs_build():
            logger.info(f"JIT-building native op {self.NAME}...")
            self.build()
        lib = ctypes.CDLL(str(self.lib_path()))
        self._declare(lib)
        _LOADED[self.NAME] = lib
        return lib

    def _declare(self, lib):
        """Subclasses set argtypes/restype for type safety."""


_LOADED = {}


def _has_compiler() -> bool:
    try:
        subprocess.run(["g++", "--version"], capture_output=True, check=True)
        return True
    except Exception:  # pragma: no cover
        return False


c_float_p = ctypes.POINTER(ctypes.c_float)
c_char_p = ctypes.c_char_p
i64 = ctypes.c_int64


class CPUAdamBuilder(CPUOpBuilder):
    NAME = "deepspeed_cpu_adam"
    SOURCE = "cpu_adam.cpp"

    def _declare(self, lib):
        lib.ds_adam_create.argtypes = [ctypes.c_int, ctypes.c_float,
                                       ctypes.c_float, ctypes.c_float,
                                       ctypes.c_float, ctypes.c_int]
        lib.ds_adam_create.restype = ctypes.c_int
        lib.ds_adam_step.argtypes = [ctypes.c_int, i64, ctypes.c_float,
                                     c_float_p, c_float_p, c_float_p,
                                     c_float_p, i64]
        lib.ds_adam_step.restype = ctypes.c_int
        lib.ds_adam_destroy.argtypes = [ctypes.c_int]
        lib.ds_adagrad_step.argtypes = [ctypes.c_float, ctypes.c_float,
                                        ctypes.c_float, c_float_p, c_float_p,
                                        c_float_p, i64]
        lib.ds_adagrad_step.restype = ctypes.c_int
        lib.ds_has_avx2.restype = ctypes.c_int


class AsyncIOBuilder(CPUOpBuilder):
    NAME = "deepspeed_aio"
    SOURCE = "aio.cpp"

    def _declare(self, lib):
        lib.aio_handle_create.argtypes = [ctypes.c_int] * 5
        lib.aio_handle_create.restype = i64
        lib.aio_handle_destroy.argtypes = [i64]
        for fn in (lib.aio_async_pread, lib.aio_async_pwrite,
                   lib.aio_sync_pread, lib.aio_sync_pwrite):
            fn.argtypes = [i64, ctypes.c_char_p, ctypes.c_char_p, i64, i64]
            fn.restype = i64
        lib.aio_wait.argtypes = [i64, i64]
        lib.aio_wait.restype = i64
        lib.aio_pending.argtypes = [i64]
        lib.aio_pending.restype = i64
        lib.aio_kernel_available.argtypes = [ctypes.c_char_p]
        lib.aio_kernel_available.restype = ctypes.c_int
        lib.aio_max_inflight.argtypes = []
        lib.aio_max_inflight.restype = i64
        lib.aio_reset_max_inflight.argtypes = []
        lib.aio_reset_max_inflight.restype = None


ALL_OPS = {
    "cpu_adam": CPUAdamBuilder,
    "async_io": AsyncIOBuilder,
}
