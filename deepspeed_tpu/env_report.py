"""``ds_report`` — environment / op compatibility report.

Rebuild of deepspeed/env_report.py (op compatibility table + version
info). Reports jax/TPU state and native-op build status instead of
torch/CUDA."""

import importlib
import shutil
import subprocess

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{RED}[NO]{END}"


def op_report():
    from deepspeed_tpu.ops.op_builder.builder import ALL_OPS
    max_dots = 23
    print("-" * 64)
    print("DeepSpeed-TPU native op report")
    print("-" * 64)
    print("op name" + "." * (max_dots - len("op name")) +
          " compatible | built")
    print("-" * 64)
    for name, builder_cls in ALL_OPS.items():
        b = builder_cls()
        compatible = OKAY if b.is_compatible() else NO
        built = OKAY if (b.lib_path().exists() and
                         not b.needs_build()) else NO
        print(name + "." * (max_dots - len(name)) +
              f" {compatible}  | {built}")
    # Pallas kernels are always "built" (JIT at trace time)
    for kname in ("flash_attention", "fused_layer_norm", "fused_bias_gelu",
                  "fused_softmax", "fused_adam", "fused_lamb", "quantizer"):
        print(kname + "." * (max_dots - len(kname)) +
              f" {OKAY}  | {OKAY} (pallas)")


def debug_report():
    import jax
    print("-" * 64)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 64)
    rows = [
        ("jax version", jax.__version__),
        ("default backend", jax.default_backend()),
        ("device count", jax.device_count()),
        ("devices", ", ".join(str(d) for d in jax.devices()[:8])),
        ("g++", shutil.which("g++") or "MISSING"),
    ]
    try:
        import flax
        rows.append(("flax version", flax.__version__))
    except ImportError:
        rows.append(("flax version", "MISSING"))
    import deepspeed_tpu
    rows.append(("deepspeed_tpu version",
                 getattr(deepspeed_tpu, "__version__", "0.1")))
    for name, value in rows:
        print(f"{name} {'.' * (30 - len(name))} {value}")


def telemetry_report():
    """Availability of each telemetry backend (telemetry/)."""
    print("-" * 64)
    print("DeepSpeed-TPU telemetry backend report")
    print("-" * 64)
    max_dots = 30

    def row(name, ok, note=""):
        print(name + "." * (max_dots - len(name)) +
              f" {OKAY if ok else NO}" + (f"  {note}" if note else ""))

    # pure-stdlib backends: always available
    row("trace spans (chrome json)", True)
    row("jsonl sink", True)
    row("prometheus text exporter", True)
    row("compile watch (signatures)", True)
    row("health observatory (numerics)", True,
        "(telemetry.health block; HEALTH.json forensics)")
    row("goodput ledger (wall-clock)", True,
        "(telemetry.goodput block; GOODPUT.json forensics)")
    row("async input prefetch", True,
        "(data_prefetch block; host workers + device double-buffering, "
        "multi-process device stage included)")
    try:
        from deepspeed_tpu.runtime.comm_overlap import (
            check_scheduler_flags, overlap_xla_flags)
        import jax as _jax
        backend = _jax.default_backend()
        armed = check_scheduler_flags(backend)
        row("comm overlap (bucketed psum)", True,
            "(comm_overlap block; DS_COMM_OVERLAP=1; latency-hiding "
            + ("flags armed" if armed and overlap_xla_flags(backend)
               else ("no flags needed on " + backend if armed
                     else "flags NOT armed — set XLA_FLAGS at launch"))
            + ")")
    except Exception:
        row("comm overlap (bucketed psum)", False)
    row("serving engine (paged KV)", True,
        "(serving block; continuous batching + chunked prefill + top-p)")
    row("serving observatory", True,
        "(serving.observability block; slot-step ledger + SLO rules -> "
        "SERVING_HEALTH.json)")
    row("serving prefix cache (COW)", True,
        "(serving.prefix_cache block; DS_SERVING_PREFIX_CACHE=1; "
        "refcounted block sharing + copy-on-write forks)")
    row("serving speculative decode", True,
        "(serving.speculative block; DS_SERVING_SPEC=1/0; truncated-layer "
        "self-draft + one-dispatch verify, rejections booked as "
        "drafted_rejected)")
    row("serving router (SLO-aware)", True,
        "(serving.router block; prefix-affinity placement + "
        "ttft_slo_breach failover across replicas)")
    row("fleet flight recorder", True,
        "(telemetry.fleet block; per-rank record shipping + skew/desync "
        "sentinels -> FLEET_HEALTH.json; bench_diff CLI)")
    row("goodput autotuner (2-stage)", True,
        "(autotuning block; compile-time pruning + measured probes -> "
        "TUNE_REPORT.json)")
    row("self-healing guardian", True,
        "(guardian block; anomaly->action policies: emergency ckpt, "
        "rollback, fp16 rescue, admission pause -> GUARDIAN.json)")
    row("run chronicle + incidents", True,
        "(telemetry.chronicle block; DS_TELEMETRY_CHRONICLE=1; one "
        "causal event timeline -> CHRONICLE.json, correlated "
        "root-caused incident chains -> INCIDENTS.json)")
    try:
        from deepspeed_tpu.telemetry.obs_server import get_obs_server
        srv = get_obs_server()
        live = srv is not None and not srv.report().get("closed", True)
        row("mission control (obs server + SLO)", True,
            (f"(telemetry.server block; DS_TELEMETRY_SERVER=1; live at "
             f"{srv.url} with {len(srv.providers())} provider(s))"
             if live else
             "(telemetry.server + telemetry.slo blocks; "
             "DS_TELEMETRY_SERVER=1 / DS_TELEMETRY_SLO=1; /metrics "
             "scrape + /api/report/* + burn-rate paging -> "
             "SLO_REPORT.json; not armed in this process)"))
    except Exception:
        row("mission control (obs server + SLO)", False)
    try:
        from deepspeed_tpu.telemetry.federation import FleetAggregator
        del FleetAggregator
        row("fleet federation (cross-process)", True,
            "(telemetry.federation block; DS_TELEMETRY_FEDERATION=1; "
            "peer registry + aggregator scrape -> /federation/metrics, "
            "/api/fleet/events, fleet SLO burn + cross-rank incidents "
            "-> FLEET_CONTROL.json)")
    except Exception:
        row("fleet federation (cross-process)", False)
    try:
        from deepspeed_tpu.telemetry.ledger import profiler_available
        row("jax.profiler programmatic capture", profiler_available(),
            "(goodput on-anomaly start_trace/stop_trace)")
    except Exception:
        row("jax.profiler programmatic capture", False)
    try:
        from deepspeed_tpu.telemetry.xplane import parse_xspace
        del parse_xspace
        row("step anatomy (xplane parser)", True,
            "(telemetry.anatomy block; engine.profile_step(n) -> "
            "STEP_ANATOMY.json; dependency-free .xplane.pb reader)")
    except Exception:
        row("step anatomy (xplane parser)", False)
    try:
        from deepspeed_tpu.telemetry.pprof import parse_profile
        del parse_profile
        import jax.profiler as _jp
        ok = hasattr(_jp, "device_memory_profile")
        row("memory observatory (pprof)", ok,
            "(telemetry.memory block; DS_TELEMETRY_MEMORY=1; "
            "engine.memory_report -> MEMORY_ANATOMY.json; "
            "dependency-free pprof reader)"
            if ok else "(jax.profiler.device_memory_profile missing)")
    except Exception:
        row("memory observatory (pprof)", False)
    try:
        from jax import monitoring
        row("jax.monitoring listener",
            hasattr(monitoring, "register_event_duration_secs_listener"))
    except Exception:
        row("jax.monitoring listener", False)
    try:
        from jax.profiler import TraceAnnotation  # noqa: F401
        row("jax.profiler annotations", True)
    except Exception:
        row("jax.profiler annotations", False)
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        row("device memory_stats", bool(stats),
            "" if stats else "(backend returns none; host RSS fallback)")
    except Exception:
        row("device memory_stats", False, "(host RSS fallback)")
    row("psutil (host RSS fallback)",
        importlib.util.find_spec("psutil") is not None)
    try:
        import torch.utils.tensorboard  # noqa: F401
        row("tensorboard monitor", True)
    except Exception:
        row("tensorboard monitor", False, "(csv fallback)")


def main():
    op_report()
    debug_report()
    telemetry_report()


def cli_main():
    main()


if __name__ == "__main__":
    main()
