"""Reference deepspeed/autotuning/__init__.py surface."""

from deepspeed_tpu.autotuning.autotuner import Autotuner  # noqa: F401
