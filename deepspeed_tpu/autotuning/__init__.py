"""Reference deepspeed/autotuning/__init__.py surface, plus the
TPU-native goodput-driven two-stage tuner (tune.py)."""

from deepspeed_tpu.autotuning.autotuner import Autotuner  # noqa: F401
from deepspeed_tpu.autotuning.tune import (GoodputTuner,  # noqa: F401
                                           GuidedCostModelTuner,
                                           TUNE_REPORT_SCHEMA,
                                           TuneCandidate)
