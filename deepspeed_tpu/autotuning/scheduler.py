"""Experiment scheduler (reference autotuning/scheduler.py:35
``ResourceManager``).

The reference fans experiment jobs out over a node pool, polls for
completion, and reads each experiment's metric file. The TPU-native
equivalent keeps the same lifecycle — queue experiments, run up to
``num_slots`` concurrently, collect a scalar metric per experiment — with
two runner styles:

* an in-process callable (``run_fn(exp) -> float``) — the default for
  single-host tuning where the engine is cheap to rebuild;
* a subprocess command template — the analogue of the reference launching
  ``deepspeed ...`` per experiment: each experiment gets a directory with
  its ``ds_config.json``; the command runs with DS_AUTOTUNING_EXP_DIR set
  and writes ``metric.json`` (``{"throughput": N}``) there.
"""

import json
import os
import subprocess
import threading
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


class Experiment:
    def __init__(self, exp_id: int, config: Dict):
        self.exp_id = exp_id
        self.config = config
        self.metric: Optional[float] = None
        self.error: Optional[str] = None
        self.done = False
        self.host: Optional[str] = None   # node that ran it (pool mode)

    def __repr__(self):
        return (f"Experiment({self.exp_id}, metric={self.metric}, "
                f"done={self.done})")


class ResourceManager:
    def __init__(self,
                 run_fn: Optional[Callable[[Dict], float]] = None,
                 cmd_template: Optional[List[str]] = None,
                 exps_dir: str = "autotuning_exps",
                 num_slots: int = 1,
                 metric_key: str = "throughput",
                 timeout: float = 3600.0,
                 hosts: Optional[List[str]] = None,
                 ssh_cmd: Optional[List[str]] = None):
        """``hosts``: node pool for cross-host scheduling (reference
        scheduler.py:35 reads it from the hostfile): each host runs up to
        ``num_slots`` experiments concurrently, remote ones through
        ``ssh_cmd host`` with the experiment dir on a SHARED filesystem
        (the reference's same assumption). 'localhost'/'127.0.0.1' rows
        run without ssh, so a single-host pool needs no sshd."""
        assert (run_fn is None) != (cmd_template is None), (
            "pass exactly one of run_fn (in-process) or cmd_template "
            "(subprocess)")
        assert hosts is None or cmd_template is not None, (
            "cross-host scheduling needs cmd_template (run_fn is "
            "in-process and cannot hop hosts)")
        self.run_fn = run_fn
        self.cmd_template = cmd_template
        self.exps_dir = exps_dir
        self.num_slots = max(1, num_slots)
        self.metric_key = metric_key
        self.timeout = timeout
        self.hosts = list(hosts) if hosts else None
        self.ssh_cmd = list(ssh_cmd) if ssh_cmd else [
            "ssh", "-o", "StrictHostKeyChecking=no"]
        self.experiments: List[Experiment] = []

    def _build_remote_cmd(self, host: str, exp_dir: str) -> List[str]:
        """ssh wrapper for one experiment on ``host`` (reference
        scheduler.py run_job): cd into the launch cwd on the shared fs
        and re-export the experiment dir."""
        import shlex
        inner = " ".join(
            ["cd", shlex.quote(os.getcwd()), "&&", "env",
             f"DS_AUTOTUNING_EXP_DIR={shlex.quote(exp_dir)}"]
            + [shlex.quote(c) for c in self.cmd_template])
        return self.ssh_cmd + [host, inner]

    def schedule_experiments(self, configs: List[Dict]) -> List[Experiment]:
        start = len(self.experiments)
        exps = [Experiment(start + i, cfg) for i, cfg in enumerate(configs)]
        self.experiments.extend(exps)
        return exps

    # ------------------------------------------------------------- running
    def _run_subprocess(self, exp: Experiment,
                        host: Optional[str] = None) -> float:
        exp_dir = os.path.join(self.exps_dir, f"exp_{exp.exp_id}")
        os.makedirs(exp_dir, exist_ok=True)
        with open(os.path.join(exp_dir, "ds_config.json"), "w") as f:
            json.dump(exp.config, f, indent=2)
        if host is not None and host not in ("localhost", "127.0.0.1"):
            cmd = self._build_remote_cmd(host, exp_dir)
            env = dict(os.environ)
        else:
            cmd = self.cmd_template
            env = dict(os.environ, DS_AUTOTUNING_EXP_DIR=exp_dir)
        proc = subprocess.run(cmd, env=env,
                              capture_output=True, text=True,
                              timeout=self.timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"experiment {exp.exp_id} failed "
                f"(host={host or 'local'}, rc={proc.returncode}): "
                f"{proc.stderr[-2000:]}")
        with open(os.path.join(exp_dir, "metric.json")) as f:
            return float(json.load(f)[self.metric_key])

    def _worker(self, queue: List[Experiment], lock: threading.Lock,
                host: Optional[str] = None):
        while True:
            with lock:
                if not queue:
                    return
                exp = queue.pop(0)
            try:
                if self.run_fn is not None:
                    exp.metric = float(self.run_fn(exp.config))
                else:
                    exp.metric = self._run_subprocess(exp, host=host)
            except Exception as e:  # failed experiments stay metric=None
                exp.error = str(e)
                logger.warning(f"experiment {exp.exp_id} failed: {e}")
            exp.done = True
            exp.host = host

    def run(self) -> List[Experiment]:
        """Run all scheduled-but-not-done experiments; returns them."""
        todo = [e for e in self.experiments if not e.done]
        lock = threading.Lock()
        if self.run_fn is not None and self.num_slots > 1:
            logger.warning(
                "in-process experiments share one device; forcing "
                "num_slots=1 (use cmd_template for parallel slots)")
        if self.hosts:
            # node pool: num_slots workers PER HOST, each pinned to its
            # host (reference ResourceManager node allocation)
            threads = [
                threading.Thread(target=self._worker,
                                 args=(todo, lock, host))
                for host in self.hosts for _ in range(self.num_slots)]
        else:
            slots = 1 if self.run_fn is not None else self.num_slots
            threads = [
                threading.Thread(target=self._worker, args=(todo, lock))
                for _ in range(min(slots, max(1, len(todo))))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self.experiments

    def best(self) -> Optional[Experiment]:
        done = [e for e in self.experiments if e.metric is not None]
        return max(done, key=lambda e: e.metric) if done else None
