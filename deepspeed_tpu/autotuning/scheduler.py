"""Experiment scheduler (reference autotuning/scheduler.py:35
``ResourceManager``).

The reference fans experiment jobs out over a node pool, polls for
completion, and reads each experiment's metric file. The TPU-native
equivalent keeps the same lifecycle — queue experiments, run up to
``num_slots`` concurrently, collect a scalar metric per experiment — with
two runner styles:

* an in-process callable (``run_fn(exp) -> float``) — the default for
  single-host tuning where the engine is cheap to rebuild;
* a subprocess command template — the analogue of the reference launching
  ``deepspeed ...`` per experiment: each experiment gets a directory with
  its ``ds_config.json``; the command runs with DS_AUTOTUNING_EXP_DIR set
  and writes ``metric.json`` (``{"throughput": N}``) there.
"""

import json
import os
import subprocess
import threading
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


class Experiment:
    def __init__(self, exp_id: int, config: Dict):
        self.exp_id = exp_id
        self.config = config
        self.metric: Optional[float] = None
        self.error: Optional[str] = None
        self.done = False

    def __repr__(self):
        return (f"Experiment({self.exp_id}, metric={self.metric}, "
                f"done={self.done})")


class ResourceManager:
    def __init__(self,
                 run_fn: Optional[Callable[[Dict], float]] = None,
                 cmd_template: Optional[List[str]] = None,
                 exps_dir: str = "autotuning_exps",
                 num_slots: int = 1,
                 metric_key: str = "throughput",
                 timeout: float = 3600.0):
        assert (run_fn is None) != (cmd_template is None), (
            "pass exactly one of run_fn (in-process) or cmd_template "
            "(subprocess)")
        self.run_fn = run_fn
        self.cmd_template = cmd_template
        self.exps_dir = exps_dir
        self.num_slots = max(1, num_slots)
        self.metric_key = metric_key
        self.timeout = timeout
        self.experiments: List[Experiment] = []

    def schedule_experiments(self, configs: List[Dict]) -> List[Experiment]:
        start = len(self.experiments)
        exps = [Experiment(start + i, cfg) for i, cfg in enumerate(configs)]
        self.experiments.extend(exps)
        return exps

    # ------------------------------------------------------------- running
    def _run_subprocess(self, exp: Experiment) -> float:
        exp_dir = os.path.join(self.exps_dir, f"exp_{exp.exp_id}")
        os.makedirs(exp_dir, exist_ok=True)
        with open(os.path.join(exp_dir, "ds_config.json"), "w") as f:
            json.dump(exp.config, f, indent=2)
        env = dict(os.environ, DS_AUTOTUNING_EXP_DIR=exp_dir)
        proc = subprocess.run(self.cmd_template, env=env,
                              capture_output=True, text=True,
                              timeout=self.timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"experiment {exp.exp_id} failed (rc={proc.returncode}): "
                f"{proc.stderr[-2000:]}")
        with open(os.path.join(exp_dir, "metric.json")) as f:
            return float(json.load(f)[self.metric_key])

    def _worker(self, queue: List[Experiment], lock: threading.Lock):
        while True:
            with lock:
                if not queue:
                    return
                exp = queue.pop(0)
            try:
                if self.run_fn is not None:
                    exp.metric = float(self.run_fn(exp.config))
                else:
                    exp.metric = self._run_subprocess(exp)
            except Exception as e:  # failed experiments stay metric=None
                exp.error = str(e)
                logger.warning(f"experiment {exp.exp_id} failed: {e}")
            exp.done = True

    def run(self) -> List[Experiment]:
        """Run all scheduled-but-not-done experiments; returns them."""
        todo = [e for e in self.experiments if not e.done]
        lock = threading.Lock()
        if self.run_fn is not None and self.num_slots > 1:
            logger.warning(
                "in-process experiments share one device; forcing "
                "num_slots=1 (use cmd_template for parallel slots)")
        slots = 1 if self.run_fn is not None else self.num_slots
        threads = [threading.Thread(target=self._worker, args=(todo, lock))
                   for _ in range(min(slots, max(1, len(todo))))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self.experiments

    def best(self) -> Optional[Experiment]:
        done = [e for e in self.experiments if e.metric is not None]
        return max(done, key=lambda e: e.metric) if done else None
