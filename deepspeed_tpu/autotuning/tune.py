"""Goodput-driven autotuner: compile-time pruning, measured probes, one
winning config.

The reference autotuner (PAPER.md §5, ``deepspeed/autotuning``) searches
micro-batch/ZeRO configs by launching whole trial training jobs and
grepping their profiles. This rebuild closes the same loop with the
instruments PRs 1-5 built, in two stages:

**Stage 1 — zero-execution pruning.** Every candidate is built as an
*abstract* engine (``abstract_init=True`` — no array ever materialises)
and its step program(s) are AOT ``lower().compile()``d exactly once
(``engine.lower_step_programs``, the same machinery as
``runtime/zero/aot_check.py``). The compiled artifact's
``memory_analysis`` gives the true HBM watermark — candidates that
cannot fit ``memory_headroom x budget`` are rejected with reason
``"hbm"`` having never executed an instruction — and the
``hlo_census``/``CostExplorer`` roofline ranks the survivors by
predicted cost per sample.

**Stage 2 — measured probes.** The top-K survivors (plus the base
config) run short in-process probes through the existing
``ResourceManager``: a materialised twin engine ADOPTS the stage-1
compiled artifact (``engine.adopt_compiled_step``) so the probe compiles
nothing, runs ``probe_steps`` steps, and is scored by the goodput
ledger: ``score = (step_time / goodput_fraction) / samples_per_step`` —
an input-bound or overflow-thrashing config cannot win by shrinking
device compute, because its badput inflates the score. Probe order is
the ``CostModelTuner`` family seeded with the stage-1 predictions
(``GuidedCostModelTuner``), and measured scores feed back into the
model. Engines are fully torn down between probes (``engine.close()``
joins prefetch/checkpoint/ledger threads and drops the AOT artifacts).

The run emits ``TUNE_REPORT.json`` — every candidate with its
pruned/probed status, reject reason, predicted cost and measured
goodput-scored step time — plus the winning full config dict.

CLI::

    python -m deepspeed_tpu.autotuning.tune --config ds_config.json

reads the ``autotuning`` config block (see CONFIG.md) and runs the demo
model factories; library users call ``GoodputTuner`` with their own
``model_factory`` / ``make_batch`` / ``data_factory``.
"""

import copy
import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.autotuning.autotuner import Autotuner, CostModelTuner
from deepspeed_tpu.utils.logging import logger

TUNE_REPORT_SCHEMA = "deepspeed_tpu.tune_report/1"

# dims with engine-level meaning beyond a dotted config path
SPECIAL_DIMS = ("micro_batch", "gas", "zero_stage", "prefetch_depth")

# relative-ranking pseudo peaks for chips the explorer cannot identify
# (CPU test meshes): absolute seconds are meaningless there, but the
# per-candidate ORDER is still driven by the censused flops/bytes/wire
_PSEUDO_PEAKS = {"peak_tflops": 1.0, "peak_hbm_gbps": 100.0,
                 "ici_gbps": 25.0}


class GuidedCostModelTuner(CostModelTuner):
    """``CostModelTuner`` whose cold-start picks follow the stage-1
    predicted-cost prior (best-predicted first) instead of random, and
    whose feature matrix carries the prediction as an extra column so
    the ridge / boosted-tree model can calibrate the static roofline
    against measured goodput as probes accrue. A (seeded) epsilon of
    random exploration survives from the base class as the escape hatch
    from a miscalibrated prior; probe budgets are small, so the default
    is leaner than the base's 0.2."""

    def __init__(self, configs: List[Dict], prior_costs: List[float],
                 seed: int = 0, explore_ratio: float = 0.1):
        super().__init__(configs, seed=seed, explore_ratio=explore_ratio)
        assert len(prior_costs) == len(configs)
        self.prior = [float(p) for p in prior_costs]
        self.X = np.concatenate(
            [self.X, np.asarray(self.prior, np.float64)[:, None]], axis=1)
        self.keys = list(self.keys) + ["predicted_cost"]

    def next(self) -> Optional[Dict]:
        rest = self._unvisited()
        if not rest:
            return None
        if len(self.xs) < self.INIT_NUM:
            idx = min(rest, key=lambda i: self.prior[i])
        elif self.explore_ratio and \
                self.rng.random() < self.explore_ratio:
            # genuine exploration (the base class's epsilon) — an escape
            # hatch from a miscalibrated static prior, NOT another
            # prior-greedy pick
            idx = self.rng.choice(rest)
        else:
            self.model.fit(self.X[self.xs], np.asarray(self.ys))
            pred = self.model.predict(self.X[rest])
            idx = rest[int(np.argmax(pred))]
        self.visited.add(idx)
        self._pending = idx
        return self.configs[idx]

    def mark_measured(self, config: Dict, perf: Optional[float]):
        """Record a measurement taken OUTSIDE the next() protocol (the
        forced base-config probe) so the model still learns from it."""
        for i, c in enumerate(self.configs):
            if c is config:
                self.visited.add(i)
                self._pending = i
                self.update(config, perf)
                return
        raise ValueError("mark_measured: config is not in the space")


class TuneCandidate:
    """One point of the declared space: overrides + the derived full
    config, stage-1 artifacts and results, stage-2 probe results."""

    def __init__(self, cand_id: int, overrides: Dict[str, Any],
                 config: Dict, model_kwargs: Dict[str, Any]):
        self.id = cand_id
        self.overrides = overrides
        self.config = config
        self.model_kwargs = model_kwargs
        self.status = "pending"
        self.reject_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.compiled: Optional[Dict[str, Any]] = None   # name -> Compiled
        self.programs: List[str] = []
        self.hbm_watermark_bytes: Optional[int] = None
        self.predicted_step_s: Optional[float] = None
        self.predicted_cost_s_per_sample: Optional[float] = None
        self.predicted_rank: Optional[int] = None
        self.probe: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "overrides": self.overrides,
            "status": self.status,
            "reject_reason": self.reject_reason,
            "error": self.error,
            "programs": self.programs,
            "hbm_watermark_bytes": self.hbm_watermark_bytes,
            "predicted_step_s": self.predicted_step_s,
            "predicted_cost_s_per_sample": self.predicted_cost_s_per_sample,
            "predicted_rank": self.predicted_rank,
            "probe": self.probe,
        }


def _set_dotted(cfg: Dict, dotted: str, value):
    node = cfg
    parts = dotted.split(".")
    for k in parts[:-1]:
        node = node.setdefault(k, {})
    node[parts[-1]] = value


class GoodputTuner:
    """Two-stage goodput-driven config search. See the module docstring.

    Parameters
    ----------
    model_factory: ``model_factory(**model_kwargs) -> flax module`` —
        ``model_kwargs`` come from ``model.<kwarg>`` space dims (remat,
        attention impl, ...); called once per trial engine.
    make_batch: ``make_batch(per_dispatch_batch_size) -> batch`` — one
        synthetic batch at the per-dispatch size (micro_batch x dp);
        used for engine shape init and stage-1 lowering.
    base_config: the user's DeepSpeed config dict — candidate 0, the
        yardstick the winner must beat.
    data_factory: optional ``data_factory(per_dispatch_batch_size) ->
        iterable of batches`` feeding the measured probes (pass the real
        input pipeline here so goodput scoring sees real input
        behavior); defaults to repeating ``make_batch``'s batch.
    space: ``{dim: [values]}`` — ``micro_batch`` / ``gas`` /
        ``zero_stage`` / ``prefetch_depth`` are engine-level dims,
        ``model.<kwarg>`` dims go to ``model_factory``, anything else is
        a dotted config path.

    Remaining knobs mirror the ``autotuning`` config block and are
    overridden by it when ``from_config`` is used.
    """

    def __init__(self,
                 model_factory: Callable[..., Any],
                 make_batch: Callable[[int], Any],
                 base_config: Dict,
                 data_factory: Optional[Callable[[int], Any]] = None,
                 space: Optional[Dict[str, List]] = None,
                 metric: str = "goodput",
                 top_k: int = 3,
                 probe_steps: int = 8,
                 probe_warmup_steps: int = 2,
                 memory_headroom: float = 0.95,
                 hbm_budget_bytes: Optional[int] = None,
                 results_dir: str = "autotuning_results",
                 report_file: str = "TUNE_REPORT.json",
                 seed: int = 0):
        self.model_factory = model_factory
        self.make_batch = make_batch
        self.base_config = base_config
        self.data_factory = data_factory or self._default_data_factory
        self.space = dict(space or {})
        assert metric in ("goodput", "step_time"), metric
        self.metric = metric
        self.top_k = int(top_k)
        self.probe_steps = int(probe_steps)
        self.probe_warmup_steps = int(probe_warmup_steps)
        self.memory_headroom = float(memory_headroom)
        self._budget_explicit = hbm_budget_bytes is not None
        self.hbm_budget_bytes = int(
            hbm_budget_bytes if hbm_budget_bytes is not None
            else Autotuner._detect_device_memory())
        self.results_dir = results_dir
        self.report_file = report_file
        self.seed = int(seed)
        self.candidates: List[TuneCandidate] = []
        self._compiles = {"train_step": 0, "aux": 0}
        self._probe_extra_compiles = 0
        self._by_cfg_id: Dict[int, TuneCandidate] = {}

    @classmethod
    def from_config(cls, base_config: Dict, model_factory, make_batch,
                    data_factory=None, space=None, **overrides):
        """Build from the ``autotuning`` block inside ``base_config``
        (env overrides already applied by the config parser); explicit
        kwargs win over the block."""
        from deepspeed_tpu.runtime.config import DeepSpeedAutotuningConfig
        at = DeepSpeedAutotuningConfig(base_config
                                       if isinstance(base_config, dict)
                                       else {})
        kw = dict(
            space=space if space is not None else at.space,
            metric=at.metric, top_k=at.top_k,
            probe_steps=at.probe_steps,
            probe_warmup_steps=at.probe_warmup_steps,
            memory_headroom=at.memory_headroom,
            hbm_budget_bytes=(int(at.hbm_budget_gb * 1024 ** 3)
                              if at.hbm_budget_gb else None),
            results_dir=at.results_dir, report_file=at.report_file,
            seed=at.seed)
        kw.update(overrides)
        return cls(model_factory, make_batch, base_config,
                   data_factory=data_factory, **kw)

    # ------------------------------------------------------------ space
    def _default_data_factory(self, batch_size):
        batch = self.make_batch(batch_size)

        def _repeat():
            while True:
                yield batch
        return _repeat()

    def _dp_world(self) -> int:
        import jax
        from deepspeed_tpu.utils import groups
        if groups.mesh_is_initialized():
            return groups.get_data_parallel_world_size()
        return jax.device_count()

    def build_candidates(self) -> List[TuneCandidate]:
        """Base config (id 0, empty overrides) + the cartesian product
        of the declared space, deduplicated against the base."""
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        dp = self._dp_world()
        parsed = DeepSpeedConfig(copy.deepcopy(self.base_config),
                                 data_parallel_size=dp)
        base_micro = parsed.train_micro_batch_size_per_gpu
        base_gas = parsed.gradient_accumulation_steps

        def derive(overrides: Dict[str, Any]) -> (Dict, Dict):
            cfg = copy.deepcopy(self.base_config)
            micro = int(overrides.get("micro_batch", base_micro))
            gas = int(overrides.get("gas", base_gas))
            cfg["train_micro_batch_size_per_gpu"] = micro
            cfg["gradient_accumulation_steps"] = gas
            cfg["train_batch_size"] = micro * gas * dp
            model_kwargs = {}
            for key, val in overrides.items():
                if key in ("micro_batch", "gas"):
                    continue
                if key == "zero_stage":
                    cfg["zero_optimization"] = dict(
                        cfg.get("zero_optimization", {}) or {},
                        stage=int(val))
                elif key == "prefetch_depth":
                    cfg["data_prefetch"] = dict(
                        cfg.get("data_prefetch", {}) or {},
                        enabled=int(val) > 0, depth=max(int(val), 1))
                elif key.startswith("model."):
                    model_kwargs[key[len("model."):]] = val
                else:
                    _set_dotted(cfg, key, val)
            return cfg, model_kwargs

        def cand_sig(cfg, mk):
            # dedup on the PARSED config, not the raw dict: an override
            # that merely materialises a block the base omits (zero
            # stage 0, prefetch off, ...) is the SAME trial and must not
            # burn a compile or a probe slot on a duplicate. The parser
            # normalises every schema default; unparseable candidates
            # fall back to the raw text (stage 1 will record the error).
            try:
                parsed = DeepSpeedConfig(copy.deepcopy(cfg),
                                         data_parallel_size=dp)
                body = {k: v for k, v in parsed.__dict__.items()
                        if not k.startswith("_")}
            except Exception:
                body = cfg
            return json.dumps(body, sort_keys=True, default=repr) + \
                json.dumps(mk, sort_keys=True, default=repr)

        cands = [TuneCandidate(0, {}, *derive({}))]
        seen = {cand_sig(cands[0].config, cands[0].model_kwargs)}
        keys = sorted(self.space)
        for combo in itertools.product(*[self.space[k] for k in keys]):
            overrides = dict(zip(keys, combo))
            cfg, mk = derive(overrides)
            sig = cand_sig(cfg, mk)
            if sig in seen:
                continue
            seen.add(sig)
            cands.append(TuneCandidate(len(cands), overrides, cfg, mk))
        self.candidates = cands
        self._by_cfg_id = {id(c.config): c for c in cands}
        return cands

    # ---------------------------------------------------------- stage 1
    def _dispatch_batch_size(self, cand: TuneCandidate) -> int:
        return int(cand.config["train_micro_batch_size_per_gpu"]) * \
            self._dp_world()

    def _ranking_explorer(self):
        """One CostExplorer for the whole run; unknown chips (CPU test
        meshes) get pseudo peaks so ranking still works."""
        if getattr(self, "_explorer", None) is None:
            from deepspeed_tpu.telemetry.cost_explorer import CostExplorer
            ex = CostExplorer()
            if not ex.peak_tflops:
                ex.peak_tflops = _PSEUDO_PEAKS["peak_tflops"]
            if not ex.peak_hbm_gbps:
                ex.peak_hbm_gbps = _PSEUDO_PEAKS["peak_hbm_gbps"]
            if not ex.ici_gbps:
                ex.ici_gbps = _PSEUDO_PEAKS["ici_gbps"]
            self._explorer = ex
        return self._explorer

    def _predicted_step_seconds(self, census, invocations: int) -> float:
        """Roofline floor of one global step: the max of the compute /
        memory / comm lower bounds (the census covers ONE dispatch;
        ``invocations`` = gas scales it to the full step)."""
        ex = self._ranking_explorer()
        flops = census.flops * invocations
        nbytes = census.bytes_accessed * invocations
        wire = census.total_wire_bytes * invocations
        floors = [flops / (ex.peak_tflops * 1e12),
                  nbytes / (ex.peak_hbm_gbps * 1e9)]
        if wire:
            floors.append(wire / (ex.ici_gbps * 1e9))
        return max(floors)

    def _stage1_config(self, cand: TuneCandidate) -> Dict:
        """The abstract twin's config: telemetry stripped (no manager
        side effects; abstract engines never own an artifact anyway)."""
        cfg = copy.deepcopy(cand.config)
        cfg.pop("telemetry", None)
        return cfg

    def _stage1_compile(self, cand: TuneCandidate):
        """Abstract-build the candidate, AOT-compile its step program(s)
        ONCE, census + HBM-prune + rank. Zero device execution: the
        engine is ``abstract_init`` — no parameter, batch or state array
        ever materialises on a device."""
        import deepspeed_tpu
        from deepspeed_tpu.telemetry.hlo_census import census_compiled
        batch = self.make_batch(self._dispatch_batch_size(cand))
        engine = None
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model_factory(**cand.model_kwargs),
                config=self._stage1_config(cand),
                sample_batch=batch, abstract_init=True, seed=self.seed)
            lowereds = engine.lower_step_programs(batch)
            cand.programs = sorted(lowereds)
            compiled, censuses = {}, {}
            for name, low in lowereds.items():
                compiled[name] = low.compile()
                key = ("train_step"
                       if name in ("fused_train_step", "micro_step")
                       else "aux")
                self._compiles[key] += 1
                censuses[name] = census_compiled(compiled[name],
                                                 mesh=engine.mesh)
            cand.compiled = compiled
            main = ("fused_train_step" if "fused_train_step" in compiled
                    else "micro_step")
            # peak static watermark over every program the step runs
            cand.hbm_watermark_bytes = max(
                c.hbm_watermark_bytes for c in censuses.values())
            limit = self.hbm_budget_bytes * self.memory_headroom
            if cand.hbm_watermark_bytes > limit:
                cand.status = "pruned"
                cand.reject_reason = "hbm"
                cand.compiled = None        # pruned: drop the artifact
                logger.info(
                    "[autotune] candidate %d %s PRUNED at compile time: "
                    "HBM watermark %.3f GiB > %.2f x %.3f GiB budget",
                    cand.id, cand.overrides,
                    cand.hbm_watermark_bytes / 1024 ** 3,
                    self.memory_headroom,
                    self.hbm_budget_bytes / 1024 ** 3)
                return
            gas = int(cand.config.get("gradient_accumulation_steps", 1))
            cand.predicted_step_s = self._predicted_step_seconds(
                censuses[main], gas)
            cand.predicted_cost_s_per_sample = (
                cand.predicted_step_s
                / int(cand.config["train_batch_size"]))
            cand.status = "survivor"
        except Exception as e:
            cand.status = "failed"
            cand.error = f"{type(e).__name__}: {e}"
            cand.compiled = None
            logger.warning("[autotune] candidate %d %s failed stage 1: %s",
                           cand.id, cand.overrides, cand.error)
        finally:
            if engine is not None:
                engine.close()

    # ---------------------------------------------------------- stage 2
    def _trial_config(self, cand: TuneCandidate) -> Dict:
        """The materialised probe's config: force-enable the cost
        explorer (so the engine owns an ``_AOTStep`` to adopt the
        stage-1 artifact into) and the goodput ledger (the probe's
        score); snapshots/rules are pointed away from the run's cwd and
        the cadence is pushed past the probe so no window machinery
        fires mid-measurement."""
        cfg = copy.deepcopy(cand.config)
        cfg.setdefault("steps_per_print", 10 ** 9)
        tel = dict(cfg.get("telemetry", {}) or {})
        tel["enabled"] = True
        tel.setdefault("trace", False)
        tel.setdefault("jsonl", False)
        tel.setdefault("prometheus", False)
        tel["cost_explorer"] = dict(tel.get("cost_explorer", {}) or {},
                                    enabled=True)
        # the stage-1 artifact was compiled WITHOUT the health stats
        # variant (abstract engines force it off) — a probe engine with
        # health on would unpack one more output than the adopted
        # program returns; probes are measurements, not health runs
        tel["health"] = {"enabled": False}
        tel["goodput"] = dict(
            tel.get("goodput", {}) or {},
            enabled=True, profiler_capture=False,
            snapshot_file=os.path.join(self.results_dir,
                                       f"trial_{cand.id}_GOODPUT.json"))
        cfg["telemetry"] = tel
        return cfg

    def _probe_run_fn(self, config: Dict) -> float:
        """ResourceManager entry point: config -> goodput-scored
        samples/sec (HIGHER is better, the scheduler/tuner convention);
        details land on the candidate."""
        cand = self._by_cfg_id.get(id(config))
        assert cand is not None, "probe config not from this tuner's space"
        return self._run_probe(cand)

    def _run_probe(self, cand: TuneCandidate) -> float:
        """One measured probe: materialised engine, stage-1 artifact
        adopted (nothing compiles), ``probe_warmup_steps`` then
        ``probe_steps`` timed steps, scored by the ledger's goodput
        fraction over exactly the timed window. The engine is fully torn
        down afterwards."""
        import jax
        import deepspeed_tpu
        from deepspeed_tpu.telemetry.ledger import GoodputLedger
        bs = self._dispatch_batch_size(cand)
        engine = None
        try:
            batch = self.make_batch(bs)
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model_factory(**cand.model_kwargs),
                config=self._trial_config(cand),
                sample_batch=batch, seed=self.seed)
            adopted = []
            # a health-variant engine (DS_TELEMETRY_HEALTH=1 overrides
            # the trial config's force-off) returns MORE outputs than
            # the health-off stage-1 artifact — skip adoption and let
            # the probe compile its own variant (the report's compile
            # accounting records the fallback honestly)
            if cand.compiled and not engine._health_on:
                adopted = sorted(engine.adopt_compiled_step(
                    cand.compiled, batch=batch))
            data_iter = iter(self.data_factory(bs))
            for _ in range(self.probe_warmup_steps):
                engine.train_batch(data_iter=data_iter)
            jax.block_until_ready(jax.tree.leaves(engine.state.params))
            led = engine._goodput
            led_on = led is not None and led.enabled
            t0 = time.perf_counter()
            el0 = led.elapsed() if led_on else 0.0
            tot0 = led.totals() if led_on else {}
            for _ in range(self.probe_steps):
                engine.train_batch(data_iter=data_iter)
            jax.block_until_ready(jax.tree.leaves(engine.state.params))
            wall_s = time.perf_counter() - t0
            step_time_s = wall_s / self.probe_steps
            goodput_fraction = None
            window = None
            if led_on:
                dur = led.elapsed() - el0
                tot1 = led.totals()
                window = {c: round(tot1[c] - tot0.get(c, 0.0), 6)
                          for c in tot1}
                goodput_fraction = GoodputLedger.goodput_fraction(
                    window, dur)
            goodput_scored = (self.metric == "goodput"
                              and goodput_fraction is not None)
            if goodput_scored:
                goodput_step_time_s = step_time_s / max(
                    goodput_fraction, 1e-3)
            else:
                goodput_step_time_s = step_time_s
            samples = int(engine.train_batch_size())
            score = goodput_step_time_s / samples
            # compile accounting: EVERY stage-1 program must have been
            # executed from its adopted artifact, never a fresh compile
            # — checked per program (an apply_step that silently
            # recompiled would otherwise hide behind the main program's
            # clean receipt)
            reused = bool(cand.compiled)
            fallbacks = 0
            for name, comp in (cand.compiled or {}).items():
                aot = engine._aot_step_for(name)
                if aot is None or aot.compiled is not comp:
                    reused = False
                if aot is not None:
                    fallbacks += int(aot.fallback_calls)
            if cand.compiled and not reused:
                self._probe_extra_compiles += 1
            self._probe_extra_compiles += fallbacks
            cand.probe = {
                "steps": self.probe_steps,
                "warmup_steps": self.probe_warmup_steps,
                "step_time_s": round(step_time_s, 6),
                "goodput_fraction": (round(goodput_fraction, 6)
                                     if goodput_fraction is not None
                                     else None),
                "goodput_scored": goodput_scored,
                "goodput_step_time_s": round(goodput_step_time_s, 6),
                "score_s_per_sample": score,
                "samples_per_sec": round(samples / goodput_step_time_s, 3),
                "categories_s": window,
                "adopted": adopted,
                "artifact_reused": reused,
                "aot_fallback_calls": fallbacks,
            }
            # measured residency (memory observatory armed via the trial
            # config / DS_TELEMETRY_MEMORY): record the measured peak and
            # its drift against THIS candidate's stage-1 watermark, so
            # "hbm" rejections become calibratable against real bytes
            mem = engine._memory
            if mem is not None:
                engine._memory_tick(force=True)
                if mem.measured_peak_bytes:
                    cand.probe["hbm_peak_bytes"] = mem.measured_peak_bytes
                    if cand.hbm_watermark_bytes:
                        n_dev = len(jax.local_devices())
                        cand.probe["watermark_drift"] = round(
                            mem.measured_peak_bytes
                            / (cand.hbm_watermark_bytes * n_dev) - 1.0, 4)
            cand.status = "probed"
            logger.info(
                "[autotune] probe %d %s: step %.2f ms, goodput %.3f -> "
                "scored %.2f ms (%.1f samples/s)",
                cand.id, cand.overrides, step_time_s * 1e3,
                goodput_fraction if goodput_fraction is not None else -1,
                goodput_step_time_s * 1e3, samples / goodput_step_time_s)
            return samples / goodput_step_time_s
        finally:
            if engine is not None:
                engine.close()

    # ------------------------------------------------------------- tune
    def tune(self):
        """Run both stages; returns ``(best_config_dict, report_dict)``
        and writes ``report_file``."""
        from deepspeed_tpu.autotuning.scheduler import ResourceManager
        os.makedirs(self.results_dir, exist_ok=True)
        if not self.candidates:
            self.build_candidates()
        t_start = time.perf_counter()

        # ---- stage 1: compile/prune/rank, zero device execution ------
        for cand in self.candidates:
            self._stage1_compile(cand)
        survivors = [c for c in self.candidates if c.status == "survivor"]
        if not survivors:
            self._write_report(None, time.perf_counter() - t_start)
            raise RuntimeError(
                "autotuning: no candidate survived compile-time pruning "
                f"(budget {self.hbm_budget_bytes / 1024 ** 3:.3f} GiB x "
                f"{self.memory_headroom} headroom) — see "
                f"{self.report_file}")
        for rank, cand in enumerate(sorted(
                survivors, key=lambda c: c.predicted_cost_s_per_sample)):
            cand.predicted_rank = rank
        logger.info(
            "[autotune] stage 1: %d candidates -> %d pruned (hbm), "
            "%d failed, %d survivors",
            len(self.candidates),
            sum(c.status == "pruned" for c in self.candidates),
            sum(c.status == "failed" for c in self.candidates),
            len(survivors))

        # ---- stage 2: measured probes through the ResourceManager ----
        rm = ResourceManager(run_fn=self._probe_run_fn,
                             exps_dir=os.path.join(self.results_dir,
                                                   "exps"))
        tuner = GuidedCostModelTuner(
            [c.config for c in survivors],
            [c.predicted_cost_s_per_sample for c in survivors],
            seed=self.seed)

        def probe(cand, via_tuner):
            exp = rm.schedule_experiments([cand.config])[0]
            rm.run()
            if exp.metric is None:
                cand.status = "probe_failed"
                cand.error = exp.error
            if via_tuner:
                tuner.update(cand.config, exp.metric)
            else:
                tuner.mark_measured(cand.config, exp.metric)
            return exp.metric

        base = self.candidates[0]
        if base.status == "survivor":
            # the yardstick is probed unconditionally — the report's
            # "winner beats base" claim needs a measured base
            probe(base, via_tuner=False)
        probed_nonbase = 0
        while probed_nonbase < self.top_k:
            cfg = tuner.next()
            if cfg is None:
                break
            cand = self._by_cfg_id[id(cfg)]
            if probe(cand, via_tuner=True) is not None:
                # a crashed probe must not consume a measurement slot —
                # the tuner's visited set already prevents re-picking
                # it, so the next-best survivor gets the probe instead
                probed_nonbase += 1
        for cand in survivors:
            if cand.status == "survivor":
                cand.status = "ranked_out"

        probed = [c for c in self.candidates if c.status == "probed"]
        if not probed:
            self._write_report(None, time.perf_counter() - t_start)
            raise RuntimeError("autotuning: every probe failed — see "
                               f"{self.report_file}")
        winner = min(probed, key=lambda c: c.probe["score_s_per_sample"])
        report = self._write_report(winner,
                                    time.perf_counter() - t_start)
        for cand in self.candidates:    # artifacts served their purpose
            cand.compiled = None
        return winner.config, report

    def _write_report(self, winner, elapsed_s):
        ex = self._ranking_explorer()
        base = self.candidates[0] if self.candidates else None
        base_probe = base.probe if base is not None else None
        winner_entry = None
        if winner is not None:
            vs_base = None
            if base_probe and winner is not base:
                vs_base = round(base_probe["score_s_per_sample"]
                                / winner.probe["score_s_per_sample"], 4)
            elif winner is base:
                vs_base = 1.0
            winner_entry = {
                "id": winner.id,
                "overrides": winner.overrides,
                "score_s_per_sample": winner.probe["score_s_per_sample"],
                "goodput_fraction": winner.probe["goodput_fraction"],
                "vs_base_speedup": vs_base,
                "config": winner.config,
            }
        report = {
            "schema": TUNE_REPORT_SCHEMA,
            "generated_by": "deepspeed_tpu.autotuning.tune",
            "metric": self.metric,
            "elapsed_s": round(elapsed_s, 3),
            "dp_world": self._dp_world(),
            "device": {
                "device_kind": ex.device_kind,
                "memory_budget_bytes": self.hbm_budget_bytes,
                "memory_headroom": self.memory_headroom,
                "budget_source": ("explicit" if self._budget_explicit
                                  else "detected"),
            },
            "space": {k: list(v) for k, v in self.space.items()},
            "n_candidates": len(self.candidates),
            "stage1": {
                "pruned": sum(c.status == "pruned"
                              for c in self.candidates),
                "failed": sum(c.status == "failed"
                              for c in self.candidates),
                "survivors": sum(c.status in ("survivor", "ranked_out",
                                              "probed", "probe_failed")
                                 for c in self.candidates),
            },
            "stage2": {
                "probed": sum(c.status == "probed"
                              for c in self.candidates),
                "probe_failed": sum(c.status == "probe_failed"
                                    for c in self.candidates),
                "probe_steps": self.probe_steps,
                "probe_warmup_steps": self.probe_warmup_steps,
                "top_k": self.top_k,
            },
            "compile": {
                "train_step_compiles": self._compiles["train_step"],
                "aux_program_compiles": self._compiles["aux"],
                "candidates_compiled": sum(
                    c.hbm_watermark_bytes is not None
                    for c in self.candidates),
                "probe_train_step_compiles": self._probe_extra_compiles,
            },
            "candidates": [c.to_dict() for c in self.candidates],
            "winner": winner_entry,
        }
        path = self.report_file
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(report, f, indent=1, default=repr, allow_nan=False)
        return report


# ------------------------------------------------------- serving tuning
SERVING_TUNE_SCHEMA = "deepspeed_tpu.serving_tune/1"

# the serving knobs worth searching: block granularity (sharing vs
# fragmentation), static batch width, multi-step decode amortisation,
# and prefill chunk (TTFT vs decode-stall). Values are demo-scale —
# callers pass their own space for real models.
SERVING_SEARCH_SPACE = {
    "block_size": [8, 16],
    "max_batch": [2, 4],
    "decode_steps": [1, 4],
    "prefill_chunk": [8, 32],
}


def tune_serving(engine, requests, space=None, ttft_slo_ms=None,
                 base_config=None, report_file=None):
    """Pick a serving config by replaying a request trace: tok/s under a
    TTFT constraint.

    Unlike the training tuner there is no AOT pruning stage — a serving
    candidate's programs are tiny (one decode step + one prefill chunk)
    and the real cost differences (preemption churn, chunk/TTFT
    tradeoff, multi-step frozen units) only show up by RUNNING the
    trace. So: full grid over ``space`` (default
    ``SERVING_SEARCH_SPACE``), one fresh ``ServingEngine`` per candidate
    over the SAME live ``InferenceEngine`` (weights are shared; pools
    are rebuilt per candidate and torn down after), each replaying
    ``requests`` (a list of ``submit()``-kwargs dicts, e.g. from
    ``tests/perf/serving_bench.py``'s trace generator).

    Scoring: generated tok/s, with candidates whose TTFT p50 exceeds
    ``ttft_slo_ms`` rejected (reason ``"ttft"``). If EVERY candidate
    breaches the constraint the best tok/s survivor still wins (flagged
    ``feasible: false``) — a router would rather run a breaching replica
    than no replica. Returns ``(winner_config, report)``; the per-replica
    entry point for heterogeneous router fleets."""
    from deepspeed_tpu.serving.server import ServingEngine
    from deepspeed_tpu.telemetry.metrics import MetricsRegistry

    space = dict(space or SERVING_SEARCH_SPACE)
    dims = sorted(space)
    requests = list(requests)
    t_start = time.perf_counter()
    entries = []
    for values in itertools.product(*(space[d] for d in dims)):
        overrides = dict(zip(dims, values))
        cand_cfg = {**(base_config or {}), **overrides}
        entry = {"config": cand_cfg, "status": "probed",
                 "reject_reason": None}
        entries.append(entry)
        srv = ServingEngine(engine, config=copy.deepcopy(cand_cfg),
                            registry=MetricsRegistry())
        try:
            t0 = time.perf_counter()
            for kw in requests:
                srv.submit(**kw)
            outs = srv.serve_forever()
            elapsed = time.perf_counter() - t0
        finally:
            srv.close()
        tokens = sum(len(o.tokens) for o in outs)
        ttfts = sorted(o.ttft_s for o in outs if o.ttft_s is not None)
        p50_ms = (1000.0 * ttfts[len(ttfts) // 2]) if ttfts else None
        entry.update({
            "tokens": tokens,
            "elapsed_s": round(elapsed, 6),
            "tokens_per_s": round(tokens / elapsed, 3) if elapsed else 0.0,
            "ttft_p50_ms": None if p50_ms is None else round(p50_ms, 3),
            "preemptions": sum(o.preemptions for o in outs),
        })
        if ttft_slo_ms is not None and p50_ms is not None \
                and p50_ms > ttft_slo_ms:
            entry["status"] = "rejected"
            entry["reject_reason"] = "ttft"
        logger.info("tune_serving %s: %.1f tok/s ttft_p50 %s ms%s",
                    overrides, entry["tokens_per_s"], entry["ttft_p50_ms"],
                    " (REJECTED: ttft)" if entry["status"] == "rejected"
                    else "")
    feasible = [e for e in entries if e["status"] == "probed"]
    pool = feasible or entries
    winner = max(pool, key=lambda e: e["tokens_per_s"])
    report = {
        "schema": SERVING_TUNE_SCHEMA,
        "space": space,
        "ttft_slo_ms": ttft_slo_ms,
        "requests": len(requests),
        "elapsed_s": round(time.perf_counter() - t_start, 3),
        "candidates": entries,
        "winner": {"config": winner["config"],
                   "tokens_per_s": winner["tokens_per_s"],
                   "ttft_p50_ms": winner["ttft_p50_ms"],
                   "feasible": bool(feasible)},
    }
    if report_file:
        d = os.path.dirname(report_file)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(report_file, "w") as f:
            json.dump(report, f, indent=1, default=repr, allow_nan=False)
    return winner["config"], report


# ------------------------------------------------------------------ CLI
def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.autotuning.tune",
        description="Goodput-driven autotuner: compile-time pruning + "
                    "measured probes over a demo model (library users "
                    "call GoodputTuner with their own factories)")
    parser.add_argument("--config", help="DeepSpeed config JSON (with an "
                        "optional 'autotuning' block)")
    parser.add_argument("--model", default="simple",
                        choices=("simple", "linear"))
    parser.add_argument("--hidden", type=int, default=128)
    parser.add_argument("--nlayers", type=int, default=2)
    parser.add_argument("--space", help="JSON search space, e.g. "
                        "'{\"micro_batch\": [1, 4, 16]}'")
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--probe-steps", type=int, default=None)
    parser.add_argument("--hbm-budget-gb", type=float, default=None)
    parser.add_argument("--out", default=None,
                        help="report path (overrides the config block)")
    args = parser.parse_args(argv)

    if args.config:
        with open(args.config) as f:
            base = json.load(f)
    else:
        import jax
        base = {"train_batch_size": jax.device_count(),
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}

    from deepspeed_tpu.models.simple import (LinearStack, SimpleModel,
                                             sample_batch)
    hidden, nlayers = args.hidden, args.nlayers
    if args.model == "simple":
        def model_factory(**kw):
            return SimpleModel(hidden_dim=hidden,
                               nlayers=kw.get("nlayers", nlayers))
    else:
        def model_factory(**kw):
            return LinearStack(input_dim=hidden, hidden_dim=hidden,
                               output_dim=hidden,
                               num_layers=kw.get("num_layers", nlayers))

    def make_batch(bs):
        return tuple(np.asarray(x) for x in sample_batch(bs, hidden))

    space = json.loads(args.space) if args.space else None
    overrides = {}
    if args.top_k is not None:
        overrides["top_k"] = args.top_k
    if args.probe_steps is not None:
        overrides["probe_steps"] = args.probe_steps
    if args.hbm_budget_gb is not None:
        overrides["hbm_budget_bytes"] = int(
            args.hbm_budget_gb * 1024 ** 3)
    if args.out:
        overrides["report_file"] = args.out
    if space is None and not (base.get("autotuning", {}) or {}).get("space"):
        space = {"micro_batch": [1, 4, 16]}
    tuner = GoodputTuner.from_config(base, model_factory, make_batch,
                                     space=space, **overrides)
    best, report = tuner.tune()
    w = report["winner"]
    print(json.dumps({
        "winner_overrides": w["overrides"],
        "score_s_per_sample": w["score_s_per_sample"],
        "vs_base_speedup": w["vs_base_speedup"],
        "pruned": report["stage1"]["pruned"],
        "probed": report["stage2"]["probed"],
        "report": tuner.report_file}, indent=1))
    return best


if __name__ == "__main__":
    main()
