"""Autotuner — config search over ZeRO stage and micro-batch size.

Rebuild of deepspeed/autotuning/ (``Autotuner`` autotuner.py:29, tuners
tuner/index_based_tuner.py:8/:23 + model_based_tuner.py:16, scheduler
scheduler.py:35). The reference forks whole training jobs per experiment
across a node pool and greps profiling jsons; here experiments run
in-process (the engine is cheap to rebuild under jax) on THIS host:

1. model-info: param count → per-stage memory model
   (runtime/zero/partition.py estimate_zero_mem — the reference's
   ``model_info_profile_run`` :664);
2. prune ZeRO stages whose state cannot fit device memory;
3. per surviving stage, search micro-batch sizes (fastest-first order by
   the tuner policy) with short timed runs;
4. emit the best config + all measurements (autotuning_results layout).
"""

import json
import os
import random as _random
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.runtime.zero.partition import estimate_zero_mem
from deepspeed_tpu.utils.logging import logger


class BaseTuner:
    """Experiment-ordering policy (reference index_based_tuner.py)."""

    def __init__(self, space: List[Any]):
        self.space = list(space)

    def order(self) -> List[Any]:
        raise NotImplementedError


class GridSearchTuner(BaseTuner):
    def order(self):
        return list(self.space)


class RandomTuner(BaseTuner):
    def __init__(self, space, seed=0):
        super().__init__(space)
        self.rng = _random.Random(seed)

    def order(self):
        out = list(self.space)
        self.rng.shuffle(out)
        return out


class ModelBasedTuner(BaseTuner):
    """Cost-model-guided ordering (reference model_based_tuner.py:16 with
    XGBoostCostModel): here the prior is the roofline intuition that
    larger micro-batches amortise better until memory pressure — order
    descending and early-stop on regression."""

    def order(self):
        return sorted(self.space, reverse=True)


TUNER_CLASSES = {"gridsearch": GridSearchTuner, "random": RandomTuner,
                 "model_based": ModelBasedTuner}


class Autotuner:
    def __init__(self,
                 make_engine: Callable[[Dict], Any],
                 make_batch: Callable[[int], Any],
                 base_config: Dict,
                 num_params: Optional[int] = None,
                 device_memory_bytes: Optional[int] = None,
                 micro_batch_sizes: Optional[List[int]] = None,
                 zero_stages: Optional[List[int]] = None,
                 tuner_type: str = "model_based",
                 steps_per_trial: int = 3,
                 early_stop: int = 2,
                 results_dir: str = "autotuning_results"):
        """make_engine(config_dict) -> engine;
        make_batch(global_batch_size) -> batch for one step."""
        self.make_engine = make_engine
        self.make_batch = make_batch
        self.base_config = base_config
        self.num_params = num_params
        self.device_memory_bytes = device_memory_bytes or \
            self._detect_device_memory()
        self.micro_batch_sizes = micro_batch_sizes or [1, 2, 4, 8, 16, 32]
        self.zero_stages = zero_stages or [0, 1, 2, 3]
        self.tuner_cls = TUNER_CLASSES[tuner_type]
        self.steps_per_trial = steps_per_trial
        self.early_stop = early_stop
        self.results_dir = results_dir
        self.records: List[Dict] = []

    @staticmethod
    def _detect_device_memory():
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_limit", 16 << 30)
        except Exception:
            return 16 << 30

    # ------------------------------------------------------------- pruning
    def prune_stages(self, dp_world: int) -> List[int]:
        """Memory-model stage pruning (reference _generate_experiments
        :287)."""
        if self.num_params is None:
            return list(self.zero_stages)
        ok = []
        for stage in self.zero_stages:
            need = estimate_zero_mem(self.num_params, dp_world, stage)
            if need < self.device_memory_bytes * 0.85:
                ok.append(stage)
        return ok or [max(self.zero_stages)]

    # -------------------------------------------------------------- trials
    def _run_trial(self, config: Dict) -> Optional[float]:
        """Returns samples/sec or None on failure/OOM."""
        try:
            from deepspeed_tpu.utils import groups
            groups.destroy()
            engine = self.make_engine(config)
            batch = self.make_batch(config["train_batch_size"])
            engine.train_batch(batch=batch)          # compile
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                engine.train_batch(batch=batch)
            jax.block_until_ready(engine.state.params)
            dt = time.perf_counter() - t0
            return config["train_batch_size"] * self.steps_per_trial / dt
        except Exception as e:
            logger.warning(f"autotuning trial failed: {e}")
            return None

    def tune(self) -> Dict:
        """Search; returns the best full config dict."""
        from deepspeed_tpu.utils import groups
        if groups.mesh_is_initialized():
            dp_world = groups.get_data_parallel_world_size()
        else:
            dp_world = jax.device_count()

        stages = self.prune_stages(dp_world)
        logger.info(f"autotuning over zero stages {stages}")
        best = None

        for stage in stages:
            tuner = self.tuner_cls(self.micro_batch_sizes)
            regressions = 0
            stage_best = None
            for micro in tuner.order():
                cfg = dict(self.base_config)
                cfg["train_micro_batch_size_per_gpu"] = micro
                cfg["train_batch_size"] = micro * dp_world
                cfg["zero_optimization"] = dict(
                    cfg.get("zero_optimization", {}), stage=stage)
                tput = self._run_trial(cfg)
                rec = {"zero_stage": stage, "micro_batch": micro,
                       "samples_per_sec": tput}
                self.records.append(rec)
                logger.info(f"trial {rec}")
                if tput is None:
                    continue
                if stage_best is None or tput > stage_best[0]:
                    stage_best = (tput, cfg)
                    regressions = 0
                else:
                    regressions += 1
                    if regressions >= self.early_stop:
                        break
            if stage_best and (best is None or stage_best[0] > best[0]):
                best = stage_best

        os.makedirs(self.results_dir, exist_ok=True)
        with open(os.path.join(self.results_dir, "results.json"), "w") as f:
            json.dump({"records": self.records,
                       "best": best[1] if best else None,
                       "best_samples_per_sec": best[0] if best else None},
                      f, indent=2)
        assert best is not None, "no autotuning trial succeeded"
        return best[1]
