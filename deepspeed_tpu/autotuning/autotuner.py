"""Autotuner — config search over ZeRO stage and micro-batch size.

Rebuild of deepspeed/autotuning/ (``Autotuner`` autotuner.py:29, tuners
tuner/index_based_tuner.py:8/:23 + model_based_tuner.py:16, scheduler
scheduler.py:35). The reference forks whole training jobs per experiment
across a node pool and greps profiling jsons; here experiments run
in-process (the engine is cheap to rebuild under jax) on THIS host:

1. model-info: param count → per-stage memory model
   (runtime/zero/partition.py estimate_zero_mem — the reference's
   ``model_info_profile_run`` :664);
2. prune ZeRO stages whose state cannot fit device memory;
3. per surviving stage, search micro-batch sizes (fastest-first order by
   the tuner policy) with short timed runs;
4. emit the best config + all measurements (autotuning_results layout).
"""

import json
import os
import random as _random
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.runtime.zero.partition import estimate_zero_mem
from deepspeed_tpu.utils.logging import logger

# warn-once latch for the host-RSS budget refusal in
# Autotuner._detect_device_memory (a staticmethod, so module state)
_WARNED_HOST_BUDGET = False


class BaseTuner:
    """Experiment-ordering policy (reference index_based_tuner.py)."""

    def __init__(self, space: List[Any]):
        self.space = list(space)

    def order(self) -> List[Any]:
        raise NotImplementedError


class GridSearchTuner(BaseTuner):
    def order(self):
        return list(self.space)


class RandomTuner(BaseTuner):
    def __init__(self, space, seed=0):
        super().__init__(space)
        self.rng = _random.Random(seed)

    def order(self):
        out = list(self.space)
        self.rng.shuffle(out)
        return out


class ModelBasedTuner(BaseTuner):
    """Scalar-space heuristic ordering (larger micro-batches amortise
    better until memory pressure — descending, early-stop on regression).
    The full cost-model tuner over multi-dim config spaces is
    :class:`CostModelTuner`."""

    def order(self):
        return sorted(self.space, reverse=True)


class CostModelTuner:
    """Cost-model-guided experiment sequencing (reference
    tuner/model_based_tuner.py:16): evaluate INIT_NUM random configs, fit
    the cost model on (features, measured perf), then repeatedly pick the
    best-predicted unvisited config, with an epsilon of random
    exploration. Interactive protocol: ``next()`` -> config or None,
    ``update(config, perf)`` after each measurement."""

    INIT_NUM = 2

    def __init__(self, configs: List[Dict], seed: int = 0,
                 explore_ratio: float = 0.2):
        from deepspeed_tpu.autotuning.cost_model import (
            GradientBoostingCostModel, featurize)
        self.configs = list(configs)
        self.X, self.keys = featurize(self.configs)
        # boosted trees once enough samples accrue (the reference's
        # XGBoost family), quadratic ridge before that
        self.model = GradientBoostingCostModel(seed=seed)
        self.rng = _random.Random(seed)
        self.explore_ratio = explore_ratio
        self.visited: set = set()
        self.xs: List[int] = []     # indices measured
        self.ys: List[float] = []

    def _unvisited(self):
        return [i for i in range(len(self.configs))
                if i not in self.visited]

    def next(self) -> Optional[Dict]:
        rest = self._unvisited()
        if not rest:
            return None
        if (len(self.xs) < self.INIT_NUM or
                self.rng.random() < self.explore_ratio):
            idx = self.rng.choice(rest)
        else:
            self.model.fit(self.X[self.xs], np.asarray(self.ys))
            pred = self.model.predict(self.X[rest])
            idx = rest[int(np.argmax(pred))]
        self.visited.add(idx)
        self._pending = idx
        return self.configs[idx]

    def update(self, config: Dict, perf: Optional[float]):
        if perf is None:
            return  # failed trial: visited but not a training point
        idx = getattr(self, "_pending", None)
        if idx is None or self.configs[idx] is not config:
            # dict-equality lookup would map the measurement to the FIRST
            # equal config when the space contains duplicate dicts,
            # training the model on the wrong feature row
            raise ValueError(
                "CostModelTuner.update must be called with the exact "
                "config object returned by the preceding next()")
        self.xs.append(idx)
        self.ys.append(float(perf))


TUNER_CLASSES = {"gridsearch": GridSearchTuner, "random": RandomTuner,
                 "model_based": ModelBasedTuner}


class Autotuner:
    def __init__(self,
                 make_engine: Callable[[Dict], Any],
                 make_batch: Callable[[int], Any],
                 base_config: Dict,
                 num_params: Optional[int] = None,
                 device_memory_bytes: Optional[int] = None,
                 micro_batch_sizes: Optional[List[int]] = None,
                 zero_stages: Optional[List[int]] = None,
                 tuner_type: str = "model_based",
                 steps_per_trial: int = 3,
                 early_stop: int = 2,
                 tuning_space: Optional[Dict[str, List]] = None,
                 max_trials: Optional[int] = None,
                 results_dir: str = "autotuning_results"):
        """make_engine(config_dict) -> engine;
        make_batch(global_batch_size) -> batch for one step."""
        self.make_engine = make_engine
        self.make_batch = make_batch
        self.base_config = base_config
        self.num_params = num_params
        self.device_memory_bytes = device_memory_bytes or \
            self._detect_device_memory()
        self.micro_batch_sizes = micro_batch_sizes or [1, 2, 4, 8, 16, 32]
        self.zero_stages = zero_stages or [0, 1, 2, 3]
        self.tuner_type = tuner_type
        self.tuner_cls = TUNER_CLASSES[tuner_type]
        self.steps_per_trial = steps_per_trial
        self.early_stop = early_stop
        # Extra search dims beyond stage x micro-batch (VERDICT r2 weak
        # #9: the knobs that actually move TPU perf) as dotted config
        # paths, e.g. {"activation_checkpointing.partition_activations":
        # [False, True], "zero_optimization.offload_optimizer.device":
        # ["none", "cpu"], "flash_block_size": [128, 256, 512]}.
        self.tuning_space = tuning_space or {}
        self.max_trials = max_trials
        self.results_dir = results_dir
        self.records: List[Dict] = []

    @staticmethod
    def _detect_device_memory():
        """Device memory budget, via the SAME detection chain the PR-2
        pre-flight uses (``cost_explorer.device_hbm_bytes``: allocator
        ``bytes_limit``, else the chip peak table) so stage pruning and
        the HBM watermark pre-flight agree on the budget, then the
        telemetry registry's ``device_memory_stats`` — but only when
        its source is a real device backend: the host-RSS fallbacks are
        REFUSED (warn-once), because pruning ZeRO stages against process
        RSS would accept configs a real chip rejects. CPU/virtual
        meshes fall to the 16 GiB default; runs that care (tests,
        benches) pass an explicit budget."""
        global _WARNED_HOST_BUDGET
        from deepspeed_tpu.telemetry.cost_explorer import device_hbm_bytes
        from deepspeed_tpu.telemetry.metrics import device_memory_stats
        hbm = device_hbm_bytes()
        if hbm:
            return int(hbm)
        stats = device_memory_stats()
        if stats.get("source") == "device" and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
        if stats.get("source", "").startswith("host") and \
                not _WARNED_HOST_BUDGET:
            _WARNED_HOST_BUDGET = True
            logger.warning(
                "[autotuning] device-memory detection found only %s — "
                "refusing to treat host RSS as an HBM budget; using the "
                "16 GiB default (pass device_memory_bytes explicitly to "
                "override)", stats["source"])
        return 16 << 30

    # ------------------------------------------------------------- pruning
    def prune_stages(self, dp_world: int) -> List[int]:
        """Memory-model stage pruning (reference _generate_experiments
        :287)."""
        if self.num_params is None:
            return list(self.zero_stages)
        ok = []
        for stage in self.zero_stages:
            need = estimate_zero_mem(self.num_params, dp_world, stage)
            if need < self.device_memory_bytes * 0.85:
                ok.append(stage)
        return ok or [max(self.zero_stages)]

    # -------------------------------------------------------------- trials
    def _run_trial(self, config: Dict) -> Optional[float]:
        """Returns samples/sec or None on failure/OOM."""
        try:
            from deepspeed_tpu.utils import groups
            groups.destroy()
            engine = self.make_engine(config)
            batch = self.make_batch(config["train_batch_size"])
            engine.train_batch(batch=batch)          # compile
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                engine.train_batch(batch=batch)
            jax.block_until_ready(engine.state.params)
            dt = time.perf_counter() - t0
            return config["train_batch_size"] * self.steps_per_trial / dt
        except Exception as e:
            logger.warning(f"autotuning trial failed: {e}")
            return None

    def _build_experiments(self, dp_world: int) -> List[Dict]:
        """Cartesian product of pruned stages x micro-batches x
        tuning_space dims (reference _generate_experiments :287)."""
        import copy
        import itertools

        def set_dotted(cfg, dotted, value):
            node = cfg
            parts = dotted.split(".")
            for k in parts[:-1]:
                node = node.setdefault(k, {})
            node[parts[-1]] = value

        stages = self.prune_stages(dp_world)
        logger.info(f"autotuning over zero stages {stages}")
        keys = list(self.tuning_space)
        combos = (list(itertools.product(*[self.tuning_space[k]
                                           for k in keys]))
                  if keys else [()])
        exps = []
        for stage in stages:
            for micro in self.micro_batch_sizes:
                for combo in combos:
                    cfg = copy.deepcopy(self.base_config)
                    cfg["train_micro_batch_size_per_gpu"] = micro
                    cfg["train_batch_size"] = micro * dp_world
                    cfg["zero_optimization"] = dict(
                        cfg.get("zero_optimization", {}), stage=stage)
                    for k, v in zip(keys, combo):
                        set_dotted(cfg, k, v)
                    exps.append(cfg)
        return exps

    def tune(self) -> Dict:
        """Search; returns the best full config dict."""
        from deepspeed_tpu.utils import groups
        if groups.mesh_is_initialized():
            dp_world = groups.get_data_parallel_world_size()
        else:
            dp_world = jax.device_count()

        exps = self._build_experiments(dp_world)
        best = None

        def measure(cfg):
            tput = self._run_trial(cfg)
            rec = {"zero_stage": cfg["zero_optimization"]["stage"],
                   "micro_batch": cfg["train_micro_batch_size_per_gpu"],
                   "samples_per_sec": tput,
                   "config": cfg}
            self.records.append(rec)
            logger.info(f"trial zero={rec['zero_stage']} "
                        f"micro={rec['micro_batch']} -> {tput}")
            return tput

        if self.tuner_type == "model_based":
            # guided search: a default budget well below the full product
            # (the point of the cost model), plus a global consecutive-
            # regression stop
            budget = self.max_trials or min(
                len(exps), max(CostModelTuner.INIT_NUM + 4,
                               (len(exps) + 1) // 2))
            tuner = CostModelTuner(exps)
            regressions = 0
            for _ in range(budget):
                cfg = tuner.next()
                if cfg is None:
                    break
                tput = measure(cfg)
                tuner.update(cfg, tput)
                if tput is None:
                    continue
                if best is None or tput > best[0]:
                    best = (tput, cfg)
                    regressions = 0
                else:
                    regressions += 1
                    if regressions >= self.early_stop * 2:
                        break
        else:
            # ordered (stage-major) search with a PER-STAGE regression
            # counter: a saturated stage is skipped without starving
            # later stages
            order = (GridSearchTuner(exps).order()
                     if self.tuner_type == "gridsearch"
                     else RandomTuner(exps).order())
            budget = self.max_trials or len(exps)
            trials = 0
            last_stage = None
            stage_best = None
            regressions = 0
            skip_stages = set()
            for cfg in order:
                if trials >= budget:
                    break
                stage = cfg["zero_optimization"]["stage"]
                if stage in skip_stages:
                    continue
                if stage != last_stage:
                    regressions = 0
                    stage_best = None
                    last_stage = stage
                trials += 1
                tput = measure(cfg)
                if tput is None:
                    continue
                if best is None or tput > best[0]:
                    best = (tput, cfg)
                if stage_best is None or tput > stage_best:
                    stage_best = tput
                    regressions = 0
                else:
                    regressions += 1
                    if regressions >= self.early_stop:
                        skip_stages.add(stage)

        os.makedirs(self.results_dir, exist_ok=True)
        with open(os.path.join(self.results_dir, "results.json"), "w") as f:
            json.dump({"records": self.records,
                       "best": best[1] if best else None,
                       "best_samples_per_sec": best[0] if best else None},
                      f, indent=2)
        assert best is not None, "no autotuning trial succeeded"
        return best[1]
