"""Cost model for the model-based tuner.

Rebuild of deepspeed/autotuning/tuner/cost_model.py:11
(``XGBoostCostModel``). XGBoost itself is not in this image;
``GradientBoostingCostModel`` (sklearn) is the same model family —
boosted regression trees — and is the default when enough samples exist.
``RidgeCostModel`` (closed-form degree-2 ridge) is the small-sample /
no-sklearn fallback: with the reference's INIT_NUM≈8 warm-up points,
trees overfit where the quadratic prior still ranks sanely — and the
tuner only needs RANKING, not absolute accuracy."""

from typing import Dict, List

import numpy as np


def flatten_config(cfg: Dict, prefix="") -> Dict[str, object]:
    """Flatten a nested config dict keeping numeric AND string leaves
    (reference autotuning/utils.py flatten; strings one-hot later —
    offload devices etc. are legitimate tuning dims)."""
    out = {}
    for k, v in cfg.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(flatten_config(v, prefix=key + "."))
        elif isinstance(v, bool):
            out[key] = float(v)
        elif isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, str):
            out[key] = v
    return out


def featurize(configs: List[Dict], keys: List[str] = None):
    """configs -> (X, keys): numeric feature matrix. String-valued dims
    become one-hot indicator columns ('key=value'), so categorical knobs
    (e.g. offload_optimizer.device) are visible to the cost model."""
    flats = [flatten_config(c) for c in configs]
    if keys is None:
        raw = sorted(set().union(*[set(f) for f in flats]))
        keys = []
        for k in raw:
            vals = {f[k] for f in flats if k in f}
            if any(isinstance(v, str) for v in vals):
                keys.extend(f"{k}={v}" for v in sorted(map(str, vals)))
            else:
                keys.append(k)

    def val(f, key):
        if "=" in key:
            k, _, v = key.partition("=")
            if k in f:
                return 1.0 if str(f[k]) == v else 0.0
            return 0.0
        x = f.get(key, 0.0)
        return float(x) if not isinstance(x, str) else 0.0

    X = np.array([[val(f, k) for k in keys] for f in flats], np.float64)
    return X, keys


class RidgeCostModel:
    """fit(X, y) / predict(X) with degree-2 polynomial expansion and L2
    regularisation; y is normalised like the reference (max-scaled)."""

    def __init__(self, l2: float = 1e-3):
        self.l2 = l2
        self.w = None
        self._mu = None
        self._sigma = None

    def _expand(self, X):
        n, d = X.shape
        cols = [np.ones((n, 1)), X]
        for i in range(d):
            for j in range(i, d):
                cols.append((X[:, i] * X[:, j])[:, None])
        return np.concatenate(cols, axis=1)

    def fit(self, X, y):
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        y = y / max(float(np.max(np.abs(y))), 1e-9)
        self._mu = X.mean(axis=0)
        self._sigma = np.where(X.std(axis=0) > 0, X.std(axis=0), 1.0)
        P = self._expand((X - self._mu) / self._sigma)
        A = P.T @ P + self.l2 * np.eye(P.shape[1])
        self.w = np.linalg.solve(A, P.T @ y)

    def predict(self, X):
        assert self.w is not None, "fit() before predict()"
        X = np.asarray(X, np.float64)
        P = self._expand((X - self._mu) / self._sigma)
        return P @ self.w


class GradientBoostingCostModel:
    """Boosted regression trees — the reference's XGBoostCostModel family
    (cost_model.py:11), via sklearn. Falls back to ridge below
    ``min_samples`` (trees need data to split) or without sklearn."""

    def __init__(self, n_estimators: int = 200, min_samples: int = 12,
                 seed: int = 0):
        self.min_samples = min_samples
        self._ridge = RidgeCostModel()
        self._gb = None
        self._use_gb = False
        try:
            from sklearn.ensemble import GradientBoostingRegressor
            # random_state pins the subsample draws: the tuner's `seed`
            # promises reproducible rankings
            self._gb = GradientBoostingRegressor(
                n_estimators=n_estimators, max_depth=3,
                learning_rate=0.05, subsample=0.9, random_state=seed)
        except ImportError:  # pragma: no cover — sklearn is baked in
            self._gb = None

    def fit(self, X, y):
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        y = y / max(float(np.max(np.abs(y))), 1e-9)
        self._use_gb = self._gb is not None and len(y) >= self.min_samples
        if self._use_gb:
            self._gb.fit(X, y)
        else:
            self._ridge.fit(X, y)

    def predict(self, X):
        if self._use_gb:
            return self._gb.predict(np.asarray(X, np.float64))
        return self._ridge.predict(X)
