"""Post-training weight quantization for checkpoint loading (MoQ
inference).

Rebuild of deepspeed/runtime/weight_quantizer.py:5 ``WeightQuantization``:
grouped symmetric int8 quantization of megatron transformer weights during
state-dict load, emitting per-group inverse scales in the layer order the
fused inference kernels expect (qkv, attn-dense, h4h, 4hh — reference
``merge_scales`` :72). numpy end-to-end; the dequantised matmul runs
through ops/quantizer (TPU) at inference time.
"""

from typing import List

import numpy as np


class WeightQuantization:
    def __init__(self, mlp_extra_grouping=True, mp_size=1):
        self.dense_scales: List[np.ndarray] = []
        self.qkv_scales: List[np.ndarray] = []
        self.mlp4hh_scales: List[np.ndarray] = []
        self.mlph4h_scales: List[np.ndarray] = []
        self.mlp_extra_grouping = mlp_extra_grouping
        self.mp_size = mp_size

    def quantize_data(self, data, quantize_bits, groups, key=None):
        """Symmetric per-group quantization (reference quantize_data :14):
        scale = 2^bits / (2*absmax + eps); int values rounded and clamped
        to [-2^(b-1), 2^(b-1)-1]."""
        data = np.asarray(data, np.float32)
        flat = data.reshape(-1)
        assert flat.size % groups == 0, (flat.size, groups)
        g = flat.reshape(groups, -1)
        max_d = np.maximum(g.max(axis=1), np.abs(g.min(axis=1)))
        scale = float(1 << quantize_bits) / (2 * max_d + 1e-5)
        lo = -(1 << (quantize_bits - 1))
        hi = (1 << (quantize_bits - 1)) - 1
        data_int = np.clip(np.round(g * scale[:, None]), lo, hi)
        return (data_int.reshape(data.shape).astype(np.int8),
                scale.astype(np.float32))

    def is_mlp(self, data, merge_count=1):
        return ((self.mp_size * data.shape[0] * merge_count) /
                data.shape[1] == 4 or
                (self.mp_size * data.shape[1] * merge_count) /
                data.shape[0] == 4)

    def is_qkv(self, data):
        return ((self.mp_size * data.shape[0]) / data.shape[1] == 3 or
                (self.mp_size * data.shape[1]) / data.shape[0] == 3)

    def Quantize(self, value_list, quantize_bits, groups, key, merge_dim=0):
        if self.mlp_extra_grouping and \
                self.is_mlp(value_list[0], merge_count=len(value_list)):
            groups *= 2
        q_scale = []
        out = []
        for data in value_list:
            data_int, scale = self.quantize_data(data, quantize_bits,
                                                 groups, key)
            q_scale.append(scale)
            out.append(data_int)
        # inverse scales, one row (reference: 1/cat(q_scale).view(-1))
        q_scale = (1.0 / np.concatenate(q_scale))[None, :]
        if "mlp.dense_4h_to_h.weight" in key:
            self.mlp4hh_scales.append(q_scale)
        elif "mlp.dense_h_to_4h.weight" in key:
            self.mlph4h_scales.append(q_scale)
        elif "attention.query_key_value.weight" in key:
            self.qkv_scales.append(q_scale)
        else:
            self.dense_scales.append(q_scale)
        return out

    def merge_layer_scales(self, layer_scales):
        max_dim = max(s.shape[-1] for s in layer_scales)
        padded = [np.pad(s, [(0, 0), (0, max_dim - s.shape[-1])])
                  if s.shape[-1] < max_dim else s for s in layer_scales]
        return np.concatenate(padded)[None]

    def merge_scales(self):
        all_scales = []
        for dense, qkv, m4hh, mh4h in zip(self.dense_scales,
                                          self.qkv_scales,
                                          self.mlp4hh_scales,
                                          self.mlph4h_scales):
            all_scales.append(self.merge_layer_scales(
                [qkv, dense, mh4h, m4hh]))
        return np.concatenate(all_scales)

    def merge_scales_split(self, split_count):
        """Per-split scale groups (reference merge_scales_split :88)."""
        all_scales = [[] for _ in range(split_count)]
        for dense, qkv, m4hh, mh4h in zip(self.dense_scales,
                                          self.qkv_scales,
                                          self.mlp4hh_scales,
                                          self.mlph4h_scales):
            for s in range(split_count):
                def piece(x):
                    return np.split(x, split_count, axis=-1)[s]
                all_scales[s].append(self.merge_layer_scales(
                    [piece(qkv), piece(dense), piece(mh4h), piece(m4hh)]))
        return [np.concatenate(s) for s in all_scales]

    def sd_quantize_megatron(self, sd, quantize_bits, groups):
        """Quantize a whole (mp-local) megatron module dict (reference
        sd_quantize_megatron)."""
        keys = sd.keys()
        for key in keys:
            value_list = [sd[key]]
            if "attention.dense.weight" in key or \
                    "mlp.dense_4h_to_h.weight" in key or \
                    "mlp.dense_h_to_4h.weight" in key or \
                    "attention.query_key_value.weight" in key:
                value_list = self.Quantize(value_list, quantize_bits,
                                           groups, key=key)
            sd[key] = value_list[0]
        return sd, self.merge_scales()


MEGATRON_QUANTIZABLE_SUBSTRINGS = (
    "attention.dense.weight", "mlp.dense_4h_to_h.weight",
    "mlp.dense_h_to_4h.weight", "attention.query_key_value.weight")


def quantize_dequantize_sd(module_sd, groups, mlp_extra_grouping=True,
                           mp_size=1, quantize_bits=8):
    """Grouped int8 quantize + immediate dequantize of the megatron
    transformer matmul weights: numerics equal the reference's
    on-the-fly-dequant fused inference kernels while the params stay a
    normal fp tree. Returns (new_sd, num_quantized)."""
    q = WeightQuantization(mlp_extra_grouping=mlp_extra_grouping,
                           mp_size=mp_size)
    out = dict(module_sd)
    n = 0
    for key, val in module_sd.items():
        if any(s in key for s in MEGATRON_QUANTIZABLE_SUBSTRINGS):
            g = groups * 2 if (mlp_extra_grouping and q.is_mlp(val)) \
                else groups
            data_int, scale = q.quantize_data(val, quantize_bits, g)
            out[key] = dequantize(data_int, 1.0 / scale, groups=g
                                  ).astype(val.dtype)
            n += 1
    return out, n


def dequantize(data_int, inv_scales, groups=None):
    """int8 grouped values + inverse scales -> fp32 (the host-side pair of
    the reference's dequantize.cu; TPU-side dequant fuses into the matmul
    via ops/quantizer)."""
    flat = data_int.reshape(-1).astype(np.float32)
    inv = np.asarray(inv_scales).reshape(-1)
    g = groups or inv.size
    return (flat.reshape(g, -1) * inv[:g, None]).reshape(data_int.shape)
