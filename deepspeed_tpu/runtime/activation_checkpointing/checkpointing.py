"""Activation checkpointing.

TPU-native rebuild of deepspeed/runtime/activation_checkpointing/
checkpointing.py (``checkpoint`` :748, ``configure`` :906,
``partition_activations`` :367, CPU checkpointing :480). The reference
re-implements torch checkpointing with mp-aware RNG tracking, activation
partitioning across model-parallel ranks, and optional CPU offload. Under
XLA the same three knobs map onto ``jax.checkpoint``:

* recompute → ``jax.checkpoint`` on the wrapped function (XLA replays the
  forward in the backward; RNG correctness is automatic because jax PRNG
  keys are values, not global state — the whole CudaRNGStatesTracker
  machinery (:91-:187) is unnecessary);
* partition_activations → a rematerialisation *policy* that saves only
  model-parallel-sharded residuals (``save_sharded_only``);
* cpu_checkpointing → ``offload`` policy saving residuals to host memory
  (jax.checkpoint_policies.offload_dot_with_no_batch_dims / save_and_
  offload_only_these_names).

``configure``/``checkpoint`` keep the reference's call signatures so
user code ports unchanged.
"""

from typing import Optional

import jax

_CONFIG = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "checkpoint_in_cpu": False,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "num_checkpoints": None,
}

_mpu = None


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference checkpointing.py:906 — store the knobs."""
    global _mpu
    _mpu = mpu_
    if deepspeed_config is not None:
        acfg = getattr(deepspeed_config, "activation_checkpointing_config",
                       None)
        if acfg is not None:
            _CONFIG.update({
                "partition_activations": acfg.partition_activations,
                "contiguous_memory_optimization":
                    acfg.contiguous_memory_optimization,
                "cpu_checkpointing": acfg.cpu_checkpointing,
                "num_checkpoints": acfg.number_checkpoints,
                "synchronize_checkpoint_boundary":
                    acfg.synchronize_checkpoint_boundary,
                "profile": acfg.profile,
            })
    for key, val in [("partition_activations", partition_activations),
                     ("contiguous_memory_optimization",
                      contiguous_checkpointing),
                     ("num_checkpoints", num_checkpoints),
                     ("checkpoint_in_cpu", checkpoint_in_cpu),
                     ("synchronize_checkpoint_boundary", synchronize),
                     ("profile", profile)]:
        if val is not None:
            _CONFIG[key] = val


def is_configured():
    return True


def _policy():
    """Map the configured knobs to a jax.checkpoint policy."""
    cp = jax.checkpoint_policies
    if _CONFIG["cpu_checkpointing"] or _CONFIG["checkpoint_in_cpu"]:
        # save matmul outputs but keep them in host memory
        if hasattr(cp, "offload_dot_with_no_batch_dims"):
            return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
        return cp.nothing_saveable
    if _CONFIG["partition_activations"]:
        # save only what is cheap per-shard; everything else recomputes —
        # the spiritual analogue of slicing saved activations across MP
        # ranks (reference :367): memory per device scales down with MP
        return cp.nothing_saveable
    return None  # default: save everything jax deems profitable


def checkpoint(function, *args):
    """Checkpoint a forward function (reference :748): returns
    function(*args) with recompute-in-backward semantics."""
    policy = _policy()
    if policy is None:
        fn = jax.checkpoint(function)
    else:
        fn = jax.checkpoint(function, policy=policy)
    return fn(*args)


def checkpoint_wrapper(function):
    """Decorator form used by model code."""
    policy = _policy()
    if policy is None:
        return jax.checkpoint(function)
    return jax.checkpoint(function, policy=policy)


# ---- reference API stubs that are no-ops under jax's functional PRNG ----
def get_cuda_rng_tracker():
    raise NotImplementedError(
        "jax PRNG keys are explicit values; thread rngs through module "
        "calls instead (see models/gpt2.py dropout rngs)")


def model_parallel_cuda_manual_seed(seed):  # pragma: no cover
    return None


def reset():
    return None
