"""Activation checkpointing.

TPU-native rebuild of deepspeed/runtime/activation_checkpointing/
checkpointing.py (``checkpoint`` :748, ``configure`` :906,
``partition_activations`` :367, CPU checkpointing :480). The reference
re-implements torch checkpointing with mp-aware RNG tracking, activation
partitioning across model-parallel ranks, and optional CPU offload. Under
XLA the same three knobs map onto ``jax.checkpoint``:

* recompute → ``jax.checkpoint`` on the wrapped function (XLA replays the
  forward in the backward; RNG correctness is automatic because jax PRNG
  keys are values, not global state — the whole CudaRNGStatesTracker
  machinery (:91-:187) is unnecessary);
* partition_activations → a rematerialisation *policy* that saves only
  model-parallel-sharded residuals (``save_sharded_only``);
* cpu_checkpointing → ``offload`` policy saving residuals to host memory
  (jax.checkpoint_policies.offload_dot_with_no_batch_dims / save_and_
  offload_only_these_names).

``configure``/``checkpoint`` keep the reference's call signatures so
user code ports unchanged.
"""

from typing import Optional

import jax

_DEFAULTS = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "checkpoint_in_cpu": False,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "num_checkpoints": None,
}
_CONFIG = dict(_DEFAULTS)

_mpu = None
_configured = False


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference checkpointing.py:906 — store the knobs."""
    global _mpu, _configured
    _mpu = mpu_
    _configured = True
    if deepspeed_config is not None:
        acfg = getattr(deepspeed_config, "activation_checkpointing_config",
                       None)
        if acfg is not None:
            _CONFIG.update({
                "partition_activations": acfg.partition_activations,
                "contiguous_memory_optimization":
                    acfg.contiguous_memory_optimization,
                "cpu_checkpointing": acfg.cpu_checkpointing,
                "num_checkpoints": acfg.number_checkpoints,
                "synchronize_checkpoint_boundary":
                    acfg.synchronize_checkpoint_boundary,
                "profile": acfg.profile,
            })
    for key, val in [("partition_activations", partition_activations),
                     ("contiguous_memory_optimization",
                      contiguous_checkpointing),
                     ("num_checkpoints", num_checkpoints),
                     ("checkpoint_in_cpu", checkpoint_in_cpu),
                     ("synchronize_checkpoint_boundary", synchronize),
                     ("profile", profile)]:
        if val is not None:
            _CONFIG[key] = val


def is_configured():
    """True once ``configure`` has run (reference checkpointing.py:928)."""
    return _configured


def _policy():
    """Map the configured knobs to a jax.checkpoint policy.

    ``jax.checkpoint`` with no policy already recomputes every
    intermediate (only the segment INPUTS are kept alive for the
    backward) — the reference's base checkpointing semantics."""
    cp = jax.checkpoint_policies
    if _CONFIG["cpu_checkpointing"] or _CONFIG["checkpoint_in_cpu"]:
        # save matmul outputs but keep them in host memory
        if hasattr(cp, "offload_dot_with_no_batch_dims"):
            return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
        return cp.nothing_saveable
    return None


def _partition_args(args):
    """partition_activations (reference :367): each MP rank stores only
    its 1/mp slice of the saved segment inputs, allgathered on backward.

    The XLA form: constrain every tensor input of the checkpointed
    segment to be sharded over the 'model' mesh axis (last dim). The
    saved residual then lives sharded — per-device activation memory
    scales down with mp — and XLA inserts the all-gather where the
    recompute consumes it, exactly the reference's gather-on-backward."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        from deepspeed_tpu.utils import groups
        mesh = groups.get_mesh()   # raises when groups not initialized
    except Exception:
        return args
    if mesh is None or "model" not in mesh.axis_names:
        return args
    mp = mesh.shape["model"]
    if mp == 1:
        return args

    def constrain(x):
        if (hasattr(x, "ndim") and x.ndim >= 1
                and x.shape[-1] % mp == 0):
            spec = P(*([None] * (x.ndim - 1)), "model")
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec))
        return x

    return jax.tree.map(constrain, args)


def checkpoint(function, *args):
    """Checkpoint a forward function (reference :748): returns
    function(*args) with recompute-in-backward semantics."""
    policy = _policy()
    if _CONFIG["partition_activations"]:
        args = _partition_args(args)
    if policy is None:
        fn = jax.checkpoint(function)
    else:
        fn = jax.checkpoint(function, policy=policy)
    return fn(*args)


def checkpoint_wrapper(function):
    """Decorator form used by model code."""
    import functools

    @functools.wraps(function)
    def wrapped(*args):
        return checkpoint(function, *args)
    return wrapped


# ---- reference API stubs that are no-ops under jax's functional PRNG ----
def get_cuda_rng_tracker():
    raise NotImplementedError(
        "jax PRNG keys are explicit values; thread rngs through module "
        "calls instead (see models/gpt2.py dropout rngs)")


def model_parallel_cuda_manual_seed(seed):  # pragma: no cover
    return None


def reset():
    """Restore the unconfigured default state (reference :941)."""
    global _mpu, _configured
    _CONFIG.clear()
    _CONFIG.update(_DEFAULTS)
    _mpu = None
    _configured = False
