"""Self-healing guardian: the anomaly->action policy engine.

Every observability layer in this repo (HEALTH, GOODPUT, SERVING_HEALTH,
FLEET_HEALTH) classifies anomalies and escalates — to a warning and a
JSON file. The guardian closes the loop: it subscribes to the monitors'
``on_anomaly`` hooks and maps fired rules to BOUNDED, rate-limited
actions:

* ``emergency_checkpoint`` — first firing of a warning-tier rule takes
  an extra checkpoint through the normal save path (async writer when
  configured, one in flight), so whatever happens next, the distance to
  the last durable state is small. Emergency tags are prefixed
  (``guardian_emergency_...``) and de-prioritized as rollback targets —
  a checkpoint taken BECAUSE something looked wrong may hold the wrong
  something.
* ``rollback`` — confirmed divergence (a loss_spike plus a streak of
  nonfinite_grads firings inside one window) restores params, optimizer
  state, the dynamic loss scale and the data-stream position from the
  newest intact tag, then RE-ARMS with a cooldown so a persistently bad
  run degrades to bounded rollbacks, never a rollback loop.
* ``fp16_rescue`` — loss_scale_collapse (scale at the floor and the
  step still overflowing) resets the dynamic-scaler state to an escape
  scale with fresh hysteresis; bounded by ``max_fp16_rescues``.
* ``serving_pause`` / ``serving_resume`` — overload rules
  (queue_growth, ttft_slo_breach, and the SLO monitor's page-tier
  slo_burn_page) shed load by pausing admission (new submits fail fast
  with a structured reason instead of joining a queue that can't
  drain); admission resumes after the rules stay quiet for
  ``resume_clear_steps`` serving steps.

The guardian itself is pure host-side bookkeeping: it never touches the
device, never changes a compiled program, and a tick with no pending
anomalies is one attribute read and a truthiness check. Actions are
delegated to callbacks the owning engine wires (``rollback_fn`` etc.);
an action that throws is journaled as failed and must never kill the
step that triggered it.

Everything the guardian does is journaled to ``GUARDIAN.json``
(schema-pinned, atomic-rename durable) — actions taken, trigger rule,
outcome — so a post-mortem can replay WHY the run healed itself.
"""

import argparse
import json
import os
import threading
import time

from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.telemetry import clock as _clk
from deepspeed_tpu.utils.logging import logger

GUARDIAN_SCHEMA = "deepspeed_tpu.guardian/1"
# rollback prefers user tags; tags with this prefix are the guardian's
# own emergency saves (state of UNKNOWN health — fallback targets only)
EMERGENCY_TAG_PREFIX = "guardian_emergency"

# first-warning rules that trigger an emergency checkpoint: trouble
# signals whose trigger state is still worth persisting. The divergence
# rules (loss_spike, nonfinite_grads, loss_scale_collapse) are EXCLUDED
# on purpose — a checkpoint taken mid-divergence would persist exactly
# the state rollback exists to escape.
DEFAULT_EMERGENCY_RULES = (
    "overflow_streak", "loss_stall", "grad_norm_spike",
    "input_bound", "goodput_regression", "checkpoint_stall",
    "step_time_skew", "input_wait_skew", "checkpoint_skew", "param_desync",
)
DEFAULT_PAUSE_RULES = ("queue_growth", "ttft_slo_breach",
                       "slo_burn_page")


def _atomic_json(path, doc):
    """tmp + rename so a reader never sees a torn journal (the same
    durability idiom as checkpoint_io, minus the checkpoint telemetry)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=repr, allow_nan=False)
    os.replace(tmp, path)


class Guardian:
    """Anomaly->action policy engine (one instance per process; the
    training engine and its serving engine share it — serving actions
    ride the same journal).

    Monitors deliver anomalies through ``hook(source)`` callbacks (safe
    to call from any thread; delivery only queues). Policies are
    evaluated and actions performed at step boundaries on the owner's
    thread: the engine calls ``tick(step)`` from its post-apply hook,
    the serving engine calls ``serving_tick(step)`` from its step loop.
    """

    def __init__(self, enabled=True, job_name="", journal_path=None,
                 action_cooldown_steps=25,
                 emergency_checkpoint=True,
                 emergency_rules=DEFAULT_EMERGENCY_RULES,
                 max_emergency_checkpoints=4,
                 rollback=True, divergence_window=50, divergence_streak=2,
                 rollback_cooldown_steps=200, max_rollbacks=2,
                 fp16_rescue=True, max_fp16_rescues=2,
                 serving_degrade=True, pause_rules=DEFAULT_PAUSE_RULES,
                 resume_clear_steps=64,
                 registry=None, log_fn=None):
        self.enabled = bool(enabled)
        self.job_name = job_name
        # None journal_path = in-memory only (unit-test construction);
        # from_config always resolves a real path under the telemetry
        # output dir — NEVER a bare CWD-relative default (the PR-4/PR-11
        # committed-artifact clobber lesson)
        self.journal_path = journal_path
        self.action_cooldown_steps = int(action_cooldown_steps)
        self.emergency_checkpoint = bool(emergency_checkpoint)
        self.emergency_rules = frozenset(emergency_rules)
        self.max_emergency_checkpoints = int(max_emergency_checkpoints)
        self.rollback = bool(rollback)
        self.divergence_window = int(divergence_window)
        self.divergence_streak = max(1, int(divergence_streak))
        self.rollback_cooldown_steps = int(rollback_cooldown_steps)
        self.max_rollbacks = int(max_rollbacks)
        self.fp16_rescue = bool(fp16_rescue)
        self.max_fp16_rescues = int(max_fp16_rescues)
        self.serving_degrade = bool(serving_degrade)
        self.pause_rules = frozenset(pause_rules)
        self.resume_clear_steps = int(resume_clear_steps)
        self.registry = registry
        self._log = log_fn or logger.warning

        # action callbacks — wired by the owning engine(s); an unwired
        # action is journaled as skipped, never an error
        self.emergency_save_fn = None   # (step) -> tag or None
        self.rollback_fn = None         # () -> restored tag or None
        self.fp16_rescue_fn = None      # () -> detail str
        self.pause_fn = None            # (reason) -> None
        self.resume_fn = None           # () -> None
        self.spec_disable_fn = None     # (reason) -> detail str

        self._lock = threading.Lock()
        self._queue = []                # (source, anomaly-dict) pending
        self.rules_seen = {}            # rule -> firings delivered
        self.sources_seen = {}          # source -> firings delivered
        self.actions = []               # journal entries, oldest first
        self.action_counts = {}         # action -> times performed (ok)
        # divergence evidence (training side)
        self._nonfinite_steps = []      # distinct steps nonfinite fired
        self._loss_spike_step = None
        self._rollback_rearm_step = -1  # no rollback before this step
        self._last_action_step = {}     # action -> step last performed
        # serving degradation state
        self.admission_paused = False
        self._pause_rule = None
        self._last_overload_step = -1
        self.last_step = -1

    @classmethod
    def from_config(cls, gconfig, output_path="telemetry/", job_name="",
                    registry=None):
        """Build from a parsed :class:`DeepSpeedGuardianConfig`. The
        journal lands under the telemetry output dir unless the
        configured name is absolute."""
        journal = gconfig.journal_file or "GUARDIAN.json"
        if not os.path.isabs(journal):
            journal = os.path.join(output_path or "telemetry/", journal)
        return cls(
            enabled=gconfig.enabled,
            job_name=job_name,
            journal_path=journal,
            action_cooldown_steps=gconfig.action_cooldown_steps,
            emergency_checkpoint=gconfig.emergency_checkpoint,
            emergency_rules=gconfig.emergency_rules,
            max_emergency_checkpoints=gconfig.max_emergency_checkpoints,
            rollback=gconfig.rollback,
            divergence_window=gconfig.divergence_window,
            divergence_streak=gconfig.divergence_streak,
            rollback_cooldown_steps=gconfig.rollback_cooldown_steps,
            max_rollbacks=gconfig.max_rollbacks,
            fp16_rescue=gconfig.fp16_rescue,
            max_fp16_rescues=gconfig.max_fp16_rescues,
            serving_degrade=gconfig.serving_degrade,
            pause_rules=gconfig.pause_rules,
            resume_clear_steps=gconfig.resume_clear_steps,
            registry=registry)

    # ------------------------------------------------------------- delivery
    def hook(self, source):
        """The ``on_anomaly`` callback to hand a monitor: delivery only
        queues (any thread); policies run at the next tick."""
        def _deliver(anoms):
            self.notify(source, anoms)
        return _deliver

    def notify(self, source, anoms):
        if not self.enabled or not anoms:
            return
        with self._lock:
            for a in anoms:
                self._queue.append((source, a))
                rule = a.get("rule", "?")
                self.rules_seen[rule] = self.rules_seen.get(rule, 0) + 1
                self.sources_seen[source] = \
                    self.sources_seen.get(source, 0) + 1

    def _drain(self):
        with self._lock:
            pending, self._queue = self._queue, []
        return pending

    # -------------------------------------------------------------- actions
    def _cooldown_ok(self, action, step, cooldown=None):
        last = self._last_action_step.get(action)
        if last is None:
            return True
        return step - last >= (self.action_cooldown_steps
                               if cooldown is None else cooldown)

    def _act(self, action, rule, step, fn, *args, detail=""):
        """Perform one action through its callback, journal the outcome,
        count it. A throwing action is a journaled failure — the policy
        engine must never kill the step that triggered it."""
        entry = {"action": action, "rule": rule, "step": int(step),
                 "t_us": _clk.monotonic_us(),
                 "unix_time": round(_clk.unix_us() / 1e6, 3),
                 "detail": detail}
        if fn is None:
            entry["outcome"] = "skipped:no_handler"
        else:
            try:
                result = fn(*args)
                entry["outcome"] = "ok"
                if result is not None:
                    entry["result"] = str(result)
                self.action_counts[action] = \
                    self.action_counts.get(action, 0) + 1
                self._last_action_step[action] = int(step)
            except Exception as e:
                entry["outcome"] = f"failed:{e}"
        self.actions.append(entry)
        self._log("[guardian] %s (rule %s, step %s): %s %s",
                  action, rule, step, entry["outcome"], detail)
        if self.registry is not None:
            self.registry.counter(
                "guardian_actions_total",
                "guardian anomaly->action policy firings",
                labels={"action": action,
                        "outcome": entry["outcome"].split(":")[0]}).inc()
        chron = _chronicle.get_chronicle()
        if chron.enabled:
            # the rule->action edge is the correlator's causal join
            chron.emit("action", source="guardian", step=int(step),
                       severity="warning", action=action, rule=rule,
                       outcome=entry["outcome"], detail=detail or None,
                       artifact=self.journal_path)
        self.write_journal()
        return entry["outcome"] == "ok"

    # ------------------------------------------------------- training tick
    def tick(self, step):
        """Evaluate the training-side policies. Called from the engine's
        post-apply hook on the main thread — the only place a rollback
        (which swaps the live train state) is safe. O(1) when nothing is
        pending."""
        if not self.enabled or not self._queue:
            return
        step = int(step)
        self.last_step = max(self.last_step, step)
        pending = self._drain()
        first_warning_rule = None
        saw_collapse = False
        for source, a in pending:
            rule = a.get("rule", "?")
            astep = int(a.get("step") or step)
            if rule == "nonfinite_grads":
                if not self._nonfinite_steps \
                        or self._nonfinite_steps[-1] != astep:
                    self._nonfinite_steps.append(astep)
            elif rule == "loss_spike":
                self._loss_spike_step = astep
            elif rule == "loss_scale_collapse":
                saw_collapse = True
            if (rule in self.emergency_rules
                    and self.rules_seen.get(rule, 0) == 1
                    and first_warning_rule is None):
                first_warning_rule = rule
        # expire divergence evidence that slid out of the window
        lo = step - self.divergence_window
        self._nonfinite_steps = [s for s in self._nonfinite_steps
                                 if s >= lo]
        if self._loss_spike_step is not None and self._loss_spike_step < lo:
            self._loss_spike_step = None

        # (c) fp16 collapse: reset the scaler before anything else — no
        # other policy can make progress while every step overflows
        if (saw_collapse and self.fp16_rescue
                and self.action_counts.get("fp16_rescue", 0)
                < self.max_fp16_rescues
                and self._cooldown_ok("fp16_rescue", step)):
            self._act("fp16_rescue", "loss_scale_collapse", step,
                      self.fp16_rescue_fn,
                      detail="dynamic loss scale reset to escape scale")

        # (b) confirmed divergence -> rollback, with cooldown re-arm
        if (self.rollback
                and len(self._nonfinite_steps) >= self.divergence_streak
                and self._loss_spike_step is not None
                and step >= self._rollback_rearm_step
                and self.action_counts.get("rollback", 0)
                < self.max_rollbacks):
            ok = self._act(
                "rollback", "loss_spike+nonfinite_grads", step,
                self.rollback_fn,
                detail=f"nonfinite on steps {self._nonfinite_steps}, "
                       f"loss_spike at {self._loss_spike_step}")
            # evidence referred to the pre-rollback trajectory either
            # way; the cooldown only arms after a rollback actually ran
            self._nonfinite_steps = []
            self._loss_spike_step = None
            if ok:
                self._rollback_rearm_step = \
                    step + self.rollback_cooldown_steps
            return   # the restored state makes other pending policies moot

        # (a) first-warning emergency checkpoint
        if (first_warning_rule is not None and self.emergency_checkpoint
                and self.action_counts.get("emergency_checkpoint", 0)
                < self.max_emergency_checkpoints
                and self._cooldown_ok("emergency_checkpoint", step)):
            self._act("emergency_checkpoint", first_warning_rule, step,
                      self.emergency_save_fn, step,
                      detail="first firing of a warning-tier rule")

    # ------------------------------------------------------- serving tick
    def serving_tick(self, step):
        """Evaluate the serving-side degradation policy. Called from the
        serving engine's step loop; ``step`` is the SERVING step
        counter (a different clock from training steps)."""
        if not self.enabled or not self.serving_degrade:
            return
        step = int(step)
        overload_rule = None
        waste_rule = None
        if self._queue:
            for source, a in self._drain():
                rule = a.get("rule", "?")
                if rule in self.pause_rules:
                    overload_rule = rule
                elif rule == "speculation_waste":
                    waste_rule = rule
        # sustained speculation waste -> turn speculation off. One-way by
        # design: the fallback retraces once, and flapping back on would
        # retrace again every flip — the owning engine only re-enables on
        # restart. Cooldown still applies so a burst of windowed firings
        # books a single action.
        if (waste_rule is not None
                and self.action_counts.get("serving_spec_disable", 0) == 0
                and self._cooldown_ok("serving_spec_disable", step)):
            self._act("serving_spec_disable", waste_rule, step,
                      self.spec_disable_fn, waste_rule,
                      detail="windowed acceptance below floor: draft work "
                             "is being rejected faster than it pays off")
        if overload_rule is not None:
            self._last_overload_step = step
            if not self.admission_paused:
                if self._act("serving_pause", overload_rule, step,
                             self.pause_fn, overload_rule,
                             detail="overload: admission paused, new "
                                    "submits fail fast"):
                    self.admission_paused = True
                    self._pause_rule = overload_rule
        elif (self.admission_paused
                and self._last_overload_step >= 0
                and step - self._last_overload_step
                >= self.resume_clear_steps):
            if self._act("serving_resume", self._pause_rule or "recovered",
                         step, self.resume_fn,
                         detail=f"overload rules quiet for "
                                f"{step - self._last_overload_step} "
                                f"serving steps"):
                self.admission_paused = False
                self._pause_rule = None

    # -------------------------------------------------------------- output
    def report(self):
        with self._lock:
            return {
                "schema": GUARDIAN_SCHEMA,
                "job_name": self.job_name,
                "armed": self.enabled,
                "policies": {
                    "emergency_checkpoint": self.emergency_checkpoint,
                    "emergency_rules": sorted(self.emergency_rules),
                    "max_emergency_checkpoints":
                        self.max_emergency_checkpoints,
                    "rollback": self.rollback,
                    "divergence_window": self.divergence_window,
                    "divergence_streak": self.divergence_streak,
                    "rollback_cooldown_steps": self.rollback_cooldown_steps,
                    "max_rollbacks": self.max_rollbacks,
                    "fp16_rescue": self.fp16_rescue,
                    "max_fp16_rescues": self.max_fp16_rescues,
                    "serving_degrade": self.serving_degrade,
                    "pause_rules": sorted(self.pause_rules),
                    "resume_clear_steps": self.resume_clear_steps,
                    "action_cooldown_steps": self.action_cooldown_steps,
                },
                "rules_seen": dict(self.rules_seen),
                "sources_seen": dict(self.sources_seen),
                "actions": list(self.actions),
                "action_counts": dict(self.action_counts),
                "admission_paused": self.admission_paused,
                "last_step": self.last_step,
            }

    def write_journal(self, path=None):
        path = path or self.journal_path
        if path is None:
            return None
        try:
            _atomic_json(path, self.report())
        except OSError as e:   # journaling must never kill an action
            self._log("[guardian] journal write failed: %s", e)
            return None
        return path

    def close(self):
        """Final journal — only when there is something to explain."""
        if self.actions or self.rules_seen:
            self.write_journal()


# --------------------------------------------------------------------- CLI

def render(report):
    """Human-readable rendering of a GUARDIAN.json report dict."""
    lines = [f"guardian: {'ARMED' if report.get('armed') else 'off'}, "
             f"{len(report.get('actions', []))} action(s)"]
    for k, v in sorted(report.get("rules_seen", {}).items()):
        lines.append(f"  rule {k}: {v} firing(s)")
    for a in report.get("actions", []):
        lines.append(f"  step {a.get('step')}: {a.get('action')} "
                     f"[{a.get('outcome')}] <- {a.get('rule')} "
                     f"({a.get('detail')})")
    return "\n".join(lines)


def _demo(args):
    """Drive a tiny fp16 engine into a guarded divergence: a warning-tier
    anomaly first (emergency checkpoint), then chaos-injected inf params
    (loss_spike + nonfinite streak -> automatic rollback to the user
    tag), then recovery. The committed repo-root GUARDIAN.json example
    comes from here."""
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.testing.chaos import DivergenceChaos
    from deepspeed_tpu.models.simple import SimpleModel, sample_batch
    from deepspeed_tpu.utils import groups

    import jax

    groups.destroy()
    groups.initialize()
    hidden = 32
    ndev = jax.device_count()
    ckpt_dir = tempfile.mkdtemp(prefix="guardian_demo_ckpt_")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden, nlayers=2),
        config={
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 8 // ndev,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "fp16": {"enabled": True, "loss_scale": 0,
                     "initial_scale_power": 8},
            "checkpoint": {"async_save": True},
            "guardian": {"enabled": True, "action_cooldown_steps": 1,
                         "divergence_streak": 2,
                         "emergency_rules": ["grad_norm_spike",
                                             "overflow_streak"],
                         "journal_file": os.path.abspath(args.out)},
            "telemetry": {"enabled": True, "trace": False,
                          "jsonl": False, "prometheus": False,
                          "health": {"enabled": True, "cadence": 1,
                                     "warmup_samples": 2}},
        },
        sample_batch=sample_batch(8, hidden))
    rng = np.random.default_rng(0)

    def batches():
        while True:
            x = rng.standard_normal((8, hidden)).astype(np.float32)
            yield (x, x * 0.5)

    it = batches()
    for step in range(1, args.steps + 1):
        if step == 3:       # the user tag rollback will restore
            engine.save_checkpoint(ckpt_dir)
        engine.train_batch(data_iter=it)
        if step == 5:
            # a first-warning anomaly for the emergency-checkpoint
            # policy: one huge outlier batch spikes the grad norm
            # without poisoning any state
            x = rng.standard_normal((8, hidden)).astype(np.float32) * 200.0
            engine.train_batch(batch=(x, x * 0.5))
    # chaos: poison the params -> loss_spike + nonfinite streak ->
    # rollback to the intact user tag
    chaos = DivergenceChaos(engine, at_call=1)
    with chaos:
        engine.train_batch(data_iter=it)
    for _ in range(3):
        engine.train_batch(data_iter=it)
    engine.close()
    report = engine.guardian_report(write=True)
    print(render(report))
    print(f"\nwrote {args.out}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="self-healing guardian demo/reporting CLI")
    sub = ap.add_subparsers(dest="cmd", required=True)
    demo = sub.add_parser("demo", help="run the guarded-divergence demo "
                                       "and write a GUARDIAN.json")
    demo.add_argument("--out", default="GUARDIAN.json")
    demo.add_argument("--steps", type=int, default=8)
    demo.add_argument("--devices", type=int, default=0)
    show = sub.add_parser("show", help="render an existing GUARDIAN.json")
    show.add_argument("path")
    args = ap.parse_args(argv)
    if args.cmd == "demo":
        return _demo(args)
    with open(args.path) as f:
        print(render(json.load(f)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
