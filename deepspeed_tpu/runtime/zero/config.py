"""ZeRO configuration.

Schema parity with ``deepspeed/runtime/zero/config.py:14``
(``DeepSpeedZeroConfig``) and ``zero/offload_config.py``. Same JSON keys;
typed dataclasses instead of dict-driven attribute stuffing.

On TPU most bucket-size knobs are advisory (XLA schedules collectives), but
they are parsed and honoured where a host-driven path exists (offload).
"""

from dataclasses import dataclass, field
from typing import Optional

ZERO_OPTIMIZATION = "zero_optimization"

VALID_STAGES = (0, 1, 2, 3)

OFFLOAD_DEVICE_NONE = "none"
OFFLOAD_DEVICE_CPU = "cpu"
OFFLOAD_DEVICE_NVME = "nvme"


@dataclass
class DeepSpeedZeroOffloadParamConfig:
    """zero_optimization.offload_param sub-dict (offload_config.py)."""
    device: str = OFFLOAD_DEVICE_NONE
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = int(1e8)
    max_in_cpu: int = int(1e9)
    pin_memory: bool = False

    @classmethod
    def from_dict(cls, d):
        d = d or {}
        return cls(device=d.get("device", OFFLOAD_DEVICE_NONE),
                   nvme_path=d.get("nvme_path"),
                   buffer_count=d.get("buffer_count", 5),
                   buffer_size=int(d.get("buffer_size", 1e8)),
                   max_in_cpu=int(d.get("max_in_cpu", 1e9)),
                   pin_memory=d.get("pin_memory", False))


@dataclass
class DeepSpeedZeroOffloadOptimizerConfig:
    """zero_optimization.offload_optimizer sub-dict."""
    device: str = OFFLOAD_DEVICE_NONE
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write

    @classmethod
    def from_dict(cls, d):
        d = d or {}
        return cls(device=d.get("device", OFFLOAD_DEVICE_NONE),
                   nvme_path=d.get("nvme_path"),
                   buffer_count=d.get("buffer_count", 4),
                   pin_memory=d.get("pin_memory", False),
                   pipeline_read=d.get("pipeline_read", False),
                   pipeline_write=d.get("pipeline_write", False),
                   fast_init=d.get("fast_init", False))


@dataclass
class DeepSpeedZeroConfig:
    """The zero_optimization config block (reference zero/config.py:14)."""
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = int(5e8)
    allgather_partitions: bool = True
    allgather_bucket_size: int = int(5e8)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = True
    cpu_offload: Optional[bool] = None        # deprecated spelling
    cpu_offload_params: Optional[bool] = None  # deprecated spelling
    cpu_offload_use_pin_memory: Optional[bool] = None
    offload_param: DeepSpeedZeroOffloadParamConfig = field(
        default_factory=DeepSpeedZeroOffloadParamConfig)
    offload_optimizer: DeepSpeedZeroOffloadOptimizerConfig = field(
        default_factory=DeepSpeedZeroOffloadOptimizerConfig)
    sub_group_size: int = int(1e9)
    max_live_parameters: int = int(1e9)
    max_reuse_distance: int = int(1e9)
    prefetch_bucket_size: int = int(5e7)
    param_persistence_threshold: int = int(1e5)
    gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False

    @classmethod
    def from_dict(cls, config_dict):
        z = dict(config_dict.get(ZERO_OPTIMIZATION) or {})
        if isinstance(config_dict.get(ZERO_OPTIMIZATION), bool):
            # "zero_optimization": true  → stage 1 (legacy form)
            z = {"stage": 1}

        stage = z.get("stage", 0)
        assert stage in VALID_STAGES, f"invalid ZeRO stage {stage}"

        offload_opt = DeepSpeedZeroOffloadOptimizerConfig.from_dict(
            z.get("offload_optimizer"))
        offload_param = DeepSpeedZeroOffloadParamConfig.from_dict(
            z.get("offload_param"))

        # Deprecated boolean spellings map onto the offload sub-configs
        # (reference zero/config.py reads both).
        if z.get("cpu_offload") and offload_opt.device == OFFLOAD_DEVICE_NONE:
            offload_opt.device = OFFLOAD_DEVICE_CPU
        if z.get("cpu_offload_params") and offload_param.device == OFFLOAD_DEVICE_NONE:
            offload_param.device = OFFLOAD_DEVICE_CPU

        overlap_comm = z.get("overlap_comm")
        if overlap_comm is None:
            # reference default: True for stage 3, False otherwise
            overlap_comm = stage == 3

        return cls(
            stage=stage,
            contiguous_gradients=z.get("contiguous_gradients", True),
            reduce_scatter=z.get("reduce_scatter", True),
            reduce_bucket_size=int(z.get("reduce_bucket_size", 5e8)),
            allgather_partitions=z.get("allgather_partitions", True),
            allgather_bucket_size=int(z.get("allgather_bucket_size", 5e8)),
            overlap_comm=overlap_comm,
            load_from_fp32_weights=z.get("load_from_fp32_weights", True),
            elastic_checkpoint=z.get("elastic_checkpoint", True),
            cpu_offload=z.get("cpu_offload"),
            cpu_offload_params=z.get("cpu_offload_params"),
            cpu_offload_use_pin_memory=z.get("cpu_offload_use_pin_memory"),
            offload_param=offload_param,
            offload_optimizer=offload_opt,
            sub_group_size=int(z.get("sub_group_size", 1e9)),
            max_live_parameters=int(z.get("stage3_max_live_parameters", 1e9)),
            max_reuse_distance=int(z.get("stage3_max_reuse_distance", 1e9)),
            prefetch_bucket_size=int(z.get("stage3_prefetch_bucket_size", 5e7)),
            param_persistence_threshold=int(
                z.get("stage3_param_persistence_threshold", 1e5)),
            gather_16bit_weights_on_model_save=z.get(
                "stage3_gather_16bit_weights_on_model_save",
                z.get("stage3_gather_fp16_weights_on_model_save", False)),
            ignore_unused_parameters=z.get("ignore_unused_parameters", True),
            legacy_stage1=z.get("legacy_stage1", False),
            round_robin_gradients=z.get("round_robin_gradients", False),
        )
