"""At-shape AOT proof for the north-star config (GPT-2 1.5B ZeRO-3, 16 chips).

BASELINE.json's one named target — "GPT-2 1.5B ZeRO-3 on v5e-16 matches
8xA100 NCCL step time" (reference claim:
docs/_posts/2021-03-08-zero3-offload.md:16) — cannot be *executed* in this
environment (one real chip, no 16-chip slice). What CAN be proven, and
what this module proves, is that the full ZeRO-3 engine step **builds at
true scale**: the train step is jitted with ``abstract_init=True`` (no
array is ever materialised), lowered over a 16-device mesh at the real
1.5B shapes, SPMD-partitioned, and compiled; the artifact records

- the EXACT per-chip state footprint (params + Adam moments + grad
  accumulator + scalars, every leaf's sharded slice counted from its
  NamedSharding) — the ZeRO-3 partitioning claim, asserted <= HBM;
- the collective structure of the compiled program (all-gather /
  all-reduce counts — the param-gather traffic ZeRO-3 is made of);
- the compiler's own memory analysis. Caveat, recorded in the artifact:
  the only 16-device compile target this environment offers is the CPU
  backend, whose scheduler does not optimise temp liveness the way the
  TPU's latency-hiding scheduler does, and whose attention path is the
  XLA O(S^2) fallback (Pallas flash lowers only for TPU). Its temp
  number is therefore an upper bound of the wrong schedule, and a
  TPU-semantics activation budget is derived analytically beside it.

Run as a module to (re)generate the committed artifact::

    python -m deepspeed_tpu.runtime.zero.aot_check NORTHSTAR_AOT.json
"""

import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

HBM_BYTES = 16 * 1024 ** 3          # v5e: 16 GiB per chip


def _leaf_sharded_bytes(leaf, sharding):
    """Bytes of ONE device's slice of a (possibly sharded) leaf —
    ``shard_shape`` is the sharding's own answer, correct even for
    padded/uneven shards."""
    return (int(np.prod(sharding.shard_shape(leaf.shape)))
            * np.dtype(leaf.dtype).itemsize)


def state_footprint_per_chip(engine):
    """EXACT per-chip bytes of the engine state, by component, from the
    abstract state tree and its shardings (no compile needed)."""
    out = {}
    for name in ("params", "opt_state", "acc_grads"):
        leaves = jax.tree.leaves(getattr(engine.state, name))
        shards = jax.tree.leaves(getattr(engine.state_shardings, name))
        assert len(leaves) == len(shards)
        out[name] = sum(_leaf_sharded_bytes(l, s)
                        for l, s in zip(leaves, shards))
    out["total"] = sum(out.values())
    return out


def northstar_aot_report(n_devices=16, seq=1024, per_chip_batch=1,
                         compile_program=True):
    """Build the 1.5B ZeRO-3 engine abstractly over ``n_devices``, lower
    the fused train step, and return the report dict."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (GPT2LMHeadModel, PRESETS,
                                           synthetic_batch)
    from deepspeed_tpu.utils import groups

    assert len(jax.devices()) >= n_devices, (
        f"need {n_devices} (virtual) devices; got {len(jax.devices())} — "
        "force them BEFORE importing anything that initialises a backend "
        "(see __graft_entry__._force_virtual_cpu_devices)")
    groups.destroy()
    groups.initialize(devices=jax.devices()[:n_devices])
    # activation checkpointing on, as the reference's 1.5B configs run
    cfg = dataclasses.replace(PRESETS["gpt2-xl"], remat=True)
    global_batch = per_chip_batch * n_devices
    batch = synthetic_batch(global_batch, seq, cfg.vocab_size)
    t0 = time.time()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg),
        config={"train_batch_size": global_batch,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 3},
                "bf16": {"enabled": True}},
        sample_batch=batch,
        abstract_init=True)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(engine.state.params))
    state = state_footprint_per_chip(engine)

    lowered = engine.lower_train_step(batch)
    lower_s = time.time() - t0

    report = {
        "config": {
            "model": "gpt2-xl (1.5B)", "n_embd": cfg.n_embd,
            "n_layer": cfg.n_layer, "n_head": cfg.n_head,
            "seq": seq, "per_chip_batch": per_chip_batch,
            "n_devices": n_devices, "zero_stage": 3, "remat": True,
            "dtype": "bf16 compute, f32 masters+moments+acc",
        },
        "n_params": n_params,
        "per_chip_state_bytes": state,
        "per_chip_state_gb": round(state["total"] / 1024 ** 3, 3),
        "hbm_bytes": HBM_BYTES,
        "state_fits_hbm": state["total"] <= HBM_BYTES,
        "lower_seconds": round(lower_s, 1),
    }

    E, L, V = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    B, S = per_chip_batch, seq
    act = {
        "remat_residuals": L * B * S * E * 2,
        "block_working_set": B * S * (9 * E) * 2,
        "ce_logits_fwd_bwd": 2 * B * S * V * 4,
        "gathered_bf16_params_all_live": n_params * 2,
        "transient_f32_grads_all_live": n_params * 4,
    }
    act["total"] = sum(act.values())
    report["tpu_activation_budget_bytes"] = act
    report["tpu_budget_total_gb"] = round(
        (state["total"] + act["total"]) / 1024 ** 3, 3)
    report["tpu_budget_fits_hbm"] = \
        state["total"] + act["total"] <= HBM_BYTES

    if compile_program:
        from deepspeed_tpu.telemetry.hlo_census import census_compiled
        t0 = time.time()
        compiled = lowered.compile()
        report["compile_seconds"] = round(time.time() - t0, 1)
        # shared census (telemetry/hlo_census.py): a REAL parse of the
        # compiled program — per-collective byte volumes and mesh-axis
        # attribution, replacing the old brittle txt.count(op + "(")
        census = census_compiled(compiled, mesh=groups.get_mesh())
        # census sections are best-effort for live telemetry, but a
        # COMMITTED artifact must not silently record zeros when the
        # backend refused an analysis
        assert census.argument_bytes > 0 and census.flops > 0, (
            "memory/cost analysis unavailable on this backend — refusing "
            "to write a zeroed NORTHSTAR artifact")
        report["cpu_backend_memory_analysis"] = {
            "argument_bytes": census.argument_bytes,
            "output_bytes": census.output_bytes,
            "alias_bytes": census.alias_bytes,
            "temp_bytes": census.temp_bytes,
            "caveat": (
                "CPU is the only 16-device compile target here: its "
                "scheduler does not minimise temp liveness and its "
                "attention is the XLA O(S^2) fallback (flash is "
                "TPU-only), so temp_bytes is an upper bound of the "
                "wrong schedule; the TPU budget above is the "
                "schedule-independent estimate"),
        }
        report["collectives"] = {
            op: census.collective_counts.get(op, 0)
            for op in ("all-gather", "reduce-scatter", "all-reduce",
                       "collective-permute", "all-to-all")}
        # consistency proof for the parser swap: on this same text the
        # structured counts must equal what the old string counter saw
        # (plus async -start forms, which only the parser can attribute)
        txt = compiled.as_text()
        for op, n in report["collectives"].items():
            # space-anchored so e.g. "all-to-all(" cannot also match a
            # "ragged-all-to-all(" (the census counts ragged separately)
            legacy = txt.count(f" {op}(") + txt.count(f" {op}-start(")
            assert n == legacy, (
                f"census parser counted {n} x {op} but the text contains "
                f"{legacy} — parser regression")
        cdict = census.to_dict()["collectives"]
        report["collectives_detail"] = {
            "result_bytes": cdict["result_bytes"],
            "wire_bytes_per_chip": cdict["wire_bytes"],
            "bytes_by_mesh_axis": cdict["bytes_by_axis"],
            "total_wire_bytes_per_chip": cdict["total_wire_bytes"],
        }
        report["xla_flops_per_chip_per_step"] = census.flops
    return report


def main(out_path="NORTHSTAR_AOT.json"):
    import os
    import sys
    sys.path.insert(0, ".")
    from __graft_entry__ import _force_virtual_cpu_devices
    _force_virtual_cpu_devices(16)
    committed = None
    if os.path.isfile(out_path):
        with open(out_path) as f:
            committed = json.load(f)
    report = northstar_aot_report()
    if committed and "collectives" in committed \
            and report["collectives"] != committed["collectives"]:
        # parser-vs-text consistency is asserted inside the report
        # builder; a diff against the COMMITTED artifact means the
        # compiled program itself drifted since the artifact was written
        # — exactly what this regeneration records. Surface it loudly.
        print(f"NOTE: collective structure drifted since the committed "
              f"artifact: {committed['collectives']} -> "
              f"{report['collectives']} (program change, not a parser "
              f"regression — the parser is asserted against the text)")
        report["collectives_drift_from_previous"] = committed["collectives"]
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: v for k, v in report.items()
                      if k != "cpu_backend_memory_analysis"}, indent=1))
    assert report["state_fits_hbm"] and report["tpu_budget_fits_hbm"]


if __name__ == "__main__":
    import sys
    main(*sys.argv[1:])
