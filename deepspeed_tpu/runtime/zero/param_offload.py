"""ZeRO-3 parameter offload: params resident on host (CPU RAM or NVMe),
streamed to the device layer-by-layer.

TPU-native rebuild of the reference's "40B params on one 32GB GPU"
machinery: ``zero.Init`` remote_device cpu/nvme
(deepspeed/runtime/zero/partition_parameters.py:701), the fetch/release
``PartitionedParameterCoordinator`` (zero/stage3.py:172), and the
``AsyncPartitionedParameterSwapper`` (swap_tensor/partitioned_param_swapper
.py:36). The reference intercepts nn.Module construction and autograd with
hooks because PyTorch is eager; under XLA the equivalent is a host-driven
layer loop:

* the model is a SEQUENCE of flax layers (the LayerSpec decomposition the
  reference's pipeline module also uses) — the full parameter set NEVER
  exists on the device;
* ``zero_init`` materialises each layer's params once, pulls them to host
  fp32 masters, and frees the device copy (zero.Init semantics: peak
  device residency = one layer);
* forward fetches layer i's params (async ``device_put`` = the allgather
  of ``fetch_sub_module``), prefetches layer i+1 (double buffering —
  ``__prefetch_nvme_param_partitions`` stage3.py:470), computes, releases;
* backward re-fetches each layer and recomputes its VJP locally (layer-
  granular rematerialisation — the PyTorch build re-fetches params via
  PreBackwardFunction hooks, stage3.py:496); gradients stream straight to
  host fp32 buffers;
* the optimizer step is a host CPU-Adam sweep (csrc/cpu_adam.cpp via
  ops/adam/cpu_adam.py) over the masters, per layer, so NVMe-resident
  masters only visit RAM one layer at a time.

Scope: single-device data path (the point is fitting a model that exceeds
one chip's HBM); compose dp/tp via the main engine when the model fits.
"""

import os
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def _nbytes(tree) -> int:
    return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(tree))


_store_ids = iter(range(1 << 30))


class HostParamStore:
    """Per-layer host fp32 masters with optional NVMe backing and live-
    bytes accounting (the swap half of partitioned_param_swapper.py:36)."""

    def __init__(self, nvme_path: Optional[str] = None,
                 swap_folder: Optional[str] = None):
        # swap keys are namespaced per store so several stores may share
        # one caller-supplied folder without clobbering each other
        self._key_prefix = f"st{next(_store_ids)}_{os.getpid()}_"
        self._ram: List[Optional[List[np.ndarray]]] = []
        self.treedefs: List[Any] = []
        self.swapper = None
        self._swap_folder = None
        self._owns_folder = False
        if nvme_path is not None:
            from deepspeed_tpu.runtime.swap_tensor.swapper import \
                AsyncTensorSwapper
            self._owns_folder = swap_folder is None
            self._swap_folder = swap_folder or os.path.join(
                nvme_path, f"ds_param_offload_{os.getpid()}")
            self.swapper = AsyncTensorSwapper(self._swap_folder)
        # device residency accounting (tests assert peak << total)
        self.live_bytes = 0
        self.peak_live_bytes = 0
        self.total_param_bytes = 0
        self._dev: dict = {}
        self._dev_bytes: dict = {}

    def _key(self, i: int, j: int) -> str:
        return f"{self._key_prefix}L{i}_p{j}"

    # ------------------------------------------------------------- host side
    def add_layer(self, params) -> int:
        """Take ownership of one layer's params as host fp32 leaves."""
        leaves, treedef = jax.tree.flatten(params)
        # np.array (not asarray): device_get returns read-only views, and
        # these buffers are the in-place-updated fp32 masters. order="C"
        # is load-bearing: some backends (axon) hand back F-ordered
        # arrays, and the default order="K" would preserve that — masters
        # and their zeros_like moments must honor the CPU-Adam kernel's
        # C-contiguity contract
        host = [np.array(jax.device_get(l), np.float32, order="C")
                for l in leaves]
        self.total_param_bytes += sum(h.nbytes for h in host)
        i = len(self.treedefs)
        self.treedefs.append(treedef)
        if self.swapper is not None:
            for j, h in enumerate(host):
                self.swapper.swap_out(self._key(i, j), h)
            self.swapper.synchronize()
            self._ram.append(None)
        else:
            self._ram.append(host)
        return i

    def host_leaves(self, i: int) -> List[np.ndarray]:
        """Masters of layer i in RAM (swapped in from NVMe if backed)."""
        if self._ram[i] is not None:
            return self._ram[i]
        return [self.swapper.swap_in(self._key(i, j))
                for j in range(self.treedefs[i].num_leaves)]

    def write_back(self, i: int, leaves: List[np.ndarray]):
        """Persist updated masters (NVMe mode; RAM mode updates in place)."""
        if self._ram[i] is not None:
            return
        for j, h in enumerate(leaves):
            self.swapper.swap_out(self._key(i, j), h)
        self.swapper.synchronize()

    def close(self):
        """Delete this run's NVMe swap files (masters are full model size —
        leaking them across runs fills the device). A caller-supplied
        swap_folder may be shared, so only this store's own files go."""
        if self.swapper is None or self._swap_folder is None:
            return
        self.swapper.synchronize()
        if self._owns_folder:
            import shutil
            shutil.rmtree(self._swap_folder, ignore_errors=True)
        else:
            for i, td in enumerate(self.treedefs):
                for j in range(td.num_leaves):
                    try:
                        os.remove(self.swapper._path(self._key(i, j)))
                    except OSError:
                        pass
        self.swapper = None

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass

    # ----------------------------------------------------------- device side
    def fetch(self, i: int, dtype) -> Any:
        """Async put of layer i's params to device (fetch_sub_module)."""
        if i in self._dev:
            return self._dev[i]
        leaves = [jnp.asarray(h, dtype) for h in self.host_leaves(i)]
        tree = jax.tree.unflatten(self.treedefs[i], leaves)
        self._dev[i] = tree
        self._dev_bytes[i] = _nbytes(tree)
        self.live_bytes += self._dev_bytes[i]
        self.peak_live_bytes = max(self.peak_live_bytes, self.live_bytes)
        return tree

    def release(self, i: int):
        """Drop the device copy (release_sub_module / param.partition())."""
        if i in self._dev:
            self.live_bytes -= self._dev_bytes.pop(i)
            del self._dev[i]


class Zero3OffloadEngine:
    """Train a layered model whose parameters exceed device memory.

    ``layers[:-1]`` map ``x -> x``; ``layers[-1]`` maps ``(x, batch) ->
    scalar loss`` (the LayerSpec + loss-head decomposition). ``input_fn``
    extracts the first layer's input from a batch (default ``batch[0]``).
    """

    def __init__(self, layers: Sequence, sample_batch, lr=1e-3,
                 betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 adamw_mode=True, compute_dtype=jnp.float32,
                 input_fn: Callable = None, nvme_path: Optional[str] = None,
                 seed: int = 0):
        self.layers = list(layers)
        assert len(self.layers) >= 2, "need at least one body layer + loss head"
        self.input_fn = input_fn or (lambda b: b[0])
        self.compute_dtype = compute_dtype
        self.lr = lr
        self._betas, self._eps, self._wd = betas, eps, weight_decay
        self._adamw = adamw_mode
        self.store = HostParamStore(nvme_path=nvme_path)
        self._adam = _HostAdam(betas, eps, weight_decay, adamw_mode)
        self.global_steps = 0

        # per-layer compiled fns: init, fwd, vjp-recompute, loss head
        # grad. Deduped by module equality: a 48-block GPT stack compiles
        # ONE init + ONE fwd + ONE bwd program shared by every identical
        # block instead of 144 (flax modules are value-hashable
        # dataclasses). Jitting init/apply is load-bearing for remote
        # backends: eager tracing dispatches every primitive as its own
        # ~100 ms tunnel round trip, turning a 1.5B-param zero_init into
        # hours.
        init_cache, fwd_cache, bwd_cache = {}, {}, {}

        def jinit(mod):
            if mod not in init_cache:
                init_cache[mod] = jax.jit(mod.init)
            return init_cache[mod]

        def fwd(mod):
            if mod not in fwd_cache:
                fwd_cache[mod] = jax.jit(
                    lambda p, x: mod.apply({"params": p}, x))
            return fwd_cache[mod]

        def bwd(mod):
            if mod not in bwd_cache:
                def f(p, x, ct):
                    _, vjp = jax.vjp(
                        lambda p, x: mod.apply({"params": p}, x), p, x)
                    return vjp(ct)
                bwd_cache[mod] = jax.jit(f)
            return bwd_cache[mod]

        # zero.Init: masters are born ON THE HOST — each layer's init runs
        # on the CPU backend (JAX RNG is bit-deterministic across
        # backends) and inter-layer shapes propagate via eval_shape, so
        # NO parameter bytes ever cross the accelerator link at init.
        # This matters doubly on asymmetric links: the axon tunnel moves
        # H2D at ~830 MB/s but D2H at ~4 MB/s, which priced a 6 GB
        # init-time device_get at ~25 minutes. Init inputs are zeros
        # (param shapes here don't depend on input values).
        try:
            cpu_dev = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # pragma: no cover — cpu backend always exists
            cpu_dev = None
        rng = jax.random.PRNGKey(seed)
        x_aval = jax.eval_shape(lambda b: jnp.asarray(self.input_fn(b)),
                                sample_batch)
        batch_zeros = jax.tree.map(
            lambda l: np.zeros(np.shape(l), np.asarray(l).dtype),
            sample_batch)
        with jax.default_device(cpu_dev):
            for i, m in enumerate(self.layers):
                lrng = jax.random.fold_in(rng, i)
                x_zero = jnp.zeros(x_aval.shape, x_aval.dtype)
                if i < len(self.layers) - 1:
                    variables = jinit(m)(lrng, x_zero)
                    x_aval = jax.eval_shape(
                        lambda p, xx, mod=m: mod.apply({"params": p}, xx),
                        variables["params"], x_aval)
                else:
                    variables = jinit(m)(lrng, x_zero, batch_zeros)
                self.store.add_layer(variables["params"])
                del variables  # host master is authoritative
        # moments live with the masters (RAM; the optimizer-state NVMe
        # swapper in zero/offload.py covers disk-resident moments)
        self._m = [[np.zeros_like(h) for h in self.store.host_leaves(i)]
                   for i in range(len(self.layers))]
        self._v = [[np.zeros_like(h) for h in self.store.host_leaves(i)]
                   for i in range(len(self.layers))]

        self._fwd = [fwd(m) for m in self.layers[:-1]]
        self._bwd = [bwd(m) for m in self.layers[:-1]]
        head = self.layers[-1]
        self._head_grad = jax.jit(jax.value_and_grad(
            lambda p, x, b: head.apply({"params": p}, x, b), argnums=(0, 1)))
        log_dist(f"Zero3OffloadEngine: {len(self.layers)} layers, "
                 f"{self.store.total_param_bytes / 2**20:.1f} MiB params "
                 f"host-resident ({'nvme' if nvme_path else 'cpu'})",
                 ranks=[0])

    # ------------------------------------------------------------------ train
    def train_batch(self, batch=None):
        L = len(self.layers)
        dt = self.compute_dtype
        x = jnp.asarray(self.input_fn(batch))
        if jnp.issubdtype(x.dtype, jnp.floating):  # token ids stay integer
            x = x.astype(dt)

        # forward sweep: fetch i, prefetch i+1, compute, release
        acts = [x]
        p_cur = self.store.fetch(0, dt)
        for i in range(L - 1):
            self.store.fetch(i + 1, dt)          # double buffer: next layer
            x = self._fwd[i](p_cur, x)
            acts.append(x)
            self.store.release(i)
            p_cur = self.store.fetch(i + 1, dt)

        # loss head: value + grads wrt (params, input)
        loss, (g_head, ct) = self._head_grad(
            self.store.fetch(L - 1, dt), acts[-1], batch)
        grads = {L - 1: self._to_host(g_head)}
        self.store.release(L - 1)

        # backward sweep: re-fetch, recompute VJP, stream grads to host
        for i in reversed(range(L - 1)):
            if i - 1 >= 0:
                self.store.fetch(i - 1, dt)      # double buffer: prev layer
            g_p, ct = self._bwd[i](self.store.fetch(i, dt), acts[i], ct)
            grads[i] = self._to_host(g_p)
            self.store.release(i)

        self._step(grads)
        self.global_steps += 1
        return loss

    def _to_host(self, grad_tree) -> List[np.ndarray]:
        return [np.asarray(jax.device_get(g), np.float32)
                for g in jax.tree.leaves(grad_tree)]

    def _step(self, grads):
        """Host Adam sweep, one layer at a time (NVMe masters visit RAM
        only for their own update — the PartitionedOptimizerSwapper
        access pattern)."""
        step_no = self.global_steps + 1
        for i in range(len(self.layers)):
            masters = self.store.host_leaves(i)
            for p, g, m, v in zip(masters, grads[i], self._m[i], self._v[i]):
                self._adam.step_leaf(step_no, self.lr, p, g, m, v)
            self.store.write_back(i, masters)

    # ------------------------------------------------------------ checkpoint
    def state_dict(self):
        # deep-copy: the masters/moments are mutated in place every step
        return {
            "params": [[np.array(h) for h in self.store.host_leaves(i)]
                       for i in range(len(self.layers))],
            "exp_avg": [[np.array(a) for a in layer] for layer in self._m],
            "exp_avg_sq": [[np.array(a) for a in layer] for layer in self._v],
            "step": self.global_steps,
        }

    def load_state_dict(self, sd):
        for i, leaves in enumerate(sd["params"]):
            masters = self.store.host_leaves(i)
            for dst, src in zip(masters, leaves):
                np.copyto(dst, src)
            self.store.write_back(i, masters)
        self._m = [[np.array(a) for a in layer] for layer in sd["exp_avg"]]
        self._v = [[np.array(a) for a in layer] for layer in sd["exp_avg_sq"]]
        self.global_steps = sd["step"]

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        """Engine-compatible file layout: one model-states file holding
        the layered masters + moments (single-process engine — the dp=1
        analogue of runtime/checkpoint_io.py), plus the `latest` tag."""
        import pickle

        from deepspeed_tpu.runtime.engine import (LATEST_FILE,
                                                  MODEL_FILE_SUFFIX)
        if tag is None:
            tag = f"global_step{self.global_steps}"
        tag_dir = os.path.join(save_dir, str(tag))
        os.makedirs(tag_dir, exist_ok=True)
        sd = self.state_dict()
        sd["client_state"] = client_state or {}
        with open(os.path.join(tag_dir, f"mp_rank_00{MODEL_FILE_SUFFIX}"),
                  "wb") as f:
            pickle.dump(sd, f)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
        return True

    def load_checkpoint(self, load_dir, tag=None):
        import pickle

        from deepspeed_tpu.runtime.engine import (LATEST_FILE,
                                                  MODEL_FILE_SUFFIX)
        if tag is None:
            latest = os.path.join(load_dir, LATEST_FILE)
            if not os.path.exists(latest):
                # engine contract (engine.py load_checkpoint): resume-if-
                # present — a fresh run starts from scratch, no crash
                log_dist(f"no '{LATEST_FILE}' file under {load_dir}; "
                         "starting from scratch", ranks=[0])
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        path = os.path.join(load_dir, str(tag),
                            f"mp_rank_00{MODEL_FILE_SUFFIX}")
        with open(path, "rb") as f:
            sd = pickle.load(f)
        client_state = sd.pop("client_state", {})
        self.load_state_dict(sd)
        return path, client_state


class _HostAdam:
    """One Adam leaf update on host buffers: the AVX C++ kernel when it
    builds (csrc/cpu_adam.cpp via CPUAdamBuilder), else vectorised numpy.
    Kept per-leaf (not list-bound like DeepSpeedCPUAdam) so NVMe-resident
    masters can stream through RAM one layer at a time."""

    def __init__(self, betas, eps, weight_decay, adamw_mode):
        self.b1, self.b2 = betas
        self.eps = eps
        self.wd = weight_decay
        self.adamw = adamw_mode
        self.lib = None
        self.opt_id = None
        try:
            from deepspeed_tpu.ops.op_builder.builder import CPUAdamBuilder
            if CPUAdamBuilder().is_compatible():
                from deepspeed_tpu.ops.adam import cpu_adam as _ca
                self.lib = CPUAdamBuilder().load()
                self.opt_id = next(_ca._ids)
                self.lib.ds_adam_create(self.opt_id, self.b1, self.b2, eps,
                                        weight_decay, 1 if adamw_mode else 0)
        except Exception:  # pragma: no cover — numpy fallback always works
            self.lib = None

    def step_leaf(self, step_no, lr, p, g, m, v):
        g = np.ascontiguousarray(g, np.float32)
        if self.lib is not None:
            from deepspeed_tpu.ops.adam.cpu_adam import _ptr
            rc = self.lib.ds_adam_step(self.opt_id, step_no, lr, _ptr(p),
                                       _ptr(g), _ptr(m), _ptr(v), p.size)
            assert rc == 0, f"ds_adam_step failed ({rc})"
            return
        if self.adamw:
            p *= (1.0 - lr * self.wd)
        elif self.wd:
            g = g + self.wd * p
        m *= self.b1
        m += (1 - self.b1) * g
        v *= self.b2
        v += (1 - self.b2) * g * g
        mh = m / (1 - self.b1 ** step_no)
        vh = v / (1 - self.b2 ** step_no)
        p -= lr * mh / (np.sqrt(vh) + self.eps)

    def __del__(self):
        if self.lib is not None:
            try:
                self.lib.ds_adam_destroy(self.opt_id)
            except Exception:
                pass
