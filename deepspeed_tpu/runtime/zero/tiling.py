"""TiledLinear — a huge Linear split into a tile grid.

Rebuild of deepspeed/runtime/zero/tiling.py:27 (``TiledLinear``,
``TiledLinearReturnBias`` :257): the reference splits a giant nn.Linear
into in_splits x out_splits smaller Linears so ZeRO-3 fetches one tile at
a time instead of the whole weight. Under XLA the same decomposition pays
off differently but for the same reason — each tile is an independent
param leaf, so the ZeRO-3 sharder, the param-offload store, and the
checkpoint layout all operate at tile granularity (a 50k x 50k fp32
weight becomes 16 leaves of 625M instead of one 10GB leaf).

Math parity: out[:, oc] = sum_ic x[:, ic] @ W[ic, oc] (+ bias[oc]), which
is exactly the untitled Linear for any split counts.
"""

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp


def split_dim(total: int, parts: int):
    """(sizes, bounds) of the reference's near-even split — delegates to
    the one partition_uniform implementation (runtime/pipe/module.py)."""
    from deepspeed_tpu.runtime.pipe.module import partition_uniform
    bounds = partition_uniform(total, parts)
    sizes = [bounds[i + 1] - bounds[i] for i in range(parts)]
    return sizes, bounds


class TiledLinear(nn.Module):
    """in_splits x out_splits grid of Dense tiles == one big Linear."""
    in_features: int
    out_features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        assert x.shape[-1] == self.in_features, (
            f"expected {self.in_features} input features, got {x.shape}")
        in_sizes, in_bounds = split_dim(self.in_features, self.in_splits)
        out_sizes, _ = split_dim(self.out_features, self.out_splits)

        outs = []
        for oc, osz in enumerate(out_sizes):
            acc = None
            for ic, isz in enumerate(in_sizes):
                xin = x[..., in_bounds[ic]:in_bounds[ic + 1]]
                # bias lives on the last input tile only (added once)
                tile = nn.Dense(
                    osz, use_bias=self.use_bias and ic == len(in_sizes) - 1,
                    kernel_init=self.kernel_init, bias_init=self.bias_init,
                    dtype=self.dtype, name=f"tile_{ic}_{oc}")(xin)
                acc = tile if acc is None else acc + tile
            outs.append(acc)
        return jnp.concatenate(outs, axis=-1)


class TiledLinearReturnBias(TiledLinear):
    """Variant returning (out_without_bias, bias) — the reference's form
    for megatron row-parallel layers that defer the bias add until after
    the allreduce (tiling.py:257)."""

    @nn.compact
    def __call__(self, x):
        out = TiledLinear(
            in_features=self.in_features, out_features=self.out_features,
            in_splits=self.in_splits, out_splits=self.out_splits,
            use_bias=False, kernel_init=self.kernel_init,
            dtype=self.dtype, name="tiles")(x)
        bias = None
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.out_features,))
        return out, bias
