"""ZeRO partitioning as declarative sharding rules.

This is the TPU-native replacement for the reference's three imperative
machines — ``DeepSpeedZeroOptimizer`` (stage_1_and_2.py:80),
``DeepSpeedZeroOptimizer_Stage3`` (stage3.py:545) and the ``zero.Init``
param partitioner (partition_parameters.py:272). On GPU those exist because
eager PyTorch cannot plan: ZeRO-3 hooks every module to allgather params
just-in-time, buckets grads into 500 MB IPG buffers, and hand-schedules
reduce-scatters on side streams. Under XLA the SAME dataflow is obtained by
*sharding annotations alone*:

* **stage 1** — optimizer state sharded over the DP axes. The jitted update
  computes Adam moments shard-wise; XLA materialises only the local shard
  and inserts the epilogue all-gather of updated params (the reference's
  stage_1_and_2.py:1745 allgather loop).
* **stage 2** — additionally constrain gradients to the same sharding; the
  grad psum becomes a fused reduce-scatter (the IPG-bucket machinery,
  reduce_independent_p_g_buckets_and_remove_grads stage_1_and_2.py:805,
  collapses into one compiler decision).
* **stage 3** — the fp32 master params themselves are sharded; XLA inserts
  per-use all-gathers in the forward/backward and frees gathered copies
  after last use — the compile-time equivalent of
  PartitionedParameterCoordinator's trace-based prefetch/release
  (stage3.py:294/:389). ``param_persistence_threshold`` maps to
  ``min_shard_numel``: tiny params stay replicated, exactly like
  ``ds_persist`` (partition_parameters.py:770).

Model-parallel (megatron) specs compose: ZeRO picks a *free* dimension not
already claimed by the MP spec.
"""

import re
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils import groups


def _axes_size(mesh: Mesh, axes: Sequence[str]) -> int:
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def choose_zero_spec(shape,
                     mesh: Mesh,
                     dp_axes: Sequence[str],
                     mp_spec: Optional[P] = None,
                     min_numel: int = 0) -> P:
    """Pick the PartitionSpec for one tensor: MP spec + a DP dimension.

    The DP axes go on the largest dimension divisible by the DP world that
    the MP spec has not claimed. Tensors smaller than *min_numel* (the
    ``ds_persist`` analogue) keep only their MP spec.
    """
    ndim = len(shape)
    mp = list(mp_spec) if mp_spec is not None else []
    mp += [None] * (ndim - len(mp))

    numel = int(np.prod(shape)) if ndim else 1
    dp_size = _axes_size(mesh, dp_axes)
    if numel < max(min_numel, 1) or ndim == 0 or dp_size == 1:
        return P(*mp) if any(a is not None for a in mp) else P()

    # candidate dims: unclaimed by MP, divisible by dp world
    best_dim, best_len = -1, 0
    for d in range(ndim):
        if mp[d] is None and shape[d] % dp_size == 0 and shape[d] > best_len:
            best_dim, best_len = d, shape[d]
    if best_dim < 0:
        return P(*mp) if any(a is not None for a in mp) else P()

    spec = list(mp)
    dp_axes = tuple(a for a in dp_axes if mesh.shape[a] > 1)
    spec[best_dim] = dp_axes if len(dp_axes) > 1 else dp_axes[0]
    return P(*spec)


class ModelParallelRules:
    """Path-regex → PartitionSpec table (megatron-style TP).

    The reference delegates TP to an external mpu (engine.py:1030); here the
    rules ARE the mpu: e.g. ``(".*attn/qkv/kernel", P(None, "model"))`` for
    column parallel, ``(".*attn/out/kernel", P("model", None))`` for row
    parallel.
    """

    def __init__(self, rules=None):
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]

    def spec_for(self, path: str) -> Optional[P]:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return None


def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def build_param_shardings(params: Any,
                          mesh: Mesh,
                          stage: int,
                          mp_rules: Optional[ModelParallelRules] = None,
                          min_shard_numel: int = 0,
                          expert_filter=None) -> Any:
    """NamedSharding pytree for the fp32 master params.

    stage<3: params replicated across DP (MP spec only).
    stage 3: params sharded over DP axes too.
    Expert params (selected by *expert_filter* on the path string) shard
    over the expert-data axes only — their "DP group" excludes the expert
    axis (reference _configure_moe_settings, stage_1_and_2.py:501).
    """
    mp_rules = mp_rules or ModelParallelRules()

    def assign(path, leaf):
        p = _path_str(path)
        mp_spec = mp_rules.spec_for(p)
        is_expert = expert_filter(p) if expert_filter else _default_expert_filter(p)
        dp_axes = groups.expert_data_parallel_axes() if is_expert \
            else groups.data_parallel_axes()
        if stage >= 3:
            spec = choose_zero_spec(leaf.shape, mesh, dp_axes, mp_spec,
                                    min_numel=min_shard_numel)
        else:
            spec = mp_spec if mp_spec is not None else P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(assign, params)


def build_opt_shardings(opt_state: Any,
                        mesh: Mesh,
                        stage: int,
                        mp_rules: Optional[ModelParallelRules] = None,
                        min_shard_numel: int = 0,
                        expert_filter=None) -> Any:
    """NamedSharding pytree for optimizer state (or any param-shaped tree).

    Leaves shaped like a parameter (mu/nu/trust-ratio buffers — optimizer
    states embed copies of the param pytree, so the param name appears in
    the leaf path and the MP rules and expert filter apply unchanged) get
    stage>=1 DP sharding; scalars (step counts) replicate.
    """

    def assign(path, leaf):
        if not hasattr(leaf, "shape") or leaf.ndim == 0:
            return NamedSharding(mesh, P())
        p = _path_str(path)
        mp_spec = (mp_rules.spec_for(p) if mp_rules else None)
        is_expert = expert_filter(p) if expert_filter else _default_expert_filter(p)
        dp_axes = groups.expert_data_parallel_axes() if is_expert \
            else groups.data_parallel_axes()
        if stage >= 1:
            spec = choose_zero_spec(leaf.shape, mesh, dp_axes, mp_spec,
                                    min_numel=min_shard_numel)
        else:
            spec = mp_spec if mp_spec is not None else P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(assign, opt_state)


def grad_constraint_fn(mesh: Mesh,
                       stage: int,
                       mp_rules: Optional[ModelParallelRules] = None,
                       min_shard_numel: int = 0):
    """Return a fn applying ``with_sharding_constraint`` to a grad pytree.

    stage>=2 turns the DP grad all-reduce into reduce-scatter (the ZeRO-2
    IPG-bucket path); stage<2 is identity (grads follow params).
    """
    if stage < 2:
        return lambda grads: grads

    def constrain(grads):
        def assign(path, leaf):
            p = _path_str(path)
            mp_spec = mp_rules.spec_for(p) if mp_rules else None
            is_expert = _default_expert_filter(p)
            dp_axes = groups.expert_data_parallel_axes() if is_expert \
                else groups.data_parallel_axes()
            spec = choose_zero_spec(leaf.shape, mesh, dp_axes, mp_spec,
                                    min_numel=min_shard_numel)
            return jax.lax.with_sharding_constraint(leaf, NamedSharding(mesh, spec))
        return jax.tree_util.tree_map_with_path(assign, grads)

    return constrain


def _default_expert_filter(path: str) -> bool:
    """Expert params are tagged by module name (reference moe/utils.py:18
    ``is_moe_param`` checks ``param.allreduce == False``; here the MoE layer
    namespaces its experts under 'experts/')."""
    return "deepspeed_experts" in path or "experts/" in path


def estimate_zero_mem(num_params: int, dp_world: int, stage: int,
                      bytes_per_param_fp32=4, bytes_per_param_bf16=2,
                      optimizer_mult=2):
    """Per-device memory model (reference estimate_zero{2,3}_model_states_mem_needs,
    stage_1_and_2.py:2229 / stage3.py tail). Returns bytes for (params,
    grads, optimizer state) per device."""
    p = num_params
    opt_bytes = optimizer_mult * bytes_per_param_fp32 * p  # m+v fp32
    master_bytes = bytes_per_param_fp32 * p
    grad_bytes = bytes_per_param_fp32 * p
    model_bytes = bytes_per_param_bf16 * p
    if stage == 0:
        return model_bytes + grad_bytes + master_bytes + opt_bytes
    if stage == 1:
        return model_bytes + grad_bytes + (master_bytes + opt_bytes) / dp_world
    if stage == 2:
        return model_bytes + (grad_bytes + master_bytes + opt_bytes) / dp_world
    return (model_bytes + grad_bytes + master_bytes + opt_bytes) / dp_world
