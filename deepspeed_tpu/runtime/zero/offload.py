"""ZeRO-Offload: optimizer state in host RAM (or NVMe), updates on CPU.

TPU-native rebuild of the reference's offload paths: CPU-Adam on pinned
host buffers (stage_1_and_2.py cpu_offload,
async_accumulate_grad_in_cpu_via_gpu :1003; stage3
_configure_tensor_swapping :987) and the NVMe optimizer-state swappers
(runtime/swap_tensor/). The device keeps ONLY the params and grads; the
Adam moments (8 bytes/param — the dominant ZeRO memory term) live
host-side and, for device="nvme", are swapped to disk between steps
through the native aio engine (csrc/aio.cpp).

Partitioning follows the GRAD layout (each process owns the shards it can
address of the reduce-scattered gradients — the reference's "rank owns its
partition" rule, stage_1_and_2.py:1628): host master shards are carved
from the params at the grad indices on the first step, updated by the AVX
CPU-Adam (csrc/cpu_adam.cpp), and scattered back into the device params.
"""

import os
from typing import Any, List, Optional

import jax
import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam


def _local_slices(arr):
    """[(index, np_shard)] for this process, deduplicated by index."""
    if not isinstance(arr, jax.Array):
        return [((slice(None),) * np.ndim(arr), np.asarray(arr))]
    out, seen = [], set()
    for s in arr.addressable_shards:
        key = tuple((sl.start, sl.stop) for sl in s.index)
        if key in seen:
            continue
        seen.add(key)
        out.append((s.index, np.asarray(s.data)))
    return out


class OffloadedOptimizer:
    """Host-resident Adam over the engine's param pytree."""

    def __init__(self, params: Any, lr: float, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True, nvme_path=None,
                 swap_folder: Optional[str] = None):
        self.treedef = jax.tree.structure(params)
        self._opt_kwargs = dict(lr=lr, betas=betas, eps=eps,
                                weight_decay=weight_decay,
                                adamw_mode=adam_w_mode)
        self.opt: Optional[DeepSpeedCPUAdam] = None
        self.masters: List[List] = []   # per leaf: [(index, master_buf)]

        self.swapper = None
        self._swap_ready = False
        if nvme_path is not None:
            from deepspeed_tpu.runtime.swap_tensor.swapper import \
                AsyncTensorSwapper
            folder = swap_folder or os.path.join(
                nvme_path, f"ds_offload_{os.getpid()}")
            self.swapper = AsyncTensorSwapper(folder)

    def _init_masters(self, grads: Any, params: Any):
        """Carve host fp32 masters at the grad-shard indices."""
        grad_leaves = self.treedef.flatten_up_to(grads)
        param_leaves = self.treedef.flatten_up_to(params)
        flat_buffers = []
        self.masters = []
        for g_leaf, p_leaf in zip(grad_leaves, param_leaves):
            p_full = np.asarray(jax.device_get(p_leaf), np.float32)
            shards = []
            for idx, _ in _local_slices(g_leaf):
                # np.array order="C", not ascontiguousarray: the masters
                # are updated in place, and ascontiguousarray of an
                # already-contiguous read-only device_get view would hand
                # back that read-only view uncopied
                shards.append((idx, np.array(p_full[idx], np.float32,
                                             order="C")))
            self.masters.append(shards)
            flat_buffers.extend(buf for _, buf in shards)
        self.opt = DeepSpeedCPUAdam(flat_buffers, **self._opt_kwargs)
        it = iter(self.opt.params)
        self.masters = [[(idx, next(it)) for idx, _ in leaf_shards]
                        for leaf_shards in self.masters]
        if self.swapper is not None:
            self._swap_out_states(block=True)
            self._swap_ready = True

    # ---------------------------------------------------------------- nvme
    def _state_key(self, kind, i):
        return f"{kind}_{i}"

    def _swap_out_states(self, block=False):
        for i, (m, v) in enumerate(zip(self.opt.exp_avg,
                                       self.opt.exp_avg_sq)):
            self.swapper.swap_out(self._state_key("m", i), m)
            self.swapper.swap_out(self._state_key("v", i), v)
        if block:
            self.swapper.synchronize()

    def _swap_in_states(self):
        self.swapper.synchronize()
        for i in range(len(self.opt.exp_avg)):
            self.opt.exp_avg[i] = self.swapper.swap_in(
                self._state_key("m", i))
            self.opt.exp_avg_sq[i] = self.swapper.swap_in(
                self._state_key("v", i))

    # ---------------------------------------------------------------- step
    _PREFETCH = 2  # moment buffers in flight (double buffering)

    def step(self, grads: Any, lr: float, params: Any, param_shardings):
        """Apply one host Adam step; returns the updated device params.

        Pipelined (reference pipelined_optimizer_swapper.py): device→host
        grad copies are issued async for every leaf up front; in NVMe mode
        each buffer's (m, v) read is prefetched while the previous buffer's
        Adam sweep runs and its write-back is submitted async behind it."""
        first_step = self.opt is None
        grad_leaves = self.treedef.flatten_up_to(grads)
        for g in grad_leaves:  # overlap D2H with everything below
            if hasattr(g, "copy_to_host_async"):
                g.copy_to_host_async()
        if first_step:
            self._init_masters(grads, params)
        self.maybe_apply_loaded_state()

        grads_np = []
        for g_leaf, leaf_masters in zip(grad_leaves, self.masters):
            shards = {tuple((sl.start, sl.stop) for sl in idx): d
                      for idx, d in _local_slices(g_leaf)}
            for idx, master in leaf_masters:
                key = tuple((sl.start, sl.stop) for sl in idx)
                grads_np.append(np.ascontiguousarray(shards[key],
                                                     np.float32))

        n = len(self.opt.params)
        self.opt.step_count += 1
        step_no = self.opt.step_count
        if self.swapper is not None and self._swap_ready and not first_step:
            # the previous step's async write-backs must land before we
            # re-read the same files (FIFO ordering only holds for
            # thread_count=1 aio handles)
            self.swapper.synchronize()
            # pipelined: fetch i+PREFETCH ‖ adam(i) ‖ write-back(i)
            fetches = {}

            def start(i):
                if i < n:
                    fetches[i] = (
                        self.swapper.swap_in_async(self._state_key("m", i)),
                        self.swapper.swap_in_async(self._state_key("v", i)))

            for i in range(min(self._PREFETCH, n)):
                start(i)
            for i in range(n):
                (m_buf, m_req), (v_buf, v_req) = fetches.pop(i)
                self.swapper.wait(m_req, m_buf.nbytes)
                self.swapper.wait(v_req, v_buf.nbytes)
                self.opt.exp_avg[i] = m_buf
                self.opt.exp_avg_sq[i] = v_buf
                start(i + self._PREFETCH)
                self.opt.step_single(i, grads_np[i], lr=lr, step_no=step_no)
                self.swapper.swap_out(self._state_key("m", i), m_buf)
                self.swapper.swap_out(self._state_key("v", i), v_buf)
            self.swapper.synchronize()
        else:
            for i in range(n):
                self.opt.step_single(i, grads_np[i], lr=lr, step_no=step_no)
            if self.swapper is not None:
                self._swap_out_states(block=False)
                self._swap_ready = True

        # scatter updated master shards back onto the device params
        new_leaves = []
        param_leaves = self.treedef.flatten_up_to(params)
        for leaf, leaf_masters in zip(param_leaves, self.masters):
            if len(leaf_masters) == 1 and \
                    leaf_masters[0][1].shape == leaf.shape:
                new_leaves.append(leaf_masters[0][1])
            else:
                full = np.array(jax.device_get(leaf))  # writable copy
                for idx, master in leaf_masters:
                    full[idx] = master
                new_leaves.append(full)
        new_params = self.treedef.unflatten(new_leaves)
        # async put: the compiled next step blocks only when it consumes
        return jax.device_put(new_params, param_shardings)

    def state_dict(self):
        if self.opt is None:
            # moments loaded but not yet attached (no step taken): pass
            # them through so save-after-load doesn't drop them
            return getattr(self, "_pending_sd", None)
        if self.swapper is not None:
            self._swap_in_states()
        sd = {"exp_avg": [np.array(m) for m in self.opt.exp_avg],
              "exp_avg_sq": [np.array(v) for v in self.opt.exp_avg_sq],
              "step": self.opt.step_count}
        if self.swapper is not None:
            self._swap_out_states(block=True)
        return sd

    def load_state_dict(self, sd):
        self._pending_sd = sd

    def maybe_apply_loaded_state(self):
        """Deferred restore: moments can only attach once masters exist
        (first step); called by the engine before each offloaded step."""
        sd = getattr(self, "_pending_sd", None)
        if sd is None or self.opt is None:
            return
        self.opt.exp_avg = [np.ascontiguousarray(m, np.float32)
                            for m in sd["exp_avg"]]
        self.opt.exp_avg_sq = [np.ascontiguousarray(v, np.float32)
                               for v in sd["exp_avg_sq"]]
        self.opt.step_count = sd["step"]
        self._pending_sd = None
        if self.swapper is not None:
            self._swap_out_states(block=True)
