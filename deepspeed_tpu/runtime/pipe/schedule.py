"""Pipeline instruction schedules.

Faithful port of deepspeed/runtime/pipe/schedule.py (``PipeSchedule`` :8,
``InferenceSchedule`` :117, ``TrainSchedule`` :182 — the even/odd 1F1B-ish
interleave over ``2*(micro_batches + stages - 1)`` steps). The instruction
stream is pure Python and keeps the reference semantics exactly; the SPMD
executor (pipe/spmd.py) compiles the equivalent dataflow into one jitted
scan, while this object drives the host-loop (multi-controller) executor
and the schedule unit tests (reference test_pipe_schedule.py).
"""


class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (self.__class__ == other.__class__ and
                self.kwargs == other.kwargs)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    """Instruction on a pipeline ring-buffer slot. ``buffer_id`` is the
    slot (micro_batch_id % num_pipe_buffers — reference schedule.py:105);
    ``micro_batch_id`` identifies the data (LoadMicroBatch needs it)."""

    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule:
    """Base iterator yielding lists of PipeInstruction per step
    (reference :8)."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    def steps(self):
        raise NotImplementedError

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _buffer_idx(self, micro_batch_id):
        """Ring-buffer slot for a micro-batch (reference schedule.py:105):
        executors allocate only num_pipe_buffers() buffers, so ids wrap."""
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def __iter__(self):
        self.it = iter(self.steps())
        return self.it


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference :117)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id

            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf,
                                               micro_batch_id=micro_batch_id))
                if self._valid_stage(self.prev_stage) and \
                        self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(buf,
                                               micro_batch_id=micro_batch_id))
                cmds.append(ForwardPass(buf, micro_batch_id=micro_batch_id))
                if self._valid_stage(self.next_stage):
                    cmds.append(SendActivation(buf,
                                               micro_batch_id=micro_batch_id))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """The reference's interleaved fwd/bwd train schedule (:182):
    ``2*(micro_batches + stages - 1)`` steps; even steps alternate
    forward/backward by stage parity; buffers bounded by warm-up depth."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)

            cmds = []
            # Exchange activations/grads with neighbours (reference
            # ordering, schedule.py:205-219: on a FORWARD step the
            # previous backward's input-grad is sent downstream; on a
            # BACKWARD step the previous forward's output goes up and the
            # current micro-batch's output-grad is received)
            if is_forward:
                if self._valid_micro_batch(micro_batch_id) and \
                        self._valid_stage(self.prev_stage):
                    cmds.append(RecvActivation(
                        self._buffer_idx(micro_batch_id),
                        micro_batch_id=micro_batch_id))
                if self._valid_micro_batch(prev_micro_batch_id) and \
                        self._valid_stage(self.prev_stage):
                    cmds.append(SendGrad(
                        self._buffer_idx(prev_micro_batch_id),
                        micro_batch_id=prev_micro_batch_id))
            else:
                if self._valid_micro_batch(prev_micro_batch_id) and \
                        self._valid_stage(self.next_stage):
                    cmds.append(SendActivation(
                        self._buffer_idx(prev_micro_batch_id),
                        micro_batch_id=prev_micro_batch_id))
                if self._valid_micro_batch(micro_batch_id) and \
                        self._valid_stage(self.next_stage):
                    cmds.append(RecvGrad(
                        self._buffer_idx(micro_batch_id),
                        micro_batch_id=micro_batch_id))

            # First/last stage loads (reference :222)
            if self.is_first_stage or self.is_last_stage:
                if is_forward and self._valid_micro_batch(micro_batch_id):
                    cmds.append(LoadMicroBatch(
                        self._buffer_idx(micro_batch_id),
                        micro_batch_id=micro_batch_id))

            # Computation
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if is_forward:
                    cmds.append(ForwardPass(buf,
                                            micro_batch_id=micro_batch_id))
                else:
                    cmds.append(BackwardPass(buf,
                                             micro_batch_id=micro_batch_id))

            # Model step at the end of the batch
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def _step_to_micro_batch(self, step_id):
        if _is_even(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._even_step_forward_id(step_id)
            is_forward = True
        elif _is_odd(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._odd_step_forward_id(step_id)
            is_forward = True
        elif _is_even(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._even_step_backward_id(step_id)
            is_forward = False
        elif _is_odd(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._odd_step_backward_id(step_id)
            is_forward = False
        else:
            raise AssertionError("unreachable")
        return micro_batch_id, is_forward

    def _even_step_forward_id(self, step_id):
        base = step_id // 2
        return base - self.stage_id // 2

    def _odd_step_forward_id(self, step_id):
        base = (step_id - 1) // 2
        return base - self.stage_id // 2

    def _even_step_backward_id(self, step_id):
        base = step_id // 2
        return base - self.stages + (self.stage_id + 1) // 2

    def _odd_step_backward_id(self, step_id):
        base = ((step_id - 1) // 2) - self.stages + 1
        return base + self.stage_id // 2

    def num_pipe_buffers(self):
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)


class DataParallelSchedule(PipeSchedule):
    """Pure DP schedule (reference tail of schedule.py)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(buffer_id=0), ForwardPass(buffer_id=0),
                    BackwardPass(buffer_id=0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0
