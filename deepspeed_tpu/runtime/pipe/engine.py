"""Host-loop pipeline engine executing the TrainSchedule instruction
stream — the 1F1B / non-uniform-stage / tied-weight path.

Rebuild of deepspeed/runtime/pipe/engine.py (``PipelineEngine`` :46,
``_exec_schedule`` :1319 dispatching ``_INSTRUCTION_MAP`` :1306, tied-grad
allreduce ``_exec_reduce_tied_grads`` :233) and pipe/p2p.py. Two pipeline
executors exist in this build, matching the two ways a pipeline maps to
TPU:

* **SPMD scan** (pipe/spmd.py) — uniform stages compiled into ONE program
  over the mesh pipe axis; jnp.roll lowers to ICI collective-permute.
  Fastest path; GPipe dataflow; the default for uniform block stacks.
* **This host loop** — the multi-controller-shaped path: each stage is a
  separately compiled program on its own device; the host interprets the
  TrainSchedule exactly (1F1B interleave, ring buffers of
  ``num_pipe_buffers()`` slots, warm-up/cool-down), activations/grads move
  stage-to-stage as device-to-device transfers (the p2p send/recv), and
  tied weights are reconciled with a grad allreduce across their stage
  copies. Supports NON-uniform stages (embeds/head inside first/last
  stages via PipelineModule's balanced partitioner) — the shapes the SPMD
  scan cannot express.

Backward uses layer-granular recompute: ForwardPass stores only the
stage's input; BackwardPass re-runs the stage under ``jax.vjp`` (the
activation-checkpointing default of the reference pipeline engine).
"""

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.pipe import schedule as sched_mod
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec)
from deepspeed_tpu.utils.logging import log_dist


class _Mailbox:
    """Single-controller p2p: (src, dst, kind, buffer_id) -> value.
    The host-loop analogue of pipe/p2p.py send/recv pairing."""

    def __init__(self):
        self._box: Dict[Tuple, Any] = {}

    def send(self, key, value):
        assert key not in self._box, f"unconsumed p2p slot {key}"
        self._box[key] = value

    def ready(self, key):
        return key in self._box

    def recv(self, key):
        return self._box.pop(key)


from deepspeed_tpu.runtime.engine import _cast_tree  # noqa: E402


class _StageRunner:
    """One pipeline stage: its specs, params, compiled fwd/bwd, buffers.

    ``compute_dtype``: fp32 master params are cast before the stage body
    runs (the main engine's mixed-precision convention, engine.py
    _compute_loss)."""

    def __init__(self, stage_id, num_stages, specs, loss_fn, device, rng,
                 compute_dtype=None):
        self.stage_id = stage_id
        self.is_first = stage_id == 0
        self.is_last = stage_id == num_stages - 1
        self.specs = specs
        self.loss_fn = loss_fn if self.is_last else None
        self.device = device
        # tied keys owned by this stage (spec order)
        self.tied_keys = [s.key for s in specs
                          if isinstance(s, TiedLayerSpec)]

        import flax.linen as nn
        stage_specs = specs
        is_last = self.is_last
        loss = self.loss_fn

        class _Stage(nn.Module):
            @nn.compact
            def __call__(self, x, labels=None):
                tied = {}
                for i, spec in enumerate(stage_specs):
                    if isinstance(spec, TiedLayerSpec):
                        if spec.key not in tied:
                            tied[spec.key] = spec.build(
                                name=f"tied_{spec.key}")
                        mod = tied[spec.key]
                        x = (spec.forward_fn(mod, x) if spec.forward_fn
                             else mod(x))
                    elif isinstance(spec, LayerSpec):
                        x = spec.build(name=f"layer_{i}")(x)
                    else:
                        x = spec(x)
                if is_last and loss is not None:
                    return loss(x, labels)
                return x

        self.module = _Stage()
        self.params = None  # set by engine (init or tied sync)
        self._rng = rng
        cdt = compute_dtype

        def apply(p, x, labels=None):
            if cdt is not None:
                p = _cast_tree(p, cdt)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
                    x = jnp.asarray(x).astype(cdt)
            if is_last and loss is not None:
                return jnp.asarray(
                    self.module.apply({"params": p}, x, labels), jnp.float32)
            return self.module.apply({"params": p}, x)

        self._apply = apply
        self.fwd = jax.jit(apply)

        if self.is_last:
            is_first = self.is_first

            def bwd(p, x, labels, ct):
                scaled = lambda g: g * ct.astype(g.dtype)  # noqa: E731
                if is_first:  # single stage: input is raw (int) data
                    g_p = jax.grad(lambda p: apply(p, x, labels))(p)
                    return jax.tree.map(scaled, g_p), None
                g_p, g_x = jax.grad(
                    lambda p, x: apply(p, x, labels), argnums=(0, 1))(p, x)
                return (jax.tree.map(scaled, g_p),
                        jax.tree.map(scaled, g_x))
        else:
            def bwd(p, x, ct):
                out, vjp = jax.vjp(lambda p, x: apply(p, x), p, x)
                # the upstream ct may arrive in a wider dtype (e.g. the
                # fp32 loss-scale seed times an fp16 activation grad)
                ct = jax.tree.map(lambda c, o: c.astype(o.dtype), ct, out)
                return vjp(ct)
        self.bwd = jax.jit(bwd)

    def init_params(self, sample_x, sample_labels=None):
        kwargs = {}
        args = (sample_x, sample_labels) if self.is_last and self.loss_fn \
            else (sample_x,)
        variables = self.module.init(self._rng, *args, **kwargs)
        self.params = jax.device_put(variables["params"], self.device)
        out = self._apply(variables["params"], *args)
        return out

    def tied_param_subtree(self, key):
        return self.params[f"tied_{key}"]


class PipelineEngine:
    """Interpret TrainSchedule over per-stage compiled programs.

    ``pipe_module``: a PipelineModule (LayerSpec list + partitioning).
    ``loss_fn(last_stage_out, labels) -> scalar`` runs inside the last
    stage. ``train_batch(batch=(x, labels))`` splits dim 0 into
    ``num_microbatches`` and returns the mean micro-batch loss.
    """

    def __init__(self, pipe_module: PipelineModule, sample_batch,
                 num_microbatches: int, lr=1e-3, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, devices: Optional[List] = None,
                 seed: int = 0, grad_scale_by_microbatches: bool = True,
                 dp: int = 1, optimizer_name: str = "adamw",
                 compute_dtype=None, dynamic_loss_scale: bool = False,
                 initial_scale: float = 1.0, scale_window: int = 1000,
                 min_scale: float = 1.0, hysteresis: int = 1,
                 lr_scheduler=None, gradient_clipping: float = 0.0,
                 curriculum_scheduler=None):
        self.pm = pipe_module
        # curriculum learning inside the pipe engine (reference
        # runtime/pipe/engine.py:307-308 injects curriculum_seqlen):
        # train_batch truncates the sequence dim to the scheduled
        # difficulty; each plateau compiles once
        self.curriculum_scheduler = curriculum_scheduler
        self.S = pipe_module.num_stages
        self.M = num_microbatches
        self.dp = dp
        assert self.S >= 1 and dp >= 1
        self.loss_fn = pipe_module.loss_fn
        assert self.loss_fn is not None, "PipelineModule needs loss_fn"
        devs = devices or jax.devices()
        # device grid [S][dp]: replica d of stage s runs its own pipeline
        # column (reference PipeModelDataParallelTopology: PP x DP axes)
        need = self.S * dp
        if len(devs) < need:
            devs = [devs[i % len(devs)] for i in range(need)]
        self.dev_grid = [[devs[s * dp + d] for d in range(dp)]
                         for s in range(self.S)]
        self.devices = [row[0] for row in self.dev_grid]
        self._scale_by_M = grad_scale_by_microbatches
        self.global_steps = 0
        self.skipped_steps = 0
        self.compute_dtype = compute_dtype
        self.gradient_clipping = float(gradient_clipping)

        # fp16 loss scaling, reusing the main engine's scale-state machine
        # (runtime/fp16/loss_scaler.py; reference PipelineEngine inherits
        # this from DeepSpeedEngine's FP16_Optimizer)
        from deepspeed_tpu.runtime.fp16.loss_scaler import (
            make_scale_state, update_scale)
        self._fp16 = compute_dtype == jnp.float16
        self._dynamic_scale = bool(dynamic_loss_scale and self._fp16)
        self._scale_state = make_scale_state(
            float(initial_scale) if self._fp16 else 1.0,
            delayed_shift=hysteresis)
        self._scale_cfg = dict(scale_window=scale_window,
                               min_scale=min_scale,
                               delayed_shift=hysteresis)
        self._update_scale = update_scale

        # LR schedule (reference PipelineEngine lr via DeepSpeedEngine
        # _configure_lr_scheduler, runtime/engine.py:790)
        self.lr_scheduler = lr_scheduler

        rng = jax.random.PRNGKey(seed)
        self.stages = [
            _StageRunner(s, self.S, pipe_module.stage_layers(s),
                         self.loss_fn, self.devices[s],
                         jax.random.fold_in(rng, s),
                         compute_dtype=compute_dtype)
            for s in range(self.S)
        ]
        # shape-propagating init on a sample micro-batch
        x, labels = self._split_sample(sample_batch)
        for st in self.stages:
            x = st.init_params(x, labels)

        # tied weights: stage copies must start identical (reference
        # broadcasts from the owner stage, pipe/module.py TiedLayerSpec)
        self._tied: Dict[str, List[int]] = {}
        for s, st in enumerate(self.stages):
            for key in st.tied_keys:
                self._tied.setdefault(key, []).append(s)
        for key, owners in self._tied.items():
            if len(owners) > 1:
                src = self.stages[owners[0]].tied_param_subtree(key)
                for s in owners[1:]:
                    p = dict(self.stages[s].params)
                    p[f"tied_{key}"] = jax.device_put(
                        src, self.stages[s].device)
                    self.stages[s].params = p

        # optimizer from the shared runtime/optim.py; 'Adam' keeps the
        # reference's L2-regularised semantics, 'AdamW' decoupled decay
        # (ADVICE r2: adam_w_mode must follow the configured type)
        from deepspeed_tpu.runtime import optim as optim_lib
        self.lr = lr
        name = optimizer_name.lower()
        if name in ("adam", "adamw"):
            self.opt = optim_lib.adam(b1=betas[0], b2=betas[1], eps=eps,
                                      weight_decay=weight_decay,
                                      adam_w_mode=(name == "adamw"))
        elif name == "sgd":
            self.opt = optim_lib.sgd(weight_decay=weight_decay)
        else:
            raise ValueError(
                f"PipelineEngine supports Adam/AdamW/SGD, got {name!r}")
        self.opt_states = [self.opt.init(st.params) for st in self.stages]

        def opt_step(grads, opt_state, params, lr_val):
            updates, new_state = self.opt.update(grads, opt_state, params,
                                                 lr_val)
            return jax.tree.map(jnp.add, params, updates), new_state
        self._opt_step = jax.jit(opt_step)

        def grad_stats(g):
            leaves = jax.tree.leaves(g)
            if not leaves:
                # a stage may own no once-counted grads (e.g. only a
                # non-first copy of a tied layer)
                return jnp.bool_(True), jnp.float32(0.0)
            finite = jnp.all(jnp.stack(
                [jnp.isfinite(leaf).all() for leaf in leaves]))
            sumsq = sum(jnp.sum(leaf.astype(jnp.float32) ** 2)
                        for leaf in leaves)
            return finite, sumsq
        self._grad_stats = jax.jit(grad_stats)
        log_dist(f"PipelineEngine(1F1B host loop): stages={self.S} dp={dp} "
                 f"microbatches={self.M} parts={pipe_module.parts} "
                 f"dtype={getattr(compute_dtype, '__name__', 'float32')} "
                 f"tied={list(self._tied)}", ranks=[0])

    def _split_sample(self, batch):
        x, labels = batch[0], batch[1]
        return x[: max(1, x.shape[0] // self.M)], \
            labels[: max(1, labels.shape[0] // self.M)]

    # ------------------------------------------------------------- execution
    def get_lr(self):
        applied = max(0, self.global_steps - self.skipped_steps)
        if self.lr_scheduler is not None:
            return [float(self.lr_scheduler.as_schedule_fn()(applied))]
        return [self.lr]

    @property
    def loss_scale(self):
        return float(jax.device_get(self._scale_state.loss_scale))

    def train_batch(self, batch):
        """One global step: M micro-batches per dp column through the
        TrainSchedule, dp-averaged ReduceGrads, tied-grad allreduce,
        fp16 unscale/overflow-skip, clip, optimizer + LR-schedule step.

        GAS in the reference pipeline IS the micro-batch count
        (train_batch_size = micro_batch * gas * dp, pipe engine.py:46),
        so there is no separate accumulation loop here."""
        if self.curriculum_scheduler is not None:
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler \
                import apply_seqlen_truncation
            batch = apply_seqlen_truncation(self.curriculum_scheduler,
                                            self.global_steps, batch)
        x, labels = batch[0], batch[1]
        B = x.shape[0]
        D, M, S = self.dp, self.M, self.S
        assert B % (M * D) == 0, \
            f"batch {B} % (microbatches {M} * dp {D}) != 0"
        mb = B // (M * D)

        def rows(d, i):
            r = (d * M + i) * mb
            return slice(r, r + mb)

        micro_x = {(d, i): jax.device_put(x[rows(d, i)],
                                          self.dev_grid[0][d])
                   for d in range(D) for i in range(M)}
        micro_y = {(d, i): jax.device_put(labels[rows(d, i)],
                                          self.dev_grid[-1][d])
                   for d in range(D) for i in range(M)}

        scale = (float(jax.device_get(self._scale_state.loss_scale))
                 if self._fp16 else 1.0)
        ct_seed = jnp.asarray(
            (1.0 / M if self._scale_by_M else 1.0) * scale, jnp.float32)

        schedules = [sched_mod.TrainSchedule(M, S, s) for s in range(S)]
        stage_streams = [list(sch.steps()) for sch in schedules]
        nbuf = [sch.num_pipe_buffers() for sch in schedules]
        # per-(stage, replica) ring buffers (reference pipe_buffers)
        in_buf = {(s, d): [None] * nbuf[s] for s in range(S)
                  for d in range(D)}
        lbl_buf = {(s, d): [None] * nbuf[s] for s in range(S)
                   for d in range(D)}
        grad_in = {(s, d): [None] * nbuf[s] for s in range(S)
                   for d in range(D)}
        grad_out = {(s, d): [None] * nbuf[s] for s in range(S)
                    for d in range(D)}
        out_buf = {(s, d): [None] * nbuf[s] for s in range(S)
                   for d in range(D)}
        # replicated params per column (DP broadcast of the stage master)
        rep_params = [[st.params if d == 0 else
                       jax.device_put(st.params, self.dev_grid[s][d])
                       for d in range(D)]
                      for s, st in enumerate(self.stages)]
        grad_accum = [[None] * D for _ in range(S)]
        grad_total: List[Any] = [None] * S
        reduced = [0] * S
        losses = []
        box = _Mailbox()

        def execute(s, d, cmd):
            st = self.stages[s]
            name = type(cmd).__name__
            if name == "LoadMicroBatch":
                if st.is_first:
                    in_buf[s, d][cmd.buffer_id] = micro_x[d, cmd.micro_batch_id]
                if st.is_last:
                    lbl_buf[s, d][cmd.buffer_id] = micro_y[d, cmd.micro_batch_id]
            elif name == "ForwardPass":
                xin = in_buf[s, d][cmd.buffer_id]
                if st.is_last:
                    out = st.fwd(rep_params[s][d], xin,
                                 lbl_buf[s, d][cmd.buffer_id])
                    losses.append(out)
                else:
                    out = st.fwd(rep_params[s][d], xin)
                out_buf[s, d][cmd.buffer_id] = out
            elif name == "BackwardPass":
                xin = in_buf[s, d][cmd.buffer_id]
                if st.is_last:
                    g_p, g_x = st.bwd(rep_params[s][d], xin,
                                      lbl_buf[s, d][cmd.buffer_id], ct_seed)
                else:
                    g_p, g_x = st.bwd(rep_params[s][d], xin,
                                      grad_in[s, d][cmd.buffer_id])
                    grad_in[s, d][cmd.buffer_id] = None
                grad_out[s, d][cmd.buffer_id] = g_x
                grad_accum[s][d] = g_p if grad_accum[s][d] is None else \
                    jax.tree.map(jnp.add, grad_accum[s][d], g_p)
            elif name == "SendActivation":
                box.send(("act", s + 1, d, cmd.micro_batch_id),
                         jax.device_put(out_buf[s, d][cmd.buffer_id],
                                        self.dev_grid[s + 1][d]))
                out_buf[s, d][cmd.buffer_id] = None
            elif name == "RecvActivation":
                in_buf[s, d][cmd.buffer_id] = box.recv(
                    ("act", s, d, cmd.micro_batch_id))
            elif name == "SendGrad":
                box.send(("grad", s - 1, d, cmd.micro_batch_id),
                         jax.device_put(grad_out[s, d][cmd.buffer_id],
                                        self.dev_grid[s - 1][d]))
                grad_out[s, d][cmd.buffer_id] = None
            elif name == "RecvGrad":
                grad_in[s, d][cmd.buffer_id] = box.recv(
                    ("grad", s, d, cmd.micro_batch_id))
            elif name == "ReduceTiedGrads":
                pass  # cross-STAGE reduce, handled after the loop
            elif name == "ReduceGrads":
                # the dp allreduce (reference _exec_reduce_grads :246):
                # when the LAST replica of this stage arrives, average the
                # column grads onto the stage master device
                reduced[s] += 1
                if reduced[s] == D:
                    dev0 = self.dev_grid[s][0]
                    tot = jax.tree.map(
                        lambda g: jax.device_put(g, dev0), grad_accum[s][0])
                    for d2 in range(1, D):
                        other = jax.tree.map(
                            lambda g: jax.device_put(g, dev0),
                            grad_accum[s][d2])
                        tot = jax.tree.map(jnp.add, tot, other)
                    grad_total[s] = (jax.tree.map(lambda g: g / D, tot)
                                     if D > 1 else tot)
            elif name == "OptimizerStep":
                pass  # applied once after the loop
            else:  # pragma: no cover
                raise ValueError(f"unknown instruction {name}")

        streams = {(s, d): stage_streams[s] for s in range(S)
                   for d in range(D)}
        self._run_schedule(streams, execute, box)

        # tied-weight grad allreduce (reference _exec_reduce_tied_grads
        # :233): sum the copies' grads so every stage applies the same
        # update and the weights stay bit-identical
        for key, owners in self._tied.items():
            if len(owners) < 2:
                continue
            subs = [jax.tree.map(lambda g: jax.device_put(g, jax.devices()[0]),
                                 grad_total[s][f"tied_{key}"])
                    for s in owners]
            total = subs[0]
            for other in subs[1:]:
                total = jax.tree.map(jnp.add, total, other)
            for s in owners:
                g = dict(grad_total[s])
                g[f"tied_{key}"] = jax.device_put(total,
                                                  self.stages[s].device)
                grad_total[s] = g

        if self._fp16 and scale != 1.0:
            inv = 1.0 / scale
            grad_total = [jax.tree.map(lambda g: g * inv, gt)
                          for gt in grad_total]

        # one compiled reduction per stage (finite-check + clip sumsq), not
        # a host transfer per leaf; tied copies past the first owner are
        # excluded so their (identical, already-summed) grads enter the
        # global norm exactly once, matching the non-pipelined engine
        need_stats = self._fp16 or self.gradient_clipping > 0
        overflow = False
        sumsq = 0.0
        if need_stats:
            dup_tied = {(s, f"tied_{key}")
                        for key, owners in self._tied.items()
                        for s in owners[1:]}
            stats = []
            for s, gt in enumerate(grad_total):
                once = {k: v for k, v in gt.items()
                        if (s, k) not in dup_tied}
                stats.append(self._grad_stats(once))
            finites, sqs = zip(*[jax.device_get(st) for st in stats])
            overflow = self._fp16 and not all(bool(f) for f in finites)
            sumsq = float(sum(sqs))

        if self._fp16:
            self._scale_state = self._update_scale(
                self._scale_state, jnp.asarray(overflow),
                dynamic=self._dynamic_scale, **self._scale_cfg)
        if overflow:
            self.skipped_steps += 1
            log_dist(f"[pipe] OVERFLOW! skipping step; new loss scale: "
                     f"{self.loss_scale}", ranks=[0])
        else:
            if self.gradient_clipping > 0:
                norm = sumsq ** 0.5
                if norm > self.gradient_clipping:
                    factor = self.gradient_clipping / (norm + 1e-6)
                    grad_total = [jax.tree.map(lambda g: g * factor, gt)
                                  for gt in grad_total]
            lr_val = jnp.float32(self.get_lr()[0])
            for s, st in enumerate(self.stages):
                st.params, self.opt_states[s] = self._opt_step(
                    grad_total[s], self.opt_states[s], st.params, lr_val)
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
        self.global_steps += 1
        # column losses live on their replica's device: co-locate to mean
        return jnp.mean(jnp.stack(
            [jax.device_put(l, self.devices[-1]) for l in losses]))

    def _run_schedule(self, streams, execute, box):
        """Cooperative interpretation of per-(stage, replica) instruction
        streams: a stage blocks only on an un-arrived recv; everything
        else retires in order (the p2p pairing of pipe/p2p.py)."""
        keys = sorted(streams)
        nsteps = len(next(iter(streams.values())))
        for t in range(nsteps):
            pending = {k: list(streams[k][t]) for k in keys}
            while any(pending.values()):
                progressed = False
                for k in keys:
                    s, d = k if isinstance(k, tuple) else (k, 0)
                    while pending[k]:
                        cmd = pending[k][0]
                        nm = type(cmd).__name__
                        if nm == "RecvActivation" and not box.ready(
                                ("act", s, d, cmd.micro_batch_id)):
                            break
                        if nm == "RecvGrad" and not box.ready(
                                ("grad", s, d, cmd.micro_batch_id)):
                            break
                        execute(s, d, pending[k].pop(0))
                        progressed = True
                if not progressed:
                    raise RuntimeError(
                        f"pipeline deadlock at step {t}: "
                        f"{ {k: p for k, p in pending.items() if p} }")

    def eval_batch(self, batch):
        """Forward-only pipeline pass executing InferenceSchedule
        (reference PipelineEngine.eval_batch → fill-drain, schedule.py
        :117): micro-batches stream through the stages, the last stage's
        losses average — no grads, no optimizer step."""
        x, labels = batch[0], batch[1]
        B = x.shape[0]
        assert B % self.M == 0
        mb = B // self.M
        micro_x = [jax.device_put(x[i * mb:(i + 1) * mb], self.devices[0])
                   for i in range(self.M)]
        micro_y = [jax.device_put(labels[i * mb:(i + 1) * mb],
                                  self.devices[-1])
                   for i in range(self.M)]
        schedules = [sched_mod.InferenceSchedule(self.M, self.S, s)
                     for s in range(self.S)]
        streams = [list(sch.steps()) for sch in schedules]
        nbuf = [sch.num_pipe_buffers() for sch in schedules]
        in_buf = [[None] * nbuf[s] for s in range(self.S)]
        lbl_buf = [[None] * nbuf[s] for s in range(self.S)]
        out_buf = [[None] * nbuf[s] for s in range(self.S)]
        losses = []
        box = _Mailbox()

        def execute(s, d, cmd):
            st = self.stages[s]
            name = type(cmd).__name__
            if name == "LoadMicroBatch":
                if st.is_first:
                    in_buf[s][cmd.buffer_id] = micro_x[cmd.micro_batch_id]
                if st.is_last:
                    lbl_buf[s][cmd.buffer_id] = micro_y[cmd.micro_batch_id]
            elif name == "ForwardPass":
                xin = in_buf[s][cmd.buffer_id]
                if st.is_last:
                    losses.append(st.fwd(st.params, xin,
                                         lbl_buf[s][cmd.buffer_id]))
                else:
                    out_buf[s][cmd.buffer_id] = st.fwd(st.params, xin)
            elif name == "SendActivation":
                box.send(("act", s + 1, 0, cmd.micro_batch_id),
                         jax.device_put(out_buf[s][cmd.buffer_id],
                                        self.devices[s + 1]))
                out_buf[s][cmd.buffer_id] = None
            elif name == "RecvActivation":
                in_buf[s][cmd.buffer_id] = box.recv(
                    ("act", s, 0, cmd.micro_batch_id))
            else:  # pragma: no cover
                raise ValueError(f"unexpected inference instruction {name}")

        self._run_schedule({(s, 0): streams[s] for s in range(self.S)},
                           execute, box)
        return jnp.mean(jnp.stack(losses))

    # ---------------------------------------------------------- checkpoints
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        """Per-LAYER checkpoint files (reference pipe/module.py:537
        ckpt_layer_path + save_state_dict): layer params are keyed by
        GLOBAL layer index, so a checkpoint written with one stage
        partitioning loads into any other. Tied layers save once under
        their key; stage optimizer states save per stage."""
        import os
        import pickle
        if tag is None:
            tag = f"global_step{self.global_steps}"
        ckpt_dir = os.path.join(save_dir, str(tag))
        os.makedirs(ckpt_dir, exist_ok=True)

        tied_written = set()
        for s, st in enumerate(self.stages):
            for li, spec in enumerate(st.specs):
                if isinstance(spec, TiedLayerSpec):
                    if spec.key in tied_written:
                        continue
                    tied_written.add(spec.key)
                    path = os.path.join(ckpt_dir,
                                        f"tied_{spec.key}-model_states.pt")
                    sub = st.params[f"tied_{spec.key}"]
                else:
                    gi = self.pm.parts[s] + li
                    path = self.pm.ckpt_layer_path(ckpt_dir, gi)
                    sub = st.params.get(f"layer_{li}")
                    if sub is None:   # plain callables carry no params
                        continue
                with open(path, "wb") as f:
                    pickle.dump(jax.tree.map(np.asarray,
                                             jax.device_get(sub)), f)
            opt_path = os.path.join(
                ckpt_dir, f"zero_pp_rank_{s}_mp_rank_00_optim_states.pt")
            with open(opt_path, "wb") as f:
                pickle.dump({
                    "optimizer_state_dict": jax.tree.map(
                        np.asarray, jax.device_get(self.opt_states[s])),
                    "parts": list(self.pm.parts),
                }, f)

        meta = {
            "global_steps": self.global_steps,
            "skipped_steps": self.skipped_steps,
            "loss_scale": self.loss_scale,
            "scale_state": {k: np.asarray(jax.device_get(v)) for k, v in
                            self._scale_state._asdict().items()},
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler else None),
            "parts": list(self.pm.parts),
            "num_stages": self.S,
            "dp": self.dp,
            "client_state": client_state or {},
        }
        with open(os.path.join(ckpt_dir, "mp_rank_00_model_states.pt"),
                  "wb") as f:
            pickle.dump(meta, f)
        if save_latest:
            with open(os.path.join(save_dir, "latest"), "w") as f:
                f.write(str(tag))
        log_dist(f"[pipe] saved checkpoint {ckpt_dir}", ranks=[0])
        return True

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        load_lr_scheduler_states=True):
        """Rebuild stage params from the per-layer files; optimizer state
        restores when the stage partitioning matches (otherwise fresh,
        with a warning — the reference has the same constraint)."""
        import os
        import pickle
        from deepspeed_tpu.utils.logging import logger
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.isfile(latest):
                logger.warning(f"no 'latest' file at {latest}; nothing loaded")
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        ckpt_dir = os.path.join(load_dir, str(tag))
        with open(os.path.join(ckpt_dir, "mp_rank_00_model_states.pt"),
                  "rb") as f:
            meta = pickle.load(f)

        for s, st in enumerate(self.stages):
            new_params = dict(st.params)
            for li, spec in enumerate(st.specs):
                if isinstance(spec, TiedLayerSpec):
                    path = os.path.join(ckpt_dir,
                                        f"tied_{spec.key}-model_states.pt")
                    key = f"tied_{spec.key}"
                else:
                    gi = self.pm.parts[s] + li
                    path = self.pm.ckpt_layer_path(ckpt_dir, gi)
                    key = f"layer_{li}"
                    if key not in new_params:
                        continue
                with open(path, "rb") as f:
                    sub = pickle.load(f)
                new_params[key] = jax.device_put(
                    jax.tree.map(jnp.asarray, sub), st.device)
            st.params = new_params

        self.global_steps = meta.get("global_steps", 0)
        self.skipped_steps = meta.get("skipped_steps", 0)
        ss = meta.get("scale_state")
        if ss is not None:
            from deepspeed_tpu.runtime.fp16.loss_scaler import LossScaleState
            self._scale_state = LossScaleState(
                loss_scale=jnp.float32(ss["loss_scale"]),
                good_steps=jnp.int32(ss["good_steps"]),
                hysteresis=jnp.int32(ss["hysteresis"]))
        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                meta.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])

        if load_optimizer_states:
            if meta.get("parts") != list(self.pm.parts):
                logger.warning(
                    f"[pipe] checkpoint partitioning {meta.get('parts')} != "
                    f"current {list(self.pm.parts)}; optimizer state NOT "
                    f"restored (params repartitioned from layer files)")
            else:
                for s in range(self.S):
                    opt_path = os.path.join(
                        ckpt_dir,
                        f"zero_pp_rank_{s}_mp_rank_00_optim_states.pt")
                    with open(opt_path, "rb") as f:
                        sd = pickle.load(f)
                    self.opt_states[s] = jax.device_put(
                        jax.tree.map(jnp.asarray, sd["optimizer_state_dict"]),
                        self.stages[s].device)
        log_dist(f"[pipe] loaded checkpoint {ckpt_dir}", ranks=[0])
        return ckpt_dir, meta.get("client_state", {})

    # ----------------------------------------------------------- inspection
    def stage_params(self):
        return [st.params for st in self.stages]
