"""Host-loop pipeline engine executing the TrainSchedule instruction
stream — the 1F1B / non-uniform-stage / tied-weight path.

Rebuild of deepspeed/runtime/pipe/engine.py (``PipelineEngine`` :46,
``_exec_schedule`` :1319 dispatching ``_INSTRUCTION_MAP`` :1306, tied-grad
allreduce ``_exec_reduce_tied_grads`` :233) and pipe/p2p.py. Two pipeline
executors exist in this build, matching the two ways a pipeline maps to
TPU:

* **SPMD scan** (pipe/spmd.py) — uniform stages compiled into ONE program
  over the mesh pipe axis; jnp.roll lowers to ICI collective-permute.
  Fastest path; GPipe dataflow; the default for uniform block stacks.
* **This host loop** — the multi-controller-shaped path: each stage is a
  separately compiled program on its own device; the host interprets the
  TrainSchedule exactly (1F1B interleave, ring buffers of
  ``num_pipe_buffers()`` slots, warm-up/cool-down), activations/grads move
  stage-to-stage as device-to-device transfers (the p2p send/recv), and
  tied weights are reconciled with a grad allreduce across their stage
  copies. Supports NON-uniform stages (embeds/head inside first/last
  stages via PipelineModule's balanced partitioner) — the shapes the SPMD
  scan cannot express.

Backward uses layer-granular recompute: ForwardPass stores only the
stage's input; BackwardPass re-runs the stage under ``jax.vjp`` (the
activation-checkpointing default of the reference pipeline engine).
"""

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.pipe import schedule as sched_mod
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec)
from deepspeed_tpu.utils.logging import log_dist


class _Mailbox:
    """Single-controller p2p: (src, dst, kind, buffer_id) -> value.
    The host-loop analogue of pipe/p2p.py send/recv pairing."""

    def __init__(self):
        self._box: Dict[Tuple, Any] = {}

    def send(self, key, value):
        assert key not in self._box, f"unconsumed p2p slot {key}"
        self._box[key] = value

    def ready(self, key):
        return key in self._box

    def recv(self, key):
        return self._box.pop(key)


class _StageRunner:
    """One pipeline stage: its specs, params, compiled fwd/bwd, buffers."""

    def __init__(self, stage_id, num_stages, specs, loss_fn, device, rng):
        self.stage_id = stage_id
        self.is_first = stage_id == 0
        self.is_last = stage_id == num_stages - 1
        self.specs = specs
        self.loss_fn = loss_fn if self.is_last else None
        self.device = device
        # tied keys owned by this stage (spec order)
        self.tied_keys = [s.key for s in specs
                          if isinstance(s, TiedLayerSpec)]

        import flax.linen as nn
        stage_specs = specs
        is_last = self.is_last
        loss = self.loss_fn

        class _Stage(nn.Module):
            @nn.compact
            def __call__(self, x, labels=None):
                tied = {}
                for i, spec in enumerate(stage_specs):
                    if isinstance(spec, TiedLayerSpec):
                        if spec.key not in tied:
                            tied[spec.key] = spec.build(
                                name=f"tied_{spec.key}")
                        mod = tied[spec.key]
                        x = (spec.forward_fn(mod, x) if spec.forward_fn
                             else mod(x))
                    elif isinstance(spec, LayerSpec):
                        x = spec.build(name=f"layer_{i}")(x)
                    else:
                        x = spec(x)
                if is_last and loss is not None:
                    return loss(x, labels)
                return x

        self.module = _Stage()
        self.params = None  # set by engine (init or tied sync)
        self._rng = rng

        def apply(p, x, labels=None):
            if is_last and loss is not None:
                return self.module.apply({"params": p}, x, labels)
            return self.module.apply({"params": p}, x)

        self._apply = apply
        self.fwd = jax.jit(apply)

        if self.is_last:
            is_first = self.is_first

            def bwd(p, x, labels, ct):
                if is_first:  # single stage: input is raw (int) data
                    g_p = jax.grad(lambda p: apply(p, x, labels))(p)
                    return jax.tree.map(lambda g: g * ct, g_p), None
                g_p, g_x = jax.grad(
                    lambda p, x: apply(p, x, labels), argnums=(0, 1))(p, x)
                return (jax.tree.map(lambda g: g * ct, g_p),
                        jax.tree.map(lambda g: g * ct, g_x))
        else:
            def bwd(p, x, ct):
                _, vjp = jax.vjp(lambda p, x: apply(p, x), p, x)
                return vjp(ct)
        self.bwd = jax.jit(bwd)

    def init_params(self, sample_x, sample_labels=None):
        kwargs = {}
        args = (sample_x, sample_labels) if self.is_last and self.loss_fn \
            else (sample_x,)
        variables = self.module.init(self._rng, *args, **kwargs)
        self.params = jax.device_put(variables["params"], self.device)
        out = self._apply(variables["params"], *args)
        return out

    def tied_param_subtree(self, key):
        return self.params[f"tied_{key}"]


class PipelineEngine:
    """Interpret TrainSchedule over per-stage compiled programs.

    ``pipe_module``: a PipelineModule (LayerSpec list + partitioning).
    ``loss_fn(last_stage_out, labels) -> scalar`` runs inside the last
    stage. ``train_batch(batch=(x, labels))`` splits dim 0 into
    ``num_microbatches`` and returns the mean micro-batch loss.
    """

    def __init__(self, pipe_module: PipelineModule, sample_batch,
                 num_microbatches: int, lr=1e-3, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, devices: Optional[List] = None,
                 seed: int = 0, grad_scale_by_microbatches: bool = True):
        self.pm = pipe_module
        self.S = pipe_module.num_stages
        self.M = num_microbatches
        assert self.S >= 1
        self.loss_fn = pipe_module.loss_fn
        assert self.loss_fn is not None, "PipelineModule needs loss_fn"
        devs = devices or jax.devices()
        if len(devs) < self.S:
            devs = [devs[i % len(devs)] for i in range(self.S)]
        self.devices = devs[:self.S]
        self._scale_by_M = grad_scale_by_microbatches
        self.global_steps = 0

        rng = jax.random.PRNGKey(seed)
        self.stages = [
            _StageRunner(s, self.S, pipe_module.stage_layers(s),
                         self.loss_fn, self.devices[s],
                         jax.random.fold_in(rng, s))
            for s in range(self.S)
        ]
        # shape-propagating init on a sample micro-batch
        x, labels = self._split_sample(sample_batch)
        for st in self.stages:
            x = st.init_params(x, labels)

        # tied weights: stage copies must start identical (reference
        # broadcasts from the owner stage, pipe/module.py TiedLayerSpec)
        self._tied: Dict[str, List[int]] = {}
        for s, st in enumerate(self.stages):
            for key in st.tied_keys:
                self._tied.setdefault(key, []).append(s)
        for key, owners in self._tied.items():
            if len(owners) > 1:
                src = self.stages[owners[0]].tied_param_subtree(key)
                for s in owners[1:]:
                    p = dict(self.stages[s].params)
                    p[f"tied_{key}"] = jax.device_put(
                        src, self.stages[s].device)
                    self.stages[s].params = p

        # the repo's own Adam (runtime/optim.py) so weight_decay keeps the
        # decoupled-AdamW semantics every other engine uses
        from deepspeed_tpu.runtime import optim as optim_lib
        self.lr = lr
        self.opt = optim_lib.adam(b1=betas[0], b2=betas[1], eps=eps,
                                  weight_decay=weight_decay,
                                  adam_w_mode=True)
        self.opt_states = [self.opt.init(st.params) for st in self.stages]

        def opt_step(grads, opt_state, params, lr_val):
            updates, new_state = self.opt.update(grads, opt_state, params,
                                                 lr_val)
            return jax.tree.map(jnp.add, params, updates), new_state
        self._opt_step = jax.jit(opt_step)
        log_dist(f"PipelineEngine(1F1B host loop): stages={self.S} "
                 f"microbatches={self.M} parts={pipe_module.parts} "
                 f"tied={list(self._tied)}", ranks=[0])

    def _split_sample(self, batch):
        x, labels = batch[0], batch[1]
        return x[: max(1, x.shape[0] // self.M)], \
            labels[: max(1, labels.shape[0] // self.M)]

    # ------------------------------------------------------------- execution
    def train_batch(self, batch):
        x, labels = batch[0], batch[1]
        B = x.shape[0]
        assert B % self.M == 0, f"batch {B} % microbatches {self.M} != 0"
        mb = B // self.M
        micro_x = [jax.device_put(x[i * mb:(i + 1) * mb], self.devices[0])
                   for i in range(self.M)]
        micro_y = [jax.device_put(labels[i * mb:(i + 1) * mb],
                                  self.devices[-1])
                   for i in range(self.M)]

        schedules = [sched_mod.TrainSchedule(self.M, self.S, s)
                     for s in range(self.S)]
        streams = [list(sch.steps()) for sch in schedules]
        nbuf = [sch.num_pipe_buffers() for sch in schedules]
        # per-stage ring buffers (reference engine.py pipe_buffers)
        in_buf = [[None] * nbuf[s] for s in range(self.S)]
        lbl_buf = [[None] * nbuf[s] for s in range(self.S)]
        grad_in = [[None] * nbuf[s] for s in range(self.S)]  # recv'd ct
        grad_out = [[None] * nbuf[s] for s in range(self.S)]  # computed g_x
        out_buf = [[None] * nbuf[s] for s in range(self.S)]
        grad_accum = [None] * self.S
        losses = []
        box = _Mailbox()
        total_steps = len(streams[0])
        ct_seed = jnp.asarray(1.0 / self.M if self._scale_by_M else 1.0,
                              jnp.float32)

        def execute(s, cmd):
            st = self.stages[s]
            name = type(cmd).__name__
            if name == "LoadMicroBatch":
                if st.is_first:
                    in_buf[s][cmd.buffer_id] = micro_x[cmd.micro_batch_id]
                if st.is_last:
                    lbl_buf[s][cmd.buffer_id] = micro_y[cmd.micro_batch_id]
            elif name == "ForwardPass":
                xin = in_buf[s][cmd.buffer_id]
                if st.is_last:
                    out = st.fwd(st.params, xin, lbl_buf[s][cmd.buffer_id])
                    losses.append(out)
                else:
                    out = st.fwd(st.params, xin)
                out_buf[s][cmd.buffer_id] = out
            elif name == "BackwardPass":
                xin = in_buf[s][cmd.buffer_id]
                if st.is_last:
                    g_p, g_x = st.bwd(st.params, xin,
                                      lbl_buf[s][cmd.buffer_id], ct_seed)
                else:
                    g_p, g_x = st.bwd(st.params, xin,
                                      grad_in[s][cmd.buffer_id])
                    grad_in[s][cmd.buffer_id] = None
                grad_out[s][cmd.buffer_id] = g_x
                grad_accum[s] = g_p if grad_accum[s] is None else \
                    jax.tree.map(jnp.add, grad_accum[s], g_p)
            elif name == "SendActivation":
                box.send(("act", s + 1, cmd.micro_batch_id),
                         jax.device_put(out_buf[s][cmd.buffer_id],
                                        self.devices[s + 1]))
                out_buf[s][cmd.buffer_id] = None
            elif name == "RecvActivation":
                in_buf[s][cmd.buffer_id] = box.recv(
                    ("act", s, cmd.micro_batch_id))
            elif name == "SendGrad":
                box.send(("grad", s - 1, cmd.micro_batch_id),
                         jax.device_put(grad_out[s][cmd.buffer_id],
                                        self.devices[s - 1]))
                grad_out[s][cmd.buffer_id] = None
            elif name == "RecvGrad":
                grad_in[s][cmd.buffer_id] = box.recv(
                    ("grad", s, cmd.micro_batch_id))
            elif name == "ReduceTiedGrads":
                pass  # handled globally below (single controller)
            elif name == "ReduceGrads":
                pass  # dp allreduce: dp=1 in the host-loop engine
            elif name == "OptimizerStep":
                pass  # applied once after the loop
            else:  # pragma: no cover
                raise ValueError(f"unknown instruction {name}")

        self._run_schedule(streams, execute, box)

        # tied-weight grad allreduce (reference _exec_reduce_tied_grads
        # :233): sum the copies' grads so every stage applies the same
        # update and the weights stay bit-identical
        for key, owners in self._tied.items():
            if len(owners) < 2:
                continue
            subs = [jax.tree.map(lambda g: jax.device_put(g, jax.devices()[0]),
                                 grad_accum[s][f"tied_{key}"])
                    for s in owners]
            total = subs[0]
            for other in subs[1:]:
                total = jax.tree.map(jnp.add, total, other)
            for s in owners:
                g = dict(grad_accum[s])
                g[f"tied_{key}"] = jax.device_put(total,
                                                  self.stages[s].device)
                grad_accum[s] = g

        # optimizer step per stage
        for s, st in enumerate(self.stages):
            st.params, self.opt_states[s] = self._opt_step(
                grad_accum[s], self.opt_states[s], st.params,
                jnp.float32(self.lr))
        self.global_steps += 1
        return jnp.mean(jnp.stack(losses))

    def _run_schedule(self, streams, execute, box):
        """Cooperative interpretation of per-stage instruction streams: a
        stage blocks only on an un-arrived recv; everything else retires
        in order (the p2p pairing of pipe/p2p.py)."""
        for t in range(len(streams[0])):
            pending = {s: list(streams[s][t]) for s in range(self.S)}
            while any(pending.values()):
                progressed = False
                for s in range(self.S):
                    while pending[s]:
                        cmd = pending[s][0]
                        nm = type(cmd).__name__
                        if nm == "RecvActivation" and not box.ready(
                                ("act", s, cmd.micro_batch_id)):
                            break
                        if nm == "RecvGrad" and not box.ready(
                                ("grad", s, cmd.micro_batch_id)):
                            break
                        execute(s, pending[s].pop(0))
                        progressed = True
                if not progressed:
                    raise RuntimeError(
                        f"pipeline deadlock at step {t}: "
                        f"{ {s: p for s, p in pending.items() if p} }")

    def eval_batch(self, batch):
        """Forward-only pipeline pass executing InferenceSchedule
        (reference PipelineEngine.eval_batch → fill-drain, schedule.py
        :117): micro-batches stream through the stages, the last stage's
        losses average — no grads, no optimizer step."""
        x, labels = batch[0], batch[1]
        B = x.shape[0]
        assert B % self.M == 0
        mb = B // self.M
        micro_x = [jax.device_put(x[i * mb:(i + 1) * mb], self.devices[0])
                   for i in range(self.M)]
        micro_y = [jax.device_put(labels[i * mb:(i + 1) * mb],
                                  self.devices[-1])
                   for i in range(self.M)]
        schedules = [sched_mod.InferenceSchedule(self.M, self.S, s)
                     for s in range(self.S)]
        streams = [list(sch.steps()) for sch in schedules]
        nbuf = [sch.num_pipe_buffers() for sch in schedules]
        in_buf = [[None] * nbuf[s] for s in range(self.S)]
        lbl_buf = [[None] * nbuf[s] for s in range(self.S)]
        out_buf = [[None] * nbuf[s] for s in range(self.S)]
        losses = []
        box = _Mailbox()

        def execute(s, cmd):
            st = self.stages[s]
            name = type(cmd).__name__
            if name == "LoadMicroBatch":
                if st.is_first:
                    in_buf[s][cmd.buffer_id] = micro_x[cmd.micro_batch_id]
                if st.is_last:
                    lbl_buf[s][cmd.buffer_id] = micro_y[cmd.micro_batch_id]
            elif name == "ForwardPass":
                xin = in_buf[s][cmd.buffer_id]
                if st.is_last:
                    losses.append(st.fwd(st.params, xin,
                                         lbl_buf[s][cmd.buffer_id]))
                else:
                    out_buf[s][cmd.buffer_id] = st.fwd(st.params, xin)
            elif name == "SendActivation":
                box.send(("act", s + 1, cmd.micro_batch_id),
                         jax.device_put(out_buf[s][cmd.buffer_id],
                                        self.devices[s + 1]))
                out_buf[s][cmd.buffer_id] = None
            elif name == "RecvActivation":
                in_buf[s][cmd.buffer_id] = box.recv(
                    ("act", s, cmd.micro_batch_id))
            else:  # pragma: no cover
                raise ValueError(f"unexpected inference instruction {name}")

        self._run_schedule(streams, execute, box)
        return jnp.mean(jnp.stack(losses))

    # ----------------------------------------------------------- inspection
    def stage_params(self):
        return [st.params for st in self.stages]
