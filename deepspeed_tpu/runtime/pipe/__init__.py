"""Reference deepspeed/runtime/pipe/__init__.py export surface."""

from deepspeed_tpu.runtime.pipe.module import (  # noqa: F401
    LayerSpec, PipelineModule, TiedLayerSpec)
from deepspeed_tpu.runtime.pipe.topology import (  # noqa: F401
    ProcessTopology)
