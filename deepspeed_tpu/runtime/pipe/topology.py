"""Cartesian process topology — rank grids with named axes.

Faithful port of the pure math in deepspeed/runtime/pipe/topology.py
(``ProcessTopology`` :12, ``PipeDataParallelTopology`` :235,
``PipeModelDataParallelTopology`` :246, ``PipelineParallelGrid`` :252).
This layer has no torch/NCCL content — it is coordinate bookkeeping the
TPU build keeps verbatim: the axes map 1:1 onto jax.sharding.Mesh axes and
the test suite (reference test_topology.py) ports unchanged.
"""

import itertools
from collections import namedtuple


class ProcessTopology:
    """Maps n-dimensional Cartesian coordinates to linear ranks (row-major,
    first axis slowest — reference topology.py:12)."""

    def __init__(self, axes, dims):
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping = {}
        for coord in itertools.product(*[range(d) for d in self.dims]):
            rank = 0
            for idx, c in enumerate(coord):
                rank = rank * self.dims[idx] + c
            self.mapping[self.ProcessCoord(*coord)] = rank

    def get_rank(self, **coord_kwargs):
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {coord_kwargs} not in topology"
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_",
                      outer_sep="-"):
        """String like 'model_00' used in checkpoint names
        (reference :86)."""
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis):
        """Lists of ranks that differ only along *axis* (the groups a
        collective over that axis spans — reference :120)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for coord in itertools.product(
                *[range(self.get_dim(a)) for a in other_axes]):
            other = dict(zip(other_axes, coord))
            ranks = [self.get_rank(**{axis: i}, **other)
                     for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        """Ranks whose coords match all filters (reference :151)."""
        def matches(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return [rank for coord, rank in self.mapping.items() if matches(coord)]

    def get_axis_list(self, axis, idx):
        """Ranks with coord[axis] == idx, sorted (reference :171)."""
        return sorted(rank for coord, rank in self.mapping.items()
                      if getattr(coord, axis) == idx)

    def world_size(self):
        n = 1
        for d in self.dims:
            n *= d
        return n

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """axes=(pipe, data) — hybrid pipeline+data (reference :235)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """axes=(pipe, data, model) — 3D parallelism (reference :246)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Axis-rank accessors over a topology (reference :252). The torch
    process-group creation is gone — a collective over axis A is an XLA
    collective bound to mesh axis A — but the rank bookkeeping (stage_id,
    p2p neighbours, checkpoint naming) is kept verbatim."""

    def __init__(self, topology=None, process_group=None, global_rank=0,
                 world_size=None):
        if topology is None:
            assert world_size is not None
            topology = PipeDataParallelTopology(1, world_size)
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)
        self.slice_parallel_size = self.model_parallel_size
        assert self.world_size == (
            self.data_parallel_size * self.pipe_parallel_size *
            self.model_parallel_size)

        coord = topology.get_coord(global_rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0)
        self.slice_parallel_id = self.model_parallel_id

    def get_stage_id(self):
        return self.stage_id

    def get_data_parallel_id(self):
        return self.data_parallel_id

    def get_pipe_parallel_rank(self):
        return self.stage_id

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_rank(self):
        return self.data_parallel_id

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_global_rank(self):
        return self.global_rank

    def get_model_parallel_rank(self):
        return self.model_parallel_id

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def stage_to_global(self, stage_id, **kwargs):
        me = self._topo.get_coord(self.global_rank)
        transform = me._replace(pipe=stage_id, **kwargs)._asdict()
        return self._topo.get_rank(**transform)

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1

    def p2p_prev(self):
        return (self.stage_id - 1) % self.pipe_parallel_size

    def p2p_next(self):
        return (self.stage_id + 1) % self.pipe_parallel_size
