"""SPMD pipeline executor — GPipe dataflow as one jitted scan.

The TPU-native replacement for the reference's instruction interpreter
(runtime/pipe/engine.py:1319 ``_exec_schedule`` dispatching
``_INSTRUCTION_MAP``) and NCCL p2p (runtime/pipe/p2p.py:48/:69). Instead
of per-rank host loops sending tensors between processes, the pipeline is
expressed as a single differentiable program over the mesh ``pipe`` axis:

* per-stage params are STACKED on a leading axis sharded ``P("pipe")``;
* each scan step applies the stage function to every stage's resident
  activation via ``vmap`` (SPMD: all stages compute in parallel);
* activations advance one stage per step with ``jnp.roll`` on the stacked
  axis — XLA lowers a roll of a pipe-sharded array to an ICI
  collective-permute, which IS the p2p send/recv;
* microbatch t enters stage 0 at step t; the last stage's output for
  microbatch t emerges at step t + S - 1. The scan runs the classic GPipe
  fill-drain of ``M + S - 1`` steps.

Because the whole thing is one traced program, ``jax.grad`` derives the
backward pipeline (reverse collective-permutes, 2(M+S-1) effective steps —
the TrainSchedule dataflow) with no hand-written schedule; remat policies
bound activation memory exactly like the reference's
activation-checkpointing hooks.
"""

import functools
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils import groups


def _pipe_constraint(x, extra=None):
    """Constrain dim 0 (the stacked stage dim) to the pipe mesh axis."""
    if not groups.mesh_is_initialized():
        return x
    mesh = groups.get_mesh()
    if mesh.shape[groups.PIPE_AXIS] == 1:
        return x
    spec = P(groups.PIPE_AXIS, *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def pipeline_apply(stage_fn: Callable,
                   stacked_params: Any,
                   microbatches: Any,
                   num_stages: int,
                   remat: bool = True):
    """Run the GPipe dataflow.

    stage_fn(params_s, x) -> y : one stage's computation (uniform across
        stages; params_s is stacked_params indexed at the stage dim).
    stacked_params: pytree with leading [num_stages] dim on every leaf.
    microbatches: pytree with leading [M, micro_batch, ...] dims.
    Returns the stacked last-stage outputs with leading [M] dim.
    """
    S = num_stages
    mb_leaves = jax.tree.leaves(microbatches)
    M = mb_leaves[0].shape[0]
    total = M + S - 1

    fn = stage_fn
    if remat:
        fn = jax.checkpoint(stage_fn)

    # per-stage resident activations, stacked [S, mb, ...]
    zero_act = jax.tree.map(
        lambda x: jnp.zeros((S,) + x.shape[1:], x.dtype), microbatches)

    # pad the microbatch stream with S-1 drain steps
    def pad(x):
        pad_block = jnp.zeros((S - 1,) + x.shape[1:], x.dtype)
        return jnp.concatenate([x, pad_block], axis=0)

    stream = jax.tree.map(pad, microbatches)

    def step(acts, x_t):
        # shift pipeline: stage s receives stage s-1's output;
        # stage 0 receives the incoming microbatch
        shifted = jax.tree.map(lambda a: jnp.roll(a, 1, axis=0), acts)
        shifted = jax.tree.map(
            lambda a, x: a.at[0].set(x), shifted, x_t)
        shifted = jax.tree.map(_pipe_constraint, shifted)
        out = jax.vmap(fn)(stacked_params, shifted)
        out = jax.tree.map(_pipe_constraint, out)
        emit = jax.tree.map(lambda o: o[S - 1], out)
        return out, emit

    _, emitted = jax.lax.scan(step, zero_act, stream)
    # microbatch t's result emerges at step t + S - 1
    return jax.tree.map(lambda e: e[S - 1:], emitted)


class GPipe(nn.Module):
    """Flax module pipelining a uniform block stack over the mesh pipe axis.

    The drop-in replacement for a ``for`` loop of ``num_stages *
    layers_per_stage`` blocks: same math, but params are stacked per stage
    (sharded ``P("pipe")`` via :func:`pipe_sharding_rules`) and the batch
    is streamed through as ``num_microbatches`` GPipe microbatches. The
    scan carries the per-stage resident activations; ``jnp.roll`` on the
    pipe-sharded dim is the ICI collective-permute p2p.

    block_cls(**block_kwargs) must map x -> x (uniform stages; put embeds
    and heads outside the pipelined section, as the reference does with
    first/last-stage LayerSpecs)."""

    block_cls: type
    block_kwargs: dict
    num_stages: int
    layers_per_stage: int
    num_microbatches: int
    remat: bool = True

    @nn.compact
    def __call__(self, x):
        S, M = self.num_stages, self.num_microbatches
        B = x.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        mb = B // M

        block_cls, block_kwargs = self.block_cls, self.block_kwargs
        layers = self.layers_per_stage

        class _StageBody(nn.Module):
            @nn.compact
            def __call__(self, h):
                for i in range(layers):
                    h = block_cls(**block_kwargs, name=f"block_{i}")(h)
                return h

        body = nn.remat(_StageBody) if self.remat else _StageBody
        Stages = nn.vmap(
            body, in_axes=0, out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            metadata_params={nn.PARTITION_NAME: "pipe"})

        class _Step(nn.Module):
            @nn.compact
            def __call__(self, acts, x_t):
                shifted = jnp.roll(acts, 1, axis=0)
                shifted = shifted.at[0].set(x_t)
                shifted = _pipe_constraint(shifted)
                out = Stages(name="stages")(shifted)
                out = _pipe_constraint(out)
                return out, out[S - 1]

        Loop = nn.scan(_Step,
                       variable_broadcast="params",
                       split_rngs={"params": False, "dropout": True},
                       in_axes=0, out_axes=0)

        stream = x.reshape(M, mb, *x.shape[1:])
        pad = jnp.zeros((S - 1, mb) + x.shape[1:], x.dtype)
        stream = jnp.concatenate([stream, pad], axis=0)
        acts0 = jnp.zeros((S, mb) + x.shape[1:], x.dtype)

        _, emitted = Loop(name="pipe_loop")(acts0, stream)
        out = emitted[S - 1:]                       # [M, mb, ...]
        return out.reshape(B, *x.shape[1:])


def pipe_sharding_rules():
    """ModelParallelRules entries: stacked stage params shard dim 0 over
    the pipe axis (the analogue of per-stage parameter residence)."""
    return [(r"pipe_loop.*stages.*", P("pipe"))]
