"""Pipeline module: LayerSpec list + stage partitioning.

Rebuild of deepspeed/runtime/pipe/module.py (``LayerSpec`` :41,
``TiedLayerSpec`` :73, ``PipelineModule`` :87, ``_partition_layers`` :360)
and the partition helpers from deepspeed/runtime/utils
(``partition_uniform``, ``partition_balanced``). The partitioning math and
the user surface are kept; execution differs: instead of per-rank
instantiation + NCCL p2p, ``PipelineModule.build_flax()`` produces (a) a
plain sequential flax module whose stage assignment is metadata (correct
everywhere), and the engine's SPMD executor (pipe/spmd.py) pipelines the
uniform repeated middle over the mesh ``pipe`` axis.
"""

import re
from typing import Any, Callable, List, Optional

import flax.linen as nn
import numpy as np


class LayerSpec:
    """Delayed-construction layer (reference module.py:41): holds the
    module class + ctor args so stages can be materialised lazily."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, nn.Module):
            raise RuntimeError("LayerSpec requires a flax nn.Module subclass")

    def __repr__(self):
        return f"LayerSpec({self.typename.__name__})"

    def build(self, name=None, log=False):
        kwargs = dict(self.module_kwargs)
        if name is not None:
            kwargs.setdefault("name", name)
        return self.typename(*self.module_args, **kwargs)

    def parameters_estimate(self):
        """Rough param count for partition_method='parameters' — built
        lazily from the module's declared features when available."""
        return 1


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared across stages by key (reference
    module.py:73). In flax, tying is expressed by reusing the module
    instance; the key groups specs that must share."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items, num_parts):
    """Even split; remainder spread over leading parts (reference
    runtime/utils.py partition_uniform)."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts


def partition_balanced(weights, num_parts):
    """Split so the max part weight is minimised (binary search over prefix
    sums — reference runtime/utils.py partition_balanced / _lprobe)."""
    weights = list(weights)
    n = len(weights)
    prefix = np.concatenate([[0], np.cumsum(weights)])

    def can_split(limit):
        parts, count, start = [0], 0, 0
        for i in range(1, n + 1):
            if prefix[i] - prefix[start] > limit:
                if i - 1 == start:       # single item exceeds limit
                    return None
                parts.append(i - 1)
                start = i - 1
                count += 1
                if count >= num_parts:
                    return None
        while len(parts) < num_parts:
            parts.append(n)
        parts.append(n)
        return parts if len(parts) == num_parts + 1 else None

    lo = max(weights) if weights else 0
    hi = int(prefix[-1]) or 1
    best = None
    while lo <= hi:
        mid = (lo + hi) // 2
        res = can_split(mid)
        if res is not None:
            best = res
            hi = mid - 1
        else:
            lo = mid + 1
    return best or partition_uniform(n, num_parts)


class PipelineModule:
    """Stage container (reference module.py:87).

    Accepts a list of LayerSpec / flax modules; partitions them over
    ``num_stages`` with ``partition_method`` in {"uniform", "parameters",
    "type:<regex>"}. ``stage_layers(s)`` returns stage s's specs;
    ``build_sequential()`` returns one flax module running all layers (the
    single-program form the SPMD executor consumes)."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn: Optional[Callable] = None,
                 partition_method="parameters",
                 activation_checkpoint_interval=0, seed_layers=False):
        self.specs = list(layers)
        if topology is not None:
            self.num_stages = topology.get_dim("pipe")
        else:
            assert num_stages is not None, "need num_stages or topology"
            self.num_stages = num_stages
        self.topology = topology
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.parts = self._partition_layers()

    # ---------------------------------------------------------- partitioning
    def _weights(self):
        method = self.partition_method.lower()
        n = len(self.specs)
        if method == "uniform":
            return [1] * n
        if method == "parameters":
            return [max(int(self._param_estimate(s)), 1) for s in self.specs]
        if method.startswith("type:"):
            pat = re.compile(method[5:], re.IGNORECASE)

            def matches(s):
                if isinstance(s, LayerSpec):
                    return bool(pat.search(s.typename.__name__))
                return bool(pat.search(type(s).__name__))

            return [1 if matches(s) else 0 for s in self.specs]
        raise NotImplementedError(f"partition_method {self.partition_method}")

    @staticmethod
    def _param_estimate(spec):
        """Estimate params from ctor kwargs of common layers; falls back
        to 1 (the reference instantiates and counts — too eager here)."""
        if not isinstance(spec, LayerSpec):
            return 1
        kw = spec.module_kwargs
        feats = kw.get("features") or kw.get("hidden_size") or \
            kw.get("n_embd") or 0
        if feats:
            return int(feats) ** 2
        return 1

    def _partition_layers(self):
        weights = self._weights()
        method = self.partition_method.lower()
        if method == "uniform":
            return partition_uniform(len(self.specs), self.num_stages)
        return partition_balanced(weights, self.num_stages)

    def stage_layers(self, stage_id) -> List[Any]:
        return self.specs[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_owner(self, layer_idx) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def num_layers(self):
        return len(self.specs)

    # ------------------------------------------------------------ flax build
    def build_sequential(self):
        """One flax module applying every layer in order; tied specs share
        one instance per key. Stage boundaries (self.parts) become the
        SPMD executor's split points."""
        specs = self.specs
        parts = self.parts
        loss_fn = self.loss_fn

        class _Sequential(nn.Module):
            @nn.compact
            def __call__(self, batch):
                x, rest = (batch[0], batch[1:]) if isinstance(
                    batch, (tuple, list)) else (batch, ())
                tied = {}
                for i, spec in enumerate(specs):
                    if isinstance(spec, TiedLayerSpec):
                        if spec.key not in tied:
                            tied[spec.key] = spec.build(name=f"tied_{spec.key}")
                        mod = tied[spec.key]
                        x = (spec.forward_fn(mod, x) if spec.forward_fn
                             else mod(x))
                    elif isinstance(spec, LayerSpec):
                        x = spec.build(name=f"layer_{i}")(x)
                    else:
                        x = spec(x)
                if loss_fn is not None and rest:
                    return loss_fn(x, *rest)
                return x

        return _Sequential()

    def ckpt_layer_path(self, ckpt_dir, local_layer_idx):
        """Checkpoint file naming parity (reference module.py:537)."""
        import os
        return os.path.join(ckpt_dir,
                            f"layer_{local_layer_idx:02d}-model_states.pt")
