"""DeepSpeed-schema JSON config system.

Parity with ``deepspeed/runtime/config.py`` (``DeepSpeedConfig`` at
config.py:789, accessors :77-680): the same JSON file a DeepSpeed user
writes is accepted unchanged. The reference exposes ~200 flat ``get_*``
helpers feeding engine properties; here the parsed values land on typed
attributes with identical names so ``engine.train_batch_size()`` etc. keep
working.

Batch-size triangulation follows the reference exactly:
``train_batch_size = micro_batch_per_gpu * gradient_accumulation_steps *
data_parallel_world_size`` — any two determine the third; one alone pins
the others to 1/world; all three must agree.
"""

import json
import os

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.utils.logging import logger

# Optimizer names (reference: runtime/config.py:77-96)
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER, ADAGRAD_OPTIMIZER
]


class DeepSpeedConfigError(Exception):
    pass


def get_scalar_param(d, name, default):
    return d.get(name, default)


class DeepSpeedConfigObject:
    """repr-able plain config holder."""

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4, default=repr)


class DeepSpeedFP16Config(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        fp16 = param_dict.get(C.FP16, {}) or {}
        self.enabled = fp16.get(C.FP16_ENABLED, C.FP16_ENABLED_DEFAULT)
        self.loss_scale = fp16.get(C.FP16_LOSS_SCALE, C.FP16_LOSS_SCALE_DEFAULT)
        self.initial_scale_power = fp16.get(C.FP16_INITIAL_SCALE_POWER,
                                            C.FP16_INITIAL_SCALE_POWER_DEFAULT)
        self.loss_scale_window = fp16.get(C.FP16_LOSS_SCALE_WINDOW,
                                          C.FP16_LOSS_SCALE_WINDOW_DEFAULT)
        self.hysteresis = fp16.get(C.FP16_HYSTERESIS, C.FP16_HYSTERESIS_DEFAULT)
        self.min_loss_scale = fp16.get(C.FP16_MIN_LOSS_SCALE,
                                       C.FP16_MIN_LOSS_SCALE_DEFAULT)
        self.master_weights_and_grads = fp16.get(
            C.FP16_MASTER_WEIGHTS_AND_GRADS, C.FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT)

    @property
    def dynamic_loss_scale(self):
        return self.loss_scale == 0


class DeepSpeedBF16Config(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        bf = param_dict.get(C.BFLOAT16, param_dict.get(C.BFLOAT16_OLD, {})) or {}
        self.enabled = bf.get(C.BFLOAT16_ENABLED, C.BFLOAT16_ENABLED_DEFAULT)


class DeepSpeedTensorboardConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        tb = param_dict.get(C.TENSORBOARD, {}) or {}
        self.enabled = tb.get(C.TENSORBOARD_ENABLED, C.TENSORBOARD_ENABLED_DEFAULT)
        self.output_path = tb.get(C.TENSORBOARD_OUTPUT_PATH,
                                  C.TENSORBOARD_OUTPUT_PATH_DEFAULT)
        self.job_name = tb.get(C.TENSORBOARD_JOB_NAME, C.TENSORBOARD_JOB_NAME_DEFAULT)


class DeepSpeedTelemetryConfig(DeepSpeedConfigObject):
    """``telemetry`` block (TPU-native, beyond the reference schema):
    structured spans + compile watch + metrics sinks (telemetry/).

    Env overrides (sweep ergonomics, applied after JSON): ``DS_TELEMETRY``
    = 1/0 force-toggles ``enabled``; ``DS_TELEMETRY_DIR`` overrides
    ``output_path``; ``DS_COST_EXPLORER`` / ``DS_TELEMETRY_HEALTH`` /
    ``DS_TELEMETRY_GOODPUT`` / ``DS_TELEMETRY_MEMORY`` /
    ``DS_TELEMETRY_CHRONICLE`` / ``DS_TELEMETRY_SERVER`` /
    ``DS_TELEMETRY_SLO`` = 1/0 force-toggle the cost-explorer / health /
    goodput / memory / chronicle / obs-server / slo sub-blocks."""

    def __init__(self, param_dict):
        t = param_dict.get(C.TELEMETRY, {}) or {}
        self.enabled = t.get(C.TELEMETRY_ENABLED, C.TELEMETRY_ENABLED_DEFAULT)
        self.output_path = t.get(C.TELEMETRY_OUTPUT_PATH,
                                 C.TELEMETRY_OUTPUT_PATH_DEFAULT)
        self.job_name = t.get(C.TELEMETRY_JOB_NAME,
                              C.TELEMETRY_JOB_NAME_DEFAULT)
        self.trace = t.get(C.TELEMETRY_TRACE, C.TELEMETRY_TRACE_DEFAULT)
        self.jax_annotations = t.get(C.TELEMETRY_JAX_ANNOTATIONS,
                                     C.TELEMETRY_JAX_ANNOTATIONS_DEFAULT)
        self.compile_watch = t.get(C.TELEMETRY_COMPILE_WATCH,
                                   C.TELEMETRY_COMPILE_WATCH_DEFAULT)
        self.jsonl = t.get(C.TELEMETRY_JSONL, C.TELEMETRY_JSONL_DEFAULT)
        self.prometheus = t.get(C.TELEMETRY_PROMETHEUS,
                                C.TELEMETRY_PROMETHEUS_DEFAULT)
        self.memory_metrics = t.get(C.TELEMETRY_MEMORY_METRICS,
                                    C.TELEMETRY_MEMORY_METRICS_DEFAULT)
        self.max_trace_events = t.get(C.TELEMETRY_MAX_TRACE_EVENTS,
                                      C.TELEMETRY_MAX_TRACE_EVENTS_DEFAULT)
        # cost_explorer sub-block (telemetry/cost_explorer.py): compiled-
        # program census + roofline/MFU + HBM pre-flight. Flattened onto
        # cost_explorer_* attributes; 0 peaks mean "detect from the chip".
        ce = t.get(C.COST_EXPLORER, {}) or {}
        self.cost_explorer_enabled = ce.get(C.COST_EXPLORER_ENABLED,
                                            C.COST_EXPLORER_ENABLED_DEFAULT)
        self.cost_explorer_peak_tflops = ce.get(
            C.COST_EXPLORER_PEAK_TFLOPS, C.COST_EXPLORER_PEAK_TFLOPS_DEFAULT)
        self.cost_explorer_peak_hbm_gbps = ce.get(
            C.COST_EXPLORER_PEAK_HBM_GBPS,
            C.COST_EXPLORER_PEAK_HBM_GBPS_DEFAULT)
        self.cost_explorer_ici_gbps = ce.get(
            C.COST_EXPLORER_ICI_GBPS, C.COST_EXPLORER_ICI_GBPS_DEFAULT)
        self.cost_explorer_hbm_gb = ce.get(C.COST_EXPLORER_HBM_GB,
                                           C.COST_EXPLORER_HBM_GB_DEFAULT)
        self.cost_explorer_preflight = ce.get(
            C.COST_EXPLORER_PREFLIGHT, C.COST_EXPLORER_PREFLIGHT_DEFAULT)
        self.cost_explorer_preflight_threshold = ce.get(
            C.COST_EXPLORER_PREFLIGHT_THRESHOLD,
            C.COST_EXPLORER_PREFLIGHT_THRESHOLD_DEFAULT)
        # health sub-block (telemetry/health.py): in-step numerics stats +
        # host-side anomaly rules + HEALTH.json forensics. Flattened onto
        # health_* attributes like the cost explorer.
        h = t.get(C.TELEMETRY_HEALTH, {}) or {}
        self.health_enabled = h.get(C.HEALTH_ENABLED,
                                    C.HEALTH_ENABLED_DEFAULT)
        self.health_bucket_depth = h.get(C.HEALTH_BUCKET_DEPTH,
                                         C.HEALTH_BUCKET_DEPTH_DEFAULT)
        self.health_cadence = h.get(C.HEALTH_CADENCE,
                                    C.HEALTH_CADENCE_DEFAULT)
        self.health_ewma_alpha = h.get(C.HEALTH_EWMA_ALPHA,
                                       C.HEALTH_EWMA_ALPHA_DEFAULT)
        self.health_loss_spike_zscore = h.get(
            C.HEALTH_LOSS_SPIKE_ZSCORE, C.HEALTH_LOSS_SPIKE_ZSCORE_DEFAULT)
        self.health_grad_spike_zscore = h.get(
            C.HEALTH_GRAD_SPIKE_ZSCORE, C.HEALTH_GRAD_SPIKE_ZSCORE_DEFAULT)
        self.health_warmup_samples = h.get(C.HEALTH_WARMUP_SAMPLES,
                                           C.HEALTH_WARMUP_SAMPLES_DEFAULT)
        self.health_overflow_streak = h.get(
            C.HEALTH_OVERFLOW_STREAK, C.HEALTH_OVERFLOW_STREAK_DEFAULT)
        self.health_stall_window = h.get(C.HEALTH_STALL_WINDOW,
                                         C.HEALTH_STALL_WINDOW_DEFAULT)
        self.health_stall_rel_delta = h.get(
            C.HEALTH_STALL_REL_DELTA, C.HEALTH_STALL_REL_DELTA_DEFAULT)
        self.health_ring_size = h.get(C.HEALTH_RING_SIZE,
                                      C.HEALTH_RING_SIZE_DEFAULT)
        self.health_snapshot_file = h.get(C.HEALTH_SNAPSHOT_FILE,
                                          C.HEALTH_SNAPSHOT_FILE_DEFAULT)
        self.health_trace_on_anomaly = h.get(
            C.HEALTH_TRACE_ON_ANOMALY, C.HEALTH_TRACE_ON_ANOMALY_DEFAULT)
        # goodput sub-block (telemetry/ledger.py): wall-clock goodput/
        # badput attribution + GOODPUT.json forensics + on-anomaly
        # profiler capture. Flattened onto goodput_* attributes.
        g = t.get(C.TELEMETRY_GOODPUT, {}) or {}
        self.goodput_enabled = g.get(C.GOODPUT_ENABLED,
                                     C.GOODPUT_ENABLED_DEFAULT)
        self.goodput_cadence = g.get(C.GOODPUT_CADENCE,
                                     C.GOODPUT_CADENCE_DEFAULT)
        self.goodput_input_wait_frac = g.get(
            C.GOODPUT_INPUT_WAIT_FRAC, C.GOODPUT_INPUT_WAIT_FRAC_DEFAULT)
        self.goodput_unattributed_frac = g.get(
            C.GOODPUT_UNATTRIBUTED_FRAC,
            C.GOODPUT_UNATTRIBUTED_FRAC_DEFAULT)
        self.goodput_warmup_windows = g.get(
            C.GOODPUT_WARMUP_WINDOWS, C.GOODPUT_WARMUP_WINDOWS_DEFAULT)
        self.goodput_window_ring = g.get(C.GOODPUT_WINDOW_RING,
                                         C.GOODPUT_WINDOW_RING_DEFAULT)
        self.goodput_snapshot_file = g.get(C.GOODPUT_SNAPSHOT_FILE,
                                           C.GOODPUT_SNAPSHOT_FILE_DEFAULT)
        self.goodput_profiler_capture = g.get(
            C.GOODPUT_PROFILER_CAPTURE, C.GOODPUT_PROFILER_CAPTURE_DEFAULT)
        self.goodput_profiler_capture_steps = g.get(
            C.GOODPUT_PROFILER_CAPTURE_STEPS,
            C.GOODPUT_PROFILER_CAPTURE_STEPS_DEFAULT)
        self.goodput_profiler_max_captures = g.get(
            C.GOODPUT_PROFILER_MAX_CAPTURES,
            C.GOODPUT_PROFILER_MAX_CAPTURES_DEFAULT)
        self.goodput_profiler_dir = g.get(C.GOODPUT_PROFILER_DIR,
                                          C.GOODPUT_PROFILER_DIR_DEFAULT)
        # anatomy sub-block (telemetry/step_anatomy.py): measured device-
        # time attribution from bounded jax.profiler captures. Flattened
        # onto anatomy_* attributes.
        an = t.get(C.TELEMETRY_ANATOMY, {}) or {}
        self.anatomy_enabled = an.get(C.ANATOMY_ENABLED,
                                      C.ANATOMY_ENABLED_DEFAULT)
        self.anatomy_capture_steps = int(an.get(
            C.ANATOMY_CAPTURE_STEPS, C.ANATOMY_CAPTURE_STEPS_DEFAULT))
        self.anatomy_keep_raw_traces = int(an.get(
            C.ANATOMY_KEEP_RAW_TRACES, C.ANATOMY_KEEP_RAW_TRACES_DEFAULT))
        self.anatomy_report_file = an.get(C.ANATOMY_REPORT_FILE,
                                          C.ANATOMY_REPORT_FILE_DEFAULT)
        # fleet sub-block (telemetry/fleet.py): cross-rank flight recorder
        # — per-rank window-record shipping + rank-0 skew/desync
        # sentinels. Flattened onto fleet_* attributes.
        fl = t.get(C.TELEMETRY_FLEET, {}) or {}
        self.fleet_enabled = fl.get(C.FLEET_ENABLED,
                                    C.FLEET_ENABLED_DEFAULT)
        self.fleet_run_dir = fl.get(C.FLEET_RUN_DIR,
                                    C.FLEET_RUN_DIR_DEFAULT)
        self.fleet_rank = int(fl.get(C.FLEET_RANK, C.FLEET_RANK_DEFAULT))
        self.fleet_cadence = int(fl.get(C.FLEET_CADENCE,
                                        C.FLEET_CADENCE_DEFAULT))
        self.fleet_desync = fl.get(C.FLEET_DESYNC, C.FLEET_DESYNC_DEFAULT)
        self.fleet_desync_cadence = int(fl.get(
            C.FLEET_DESYNC_CADENCE, C.FLEET_DESYNC_CADENCE_DEFAULT))
        self.fleet_step_time_skew_frac = float(fl.get(
            C.FLEET_STEP_TIME_SKEW_FRAC,
            C.FLEET_STEP_TIME_SKEW_FRAC_DEFAULT))
        self.fleet_input_wait_skew_frac = float(fl.get(
            C.FLEET_INPUT_WAIT_SKEW_FRAC,
            C.FLEET_INPUT_WAIT_SKEW_FRAC_DEFAULT))
        self.fleet_checkpoint_skew_frac = float(fl.get(
            C.FLEET_CHECKPOINT_SKEW_FRAC,
            C.FLEET_CHECKPOINT_SKEW_FRAC_DEFAULT))
        self.fleet_checkpoint_skew_floor_ms = float(fl.get(
            C.FLEET_CHECKPOINT_SKEW_FLOOR_MS,
            C.FLEET_CHECKPOINT_SKEW_FLOOR_MS_DEFAULT))
        self.fleet_warmup_windows = int(fl.get(
            C.FLEET_WARMUP_WINDOWS, C.FLEET_WARMUP_WINDOWS_DEFAULT))
        self.fleet_window_ring = int(fl.get(C.FLEET_WINDOW_RING,
                                            C.FLEET_WINDOW_RING_DEFAULT))
        self.fleet_snapshot_file = fl.get(C.FLEET_SNAPSHOT_FILE,
                                          C.FLEET_SNAPSHOT_FILE_DEFAULT)
        self.fleet_background_ship = fl.get(
            C.FLEET_BACKGROUND_SHIP, C.FLEET_BACKGROUND_SHIP_DEFAULT)
        # memory sub-block (telemetry/memory_observatory.py): HBM residency
        # observatory — measured buffer attribution + leak/drift/frag/oom
        # sentinels. Flattened onto memory_* attributes.
        m = t.get(C.TELEMETRY_MEMORY, {}) or {}
        self.memory_enabled = m.get(C.MEMORY_ENABLED,
                                    C.MEMORY_ENABLED_DEFAULT)
        self.memory_cadence = int(m.get(C.MEMORY_CADENCE,
                                        C.MEMORY_CADENCE_DEFAULT))
        self.memory_snapshot_file = m.get(C.MEMORY_SNAPSHOT_FILE,
                                          C.MEMORY_SNAPSHOT_FILE_DEFAULT)
        self.memory_report_file = m.get(C.MEMORY_REPORT_FILE,
                                        C.MEMORY_REPORT_FILE_DEFAULT)
        self.memory_leak_windows = int(m.get(
            C.MEMORY_LEAK_WINDOWS, C.MEMORY_LEAK_WINDOWS_DEFAULT))
        self.memory_warmup_windows = int(m.get(
            C.MEMORY_WARMUP_WINDOWS, C.MEMORY_WARMUP_WINDOWS_DEFAULT))
        self.memory_drift_threshold = float(m.get(
            C.MEMORY_DRIFT_THRESHOLD, C.MEMORY_DRIFT_THRESHOLD_DEFAULT))
        self.memory_frag_threshold = float(m.get(
            C.MEMORY_FRAG_THRESHOLD, C.MEMORY_FRAG_THRESHOLD_DEFAULT))
        self.memory_headroom = float(m.get(C.MEMORY_HEADROOM,
                                           C.MEMORY_HEADROOM_DEFAULT))
        self.memory_budget_bytes = int(m.get(
            C.MEMORY_BUDGET_BYTES, C.MEMORY_BUDGET_BYTES_DEFAULT))
        self.memory_ring_size = int(m.get(C.MEMORY_RING_SIZE,
                                          C.MEMORY_RING_SIZE_DEFAULT))
        # chronicle sub-block (telemetry/chronicle.py + incidents.py):
        # the run-wide causal event timeline. Flattened onto chronicle_*.
        ch = t.get(C.TELEMETRY_CHRONICLE, {}) or {}
        self.chronicle_enabled = ch.get(C.CHRONICLE_ENABLED,
                                        C.CHRONICLE_ENABLED_DEFAULT)
        self.chronicle_run_dir = ch.get(C.CHRONICLE_RUN_DIR,
                                        C.CHRONICLE_RUN_DIR_DEFAULT)
        self.chronicle_max_events = int(ch.get(
            C.CHRONICLE_MAX_EVENTS, C.CHRONICLE_MAX_EVENTS_DEFAULT))
        self.chronicle_summary_file = ch.get(
            C.CHRONICLE_SUMMARY_FILE, C.CHRONICLE_SUMMARY_FILE_DEFAULT)
        self.chronicle_incidents_file = ch.get(
            C.CHRONICLE_INCIDENTS_FILE, C.CHRONICLE_INCIDENTS_FILE_DEFAULT)
        self.chronicle_step_window = int(ch.get(
            C.CHRONICLE_STEP_WINDOW, C.CHRONICLE_STEP_WINDOW_DEFAULT))
        self.chronicle_time_window_s = float(ch.get(
            C.CHRONICLE_TIME_WINDOW_S, C.CHRONICLE_TIME_WINDOW_S_DEFAULT))
        self.chronicle_background = ch.get(C.CHRONICLE_BACKGROUND,
                                           C.CHRONICLE_BACKGROUND_DEFAULT)
        # server sub-block (telemetry/obs_server.py): the live HTTP
        # scrape/status endpoint. Flattened onto server_*.
        sv = t.get(C.TELEMETRY_SERVER, {}) or {}
        self.server_enabled = sv.get(C.SERVER_ENABLED,
                                     C.SERVER_ENABLED_DEFAULT)
        self.server_host = sv.get(C.SERVER_HOST, C.SERVER_HOST_DEFAULT)
        self.server_port = int(sv.get(C.SERVER_PORT,
                                      C.SERVER_PORT_DEFAULT))
        self.server_token = sv.get(C.SERVER_TOKEN,
                                   C.SERVER_TOKEN_DEFAULT)
        self.server_events_tail = int(sv.get(
            C.SERVER_EVENTS_TAIL, C.SERVER_EVENTS_TAIL_DEFAULT))
        # slo sub-block (telemetry/slo.py): multi-window burn-rate
        # alerting over declarative objectives. Flattened onto slo_*.
        sl = t.get(C.TELEMETRY_SLO, {}) or {}
        self.slo_enabled = sl.get(C.SLO_ENABLED, C.SLO_ENABLED_DEFAULT)
        self.slo_fast_window_s = float(sl.get(
            C.SLO_FAST_WINDOW_S, C.SLO_FAST_WINDOW_S_DEFAULT))
        self.slo_slow_window_s = float(sl.get(
            C.SLO_SLOW_WINDOW_S, C.SLO_SLOW_WINDOW_S_DEFAULT))
        self.slo_burn_threshold = float(sl.get(
            C.SLO_BURN_THRESHOLD, C.SLO_BURN_THRESHOLD_DEFAULT))
        self.slo_eval_interval_s = float(sl.get(
            C.SLO_EVAL_INTERVAL_S, C.SLO_EVAL_INTERVAL_S_DEFAULT))
        self.slo_objectives = tuple(sl.get(C.SLO_OBJECTIVES)
                                    or C.SLO_OBJECTIVES_DEFAULT)
        self.slo_goodput_target = float(sl.get(
            C.SLO_GOODPUT_TARGET, C.SLO_GOODPUT_TARGET_DEFAULT))
        self.slo_ttft_target = float(sl.get(
            C.SLO_TTFT_TARGET, C.SLO_TTFT_TARGET_DEFAULT))
        self.slo_ttft_threshold_ms = float(sl.get(
            C.SLO_TTFT_THRESHOLD_MS, C.SLO_TTFT_THRESHOLD_MS_DEFAULT))
        self.slo_e2e_target = float(sl.get(
            C.SLO_E2E_TARGET, C.SLO_E2E_TARGET_DEFAULT))
        self.slo_e2e_threshold_ms = float(sl.get(
            C.SLO_E2E_THRESHOLD_MS, C.SLO_E2E_THRESHOLD_MS_DEFAULT))
        self.slo_snapshot_file = sl.get(C.SLO_SNAPSHOT_FILE,
                                        C.SLO_SNAPSHOT_FILE_DEFAULT)
        # federation sub-block (telemetry/federation.py): cross-process
        # mission control — peer-scraping aggregator, merged fleet
        # timeline, fleet-level SLO burn. Flattened onto federation_*.
        fed = t.get(C.TELEMETRY_FEDERATION, {}) or {}
        self.federation_enabled = fed.get(C.FEDERATION_ENABLED,
                                          C.FEDERATION_ENABLED_DEFAULT)
        self.federation_peers = tuple(fed.get(C.FEDERATION_PEERS)
                                      or C.FEDERATION_PEERS_DEFAULT)
        self.federation_run_dir = fed.get(C.FEDERATION_RUN_DIR,
                                          C.FEDERATION_RUN_DIR_DEFAULT)
        self.federation_aggregator = str(fed.get(
            C.FEDERATION_AGGREGATOR, C.FEDERATION_AGGREGATOR_DEFAULT))
        self.federation_scrape_interval_s = float(fed.get(
            C.FEDERATION_SCRAPE_INTERVAL_S,
            C.FEDERATION_SCRAPE_INTERVAL_S_DEFAULT))
        self.federation_timeout_s = float(fed.get(
            C.FEDERATION_TIMEOUT_S, C.FEDERATION_TIMEOUT_S_DEFAULT))
        self.federation_stale_after_s = float(fed.get(
            C.FEDERATION_STALE_AFTER_S,
            C.FEDERATION_STALE_AFTER_S_DEFAULT))
        self.federation_events_ring = int(fed.get(
            C.FEDERATION_EVENTS_RING, C.FEDERATION_EVENTS_RING_DEFAULT))
        self.federation_snapshot_file = fed.get(
            C.FEDERATION_SNAPSHOT_FILE, C.FEDERATION_SNAPSHOT_FILE_DEFAULT)
        self.federation_goodput_target = float(fed.get(
            C.FEDERATION_GOODPUT_TARGET,
            C.FEDERATION_GOODPUT_TARGET_DEFAULT))
        self.federation_ttft_target = float(fed.get(
            C.FEDERATION_TTFT_TARGET, C.FEDERATION_TTFT_TARGET_DEFAULT))
        env = os.environ.get("DS_TELEMETRY")
        if env is not None:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        env_dir = os.environ.get("DS_TELEMETRY_DIR")
        if env_dir:
            self.output_path = env_dir
        env_ce = os.environ.get("DS_COST_EXPLORER")
        if env_ce is not None:
            self.cost_explorer_enabled = env_ce.lower() in (
                "1", "true", "yes", "on")
        env_h = os.environ.get("DS_TELEMETRY_HEALTH")
        if env_h is not None:
            self.health_enabled = env_h.lower() in ("1", "true", "yes", "on")
        env_g = os.environ.get("DS_TELEMETRY_GOODPUT")
        if env_g is not None:
            self.goodput_enabled = env_g.lower() in ("1", "true", "yes",
                                                     "on")
        env_an = os.environ.get("DS_TELEMETRY_ANATOMY")
        if env_an is not None:
            self.anatomy_enabled = env_an.lower() in ("1", "true", "yes",
                                                      "on")
        env_f = os.environ.get("DS_TELEMETRY_FLEET")
        if env_f is not None:
            self.fleet_enabled = env_f.lower() in ("1", "true", "yes",
                                                   "on")
        env_fd = os.environ.get("DS_TELEMETRY_FLEET_RUN_DIR")
        if env_fd:
            self.fleet_run_dir = env_fd
        env_fr = os.environ.get("DS_TELEMETRY_FLEET_RANK")
        if env_fr is not None:
            self.fleet_rank = int(env_fr)
        env_m = os.environ.get("DS_TELEMETRY_MEMORY")
        if env_m is not None:
            self.memory_enabled = env_m.lower() in ("1", "true", "yes",
                                                    "on")
        env_ch = os.environ.get("DS_TELEMETRY_CHRONICLE")
        if env_ch is not None:
            self.chronicle_enabled = env_ch.lower() in ("1", "true",
                                                        "yes", "on")
        env_sv = os.environ.get("DS_TELEMETRY_SERVER")
        if env_sv is not None:
            self.server_enabled = env_sv.lower() in ("1", "true", "yes",
                                                     "on")
        env_sl = os.environ.get("DS_TELEMETRY_SLO")
        if env_sl is not None:
            self.slo_enabled = env_sl.lower() in ("1", "true", "yes",
                                                  "on")
        env_fe = os.environ.get("DS_TELEMETRY_FEDERATION")
        if env_fe is not None:
            self.federation_enabled = env_fe.lower() in ("1", "true",
                                                         "yes", "on")
        env_frd = os.environ.get("DS_TELEMETRY_FEDERATION_RUN_DIR")
        if env_frd:
            self.federation_run_dir = env_frd
        env_fp = os.environ.get("DS_TELEMETRY_FEDERATION_PEERS")
        if env_fp:
            self.federation_peers = tuple(
                p.strip() for p in env_fp.split(",") if p.strip())
        env_fa = os.environ.get("DS_TELEMETRY_FEDERATION_AGGREGATOR")
        if env_fa:
            self.federation_aggregator = env_fa
        if self.anatomy_capture_steps < 1:
            raise DeepSpeedConfigError(
                f"telemetry.anatomy.capture_steps must be >= 1, got "
                f"{self.anatomy_capture_steps}")
        if self.anatomy_keep_raw_traces < 0:
            raise DeepSpeedConfigError(
                f"telemetry.anatomy.keep_raw_traces must be >= 0, got "
                f"{self.anatomy_keep_raw_traces}")
        if self.fleet_cadence < 0:
            raise DeepSpeedConfigError(
                f"telemetry.fleet.cadence must be >= 0, got "
                f"{self.fleet_cadence}")
        if self.fleet_desync_cadence < 0:
            raise DeepSpeedConfigError(
                f"telemetry.fleet.desync_cadence must be >= 0, got "
                f"{self.fleet_desync_cadence}")
        for name, frac in (("step_time_skew_frac",
                            self.fleet_step_time_skew_frac),
                           ("input_wait_skew_frac",
                            self.fleet_input_wait_skew_frac),
                           ("checkpoint_skew_frac",
                            self.fleet_checkpoint_skew_frac)):
            if not 0.0 < frac <= 1.0:
                raise DeepSpeedConfigError(
                    f"telemetry.fleet.{name} must be in (0, 1], got "
                    f"{frac}")
        if self.fleet_window_ring < 1:
            raise DeepSpeedConfigError(
                f"telemetry.fleet.window_ring must be >= 1, got "
                f"{self.fleet_window_ring}")
        if self.memory_cadence < 0:
            raise DeepSpeedConfigError(
                f"telemetry.memory.cadence must be >= 0, got "
                f"{self.memory_cadence}")
        if self.memory_leak_windows < 2:
            raise DeepSpeedConfigError(
                f"telemetry.memory.leak_windows must be >= 2, got "
                f"{self.memory_leak_windows}")
        if self.memory_warmup_windows < 0:
            raise DeepSpeedConfigError(
                f"telemetry.memory.warmup_windows must be >= 0, got "
                f"{self.memory_warmup_windows}")
        if not 0.0 < self.memory_drift_threshold:
            raise DeepSpeedConfigError(
                f"telemetry.memory.drift_threshold must be > 0, got "
                f"{self.memory_drift_threshold}")
        if not 0.0 < self.memory_frag_threshold <= 1.0:
            raise DeepSpeedConfigError(
                f"telemetry.memory.frag_threshold must be in (0, 1], got "
                f"{self.memory_frag_threshold}")
        if not 0.0 < self.memory_headroom <= 1.0:
            raise DeepSpeedConfigError(
                f"telemetry.memory.headroom must be in (0, 1], got "
                f"{self.memory_headroom}")
        if self.memory_budget_bytes < 0:
            raise DeepSpeedConfigError(
                f"telemetry.memory.budget_bytes must be >= 0, got "
                f"{self.memory_budget_bytes}")
        if self.memory_ring_size < 1:
            raise DeepSpeedConfigError(
                f"telemetry.memory.ring_size must be >= 1, got "
                f"{self.memory_ring_size}")
        if self.chronicle_max_events < 1:
            raise DeepSpeedConfigError(
                f"telemetry.chronicle.max_events must be >= 1, got "
                f"{self.chronicle_max_events}")
        if self.chronicle_step_window < 0:
            raise DeepSpeedConfigError(
                f"telemetry.chronicle.step_window must be >= 0, got "
                f"{self.chronicle_step_window}")
        if self.chronicle_time_window_s <= 0:
            raise DeepSpeedConfigError(
                f"telemetry.chronicle.time_window_s must be > 0, got "
                f"{self.chronicle_time_window_s}")
        if not 0 <= self.server_port <= 65535:
            raise DeepSpeedConfigError(
                f"telemetry.server.port must be in [0, 65535], got "
                f"{self.server_port}")
        if self.server_events_tail < 1:
            raise DeepSpeedConfigError(
                f"telemetry.server.events_tail must be >= 1, got "
                f"{self.server_events_tail}")
        if not 0.0 < self.slo_fast_window_s < self.slo_slow_window_s:
            raise DeepSpeedConfigError(
                f"telemetry.slo windows must satisfy 0 < fast_window_s "
                f"< slow_window_s, got {self.slo_fast_window_s} / "
                f"{self.slo_slow_window_s}")
        if self.slo_burn_threshold <= 0:
            raise DeepSpeedConfigError(
                f"telemetry.slo.burn_threshold must be > 0, got "
                f"{self.slo_burn_threshold}")
        if self.slo_eval_interval_s <= 0:
            raise DeepSpeedConfigError(
                f"telemetry.slo.eval_interval_s must be > 0, got "
                f"{self.slo_eval_interval_s}")
        for tname, target in (("goodput_target", self.slo_goodput_target),
                              ("ttft_target", self.slo_ttft_target),
                              ("e2e_target", self.slo_e2e_target)):
            if not 0.0 < target < 1.0:
                raise DeepSpeedConfigError(
                    f"telemetry.slo.{tname} must be in (0, 1), got "
                    f"{target}")
        for mname, ms in (("ttft_threshold_ms",
                           self.slo_ttft_threshold_ms),
                          ("e2e_threshold_ms",
                           self.slo_e2e_threshold_ms)):
            if ms <= 0:
                raise DeepSpeedConfigError(
                    f"telemetry.slo.{mname} must be > 0, got {ms}")
        for o in self.slo_objectives:
            # declarative objectives fail at config time, not first tick
            from deepspeed_tpu.telemetry.slo import normalize_objective
            try:
                normalize_objective(o)
            except ValueError as e:
                raise DeepSpeedConfigError(
                    f"telemetry.slo.objectives: {e}")
        if self.federation_aggregator not in ("auto", "always", "never"):
            raise DeepSpeedConfigError(
                f"telemetry.federation.aggregator must be one of "
                f"auto/always/never, got {self.federation_aggregator!r}")
        for fname, fval in (
                ("scrape_interval_s", self.federation_scrape_interval_s),
                ("timeout_s", self.federation_timeout_s),
                ("stale_after_s", self.federation_stale_after_s)):
            if fval <= 0:
                raise DeepSpeedConfigError(
                    f"telemetry.federation.{fname} must be > 0, got "
                    f"{fval}")
        if self.federation_events_ring < 16:
            raise DeepSpeedConfigError(
                f"telemetry.federation.events_ring must be >= 16, got "
                f"{self.federation_events_ring}")
        for tname, target in (
                ("goodput_target", self.federation_goodput_target),
                ("ttft_target", self.federation_ttft_target)):
            if not 0.0 < target < 1.0:
                raise DeepSpeedConfigError(
                    f"telemetry.federation.{tname} must be in (0, 1), "
                    f"got {target}")
        for p in self.federation_peers:
            if not isinstance(p, str) or not p.startswith("http"):
                raise DeepSpeedConfigError(
                    f"telemetry.federation.peers entries must be http "
                    f"base urls, got {p!r}")


class DeepSpeedDataPrefetchConfig(DeepSpeedConfigObject):
    """``data_prefetch`` block (runtime/prefetch.py): bounded background
    input pipeline — host-stage collate workers + (single-process) device
    double-buffering that overlaps the H2D copy with device compute.

    Env override (sweep ergonomics): ``DS_DATA_PREFETCH`` = 1/0
    force-toggles ``enabled`` after JSON parsing."""

    def __init__(self, param_dict):
        p = param_dict.get(C.DATA_PREFETCH, {}) or {}
        self.enabled = p.get(C.DATA_PREFETCH_ENABLED,
                             C.DATA_PREFETCH_ENABLED_DEFAULT)
        self.depth = int(p.get(C.DATA_PREFETCH_DEPTH,
                               C.DATA_PREFETCH_DEPTH_DEFAULT))
        self.to_device = p.get(C.DATA_PREFETCH_TO_DEVICE,
                               C.DATA_PREFETCH_TO_DEVICE_DEFAULT)
        env = os.environ.get("DS_DATA_PREFETCH")
        if env is not None:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        if self.depth < 1:
            raise DeepSpeedConfigError(
                f"data_prefetch.depth must be >= 1, got {self.depth}")


class DeepSpeedCommOverlapConfig(DeepSpeedConfigObject):
    """``comm_overlap`` block (runtime/comm_overlap.py): bucketed
    gradient-collective overlap — the train step reduces gradients with
    one psum per size-targeted bucket (issued as the backward produces
    each bucket's grads) instead of one GSPMD all-reduce per grad leaf
    at the step tail. The engine falls back (warn once) outside the
    supported envelope: dp > 1, zero stage <= 1, mp/ep/pp == 1, dense
    grads, default batch sharding.

    Env override (sweep ergonomics): ``DS_COMM_OVERLAP`` = 1/0
    force-toggles ``enabled`` after JSON parsing."""

    def __init__(self, param_dict):
        o = param_dict.get(C.COMM_OVERLAP, {}) or {}
        self.enabled = o.get(C.COMM_OVERLAP_ENABLED,
                             C.COMM_OVERLAP_ENABLED_DEFAULT)
        self.bucket_mb = float(o.get(C.COMM_OVERLAP_BUCKET_MB,
                                     C.COMM_OVERLAP_BUCKET_MB_DEFAULT))
        self.scheduler_flags = o.get(C.COMM_OVERLAP_SCHEDULER_FLAGS,
                                     C.COMM_OVERLAP_SCHEDULER_FLAGS_DEFAULT)
        env = os.environ.get("DS_COMM_OVERLAP")
        if env is not None:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        if self.bucket_mb <= 0:
            raise DeepSpeedConfigError(
                f"comm_overlap.bucket_mb must be > 0, got {self.bucket_mb}")

    @property
    def bucket_bytes(self):
        return int(self.bucket_mb * (1 << 20))


class DeepSpeedGuardianConfig(DeepSpeedConfigObject):
    """``guardian`` block (runtime/guardian.py): the self-healing
    anomaly->action policy engine. Subscribes to the telemetry monitors'
    ``on_anomaly`` hooks and maps fired rules to bounded, rate-limited
    actions — emergency checkpoint, rollback-to-last-intact, fp16
    loss-scale rescue, serving admission pause/resume. Every action is
    journaled to ``GUARDIAN.json``.

    Env overrides (sweep ergonomics, after JSON parsing):
    ``DS_GUARDIAN`` = 1/0 force-toggles ``enabled``;
    ``DS_GUARDIAN_JOURNAL`` overrides ``journal_file``;
    ``DS_GUARDIAN_MAX_ROLLBACKS`` and ``DS_GUARDIAN_COOLDOWN_STEPS``
    override the rollback budget and the per-action cooldown."""

    def __init__(self, param_dict):
        g = param_dict.get(C.GUARDIAN, {}) or {}
        self.enabled = g.get(C.GUARDIAN_ENABLED, C.GUARDIAN_ENABLED_DEFAULT)
        self.journal_file = g.get(C.GUARDIAN_JOURNAL_FILE,
                                  C.GUARDIAN_JOURNAL_FILE_DEFAULT)
        self.action_cooldown_steps = int(g.get(
            C.GUARDIAN_ACTION_COOLDOWN, C.GUARDIAN_ACTION_COOLDOWN_DEFAULT))
        self.emergency_checkpoint = g.get(
            C.GUARDIAN_EMERGENCY_CHECKPOINT,
            C.GUARDIAN_EMERGENCY_CHECKPOINT_DEFAULT)
        # [] / absent -> the guardian's built-in warning-tier rule set
        from deepspeed_tpu.runtime.guardian import (DEFAULT_EMERGENCY_RULES,
                                                    DEFAULT_PAUSE_RULES)
        self.emergency_rules = tuple(
            g.get(C.GUARDIAN_EMERGENCY_RULES) or DEFAULT_EMERGENCY_RULES)
        self.max_emergency_checkpoints = int(g.get(
            C.GUARDIAN_MAX_EMERGENCY_CHECKPOINTS,
            C.GUARDIAN_MAX_EMERGENCY_CHECKPOINTS_DEFAULT))
        self.rollback = g.get(C.GUARDIAN_ROLLBACK,
                              C.GUARDIAN_ROLLBACK_DEFAULT)
        self.divergence_window = int(g.get(
            C.GUARDIAN_DIVERGENCE_WINDOW,
            C.GUARDIAN_DIVERGENCE_WINDOW_DEFAULT))
        self.divergence_streak = int(g.get(
            C.GUARDIAN_DIVERGENCE_STREAK,
            C.GUARDIAN_DIVERGENCE_STREAK_DEFAULT))
        self.rollback_cooldown_steps = int(g.get(
            C.GUARDIAN_ROLLBACK_COOLDOWN,
            C.GUARDIAN_ROLLBACK_COOLDOWN_DEFAULT))
        self.max_rollbacks = int(g.get(C.GUARDIAN_MAX_ROLLBACKS,
                                       C.GUARDIAN_MAX_ROLLBACKS_DEFAULT))
        self.fp16_rescue = g.get(C.GUARDIAN_FP16_RESCUE,
                                 C.GUARDIAN_FP16_RESCUE_DEFAULT)
        self.max_fp16_rescues = int(g.get(
            C.GUARDIAN_MAX_FP16_RESCUES,
            C.GUARDIAN_MAX_FP16_RESCUES_DEFAULT))
        self.serving_degrade = g.get(C.GUARDIAN_SERVING_DEGRADE,
                                     C.GUARDIAN_SERVING_DEGRADE_DEFAULT)
        self.pause_rules = tuple(
            g.get(C.GUARDIAN_PAUSE_RULES) or DEFAULT_PAUSE_RULES)
        self.resume_clear_steps = int(g.get(
            C.GUARDIAN_RESUME_CLEAR_STEPS,
            C.GUARDIAN_RESUME_CLEAR_STEPS_DEFAULT))
        env = os.environ.get("DS_GUARDIAN")
        if env is not None:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        env_j = os.environ.get("DS_GUARDIAN_JOURNAL")
        if env_j is not None:
            self.journal_file = env_j
        env_r = os.environ.get("DS_GUARDIAN_MAX_ROLLBACKS")
        if env_r is not None:
            self.max_rollbacks = int(env_r)
        env_c = os.environ.get("DS_GUARDIAN_COOLDOWN_STEPS")
        if env_c is not None:
            self.action_cooldown_steps = int(env_c)
        if self.action_cooldown_steps < 0:
            raise DeepSpeedConfigError(
                f"guardian.{C.GUARDIAN_ACTION_COOLDOWN} must be >= 0, got "
                f"{self.action_cooldown_steps}")
        if self.divergence_streak < 1:
            raise DeepSpeedConfigError(
                f"guardian.{C.GUARDIAN_DIVERGENCE_STREAK} must be >= 1, "
                f"got {self.divergence_streak}")
        if self.divergence_window < 1:
            raise DeepSpeedConfigError(
                f"guardian.{C.GUARDIAN_DIVERGENCE_WINDOW} must be >= 1, "
                f"got {self.divergence_window}")
        if self.max_rollbacks < 0:
            raise DeepSpeedConfigError(
                f"guardian.{C.GUARDIAN_MAX_ROLLBACKS} must be >= 0, got "
                f"{self.max_rollbacks}")
        if self.rollback_cooldown_steps < 1:
            # a 0 cooldown would let two consecutive divergent steps
            # rollback-loop against the same intact tag
            raise DeepSpeedConfigError(
                f"guardian.{C.GUARDIAN_ROLLBACK_COOLDOWN} must be >= 1, "
                f"got {self.rollback_cooldown_steps}")
        if self.resume_clear_steps < 1:
            raise DeepSpeedConfigError(
                f"guardian.{C.GUARDIAN_RESUME_CLEAR_STEPS} must be >= 1, "
                f"got {self.resume_clear_steps}")


class DeepSpeedServingObservabilityConfig(DeepSpeedConfigObject):
    """``serving.observability`` sub-block
    (telemetry/serving_observatory.py): per-request lifecycle timelines
    + per-slot Chrome-trace lanes, the slot-step attribution ledger
    (decode_useful/cached_prefill/prefill/recompute/frozen/idle, sums to
    ``steps x max_batch x decode_steps`` by construction), and windowed
    SLO rules escalating warn-once -> throttled ``SERVING_HEALTH.json``
    -> trace flush.

    Env override (sweep ergonomics): ``DS_SERVING_OBS`` = 1/0
    force-toggles ``enabled`` after JSON parsing."""

    def __init__(self, serving_dict):
        o = serving_dict.get(C.SERVING_OBSERVABILITY, {}) or {}
        self.enabled = o.get(C.SERVING_OBS_ENABLED,
                             C.SERVING_OBS_ENABLED_DEFAULT)
        self.window = int(o.get(C.SERVING_OBS_WINDOW,
                                C.SERVING_OBS_WINDOW_DEFAULT))
        self.warmup_windows = int(o.get(C.SERVING_OBS_WARMUP,
                                        C.SERVING_OBS_WARMUP_DEFAULT))
        self.ttft_slo_ms = float(o.get(C.SERVING_OBS_TTFT_SLO_MS,
                                       C.SERVING_OBS_TTFT_SLO_MS_DEFAULT))
        self.ttft_breach_frac = float(
            o.get(C.SERVING_OBS_TTFT_BREACH_FRAC,
                  C.SERVING_OBS_TTFT_BREACH_FRAC_DEFAULT))
        self.queue_growth_windows = int(
            o.get(C.SERVING_OBS_QUEUE_GROWTH_WINDOWS,
                  C.SERVING_OBS_QUEUE_GROWTH_WINDOWS_DEFAULT))
        self.preemption_thrash = int(
            o.get(C.SERVING_OBS_PREEMPTION_THRASH,
                  C.SERVING_OBS_PREEMPTION_THRASH_DEFAULT))
        self.no_progress_steps = int(
            o.get(C.SERVING_OBS_NO_PROGRESS_STEPS,
                  C.SERVING_OBS_NO_PROGRESS_STEPS_DEFAULT))
        self.timeline_ring = int(o.get(C.SERVING_OBS_TIMELINE_RING,
                                       C.SERVING_OBS_TIMELINE_RING_DEFAULT))
        self.window_ring = int(o.get(C.SERVING_OBS_WINDOW_RING,
                                     C.SERVING_OBS_WINDOW_RING_DEFAULT))
        self.trace_lanes = o.get(C.SERVING_OBS_TRACE_LANES,
                                 C.SERVING_OBS_TRACE_LANES_DEFAULT)
        self.snapshot_file = o.get(C.SERVING_OBS_SNAPSHOT_FILE,
                                   C.SERVING_OBS_SNAPSHOT_FILE_DEFAULT)
        env = os.environ.get("DS_SERVING_OBS")
        if env is not None:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        if self.window < 1:
            raise DeepSpeedConfigError(
                f"serving.observability.window must be >= 1, got "
                f"{self.window}")
        if self.warmup_windows < 0:
            raise DeepSpeedConfigError(
                f"serving.observability.warmup_windows must be >= 0, got "
                f"{self.warmup_windows}")
        if not 0.0 < self.ttft_breach_frac <= 1.0:
            raise DeepSpeedConfigError(
                f"serving.observability.ttft_breach_frac must be in "
                f"(0, 1], got {self.ttft_breach_frac}")
        if self.no_progress_steps < 1:
            raise DeepSpeedConfigError(
                f"serving.observability.no_progress_steps must be >= 1, "
                f"got {self.no_progress_steps}")
        if self.queue_growth_windows < 1:
            raise DeepSpeedConfigError(
                f"serving.observability.queue_growth_windows must be "
                f">= 1, got {self.queue_growth_windows}")
        if self.preemption_thrash < 1:
            # the rule is `window preemptions >= threshold`, and every
            # window has >= 0 preemptions — a 0 threshold would fire the
            # thrash rule on every post-warmup window forever
            raise DeepSpeedConfigError(
                f"serving.observability.preemption_thrash must be >= 1 "
                f"(disable rules with enabled=false), got "
                f"{self.preemption_thrash}")
        if self.ttft_slo_ms <= 0:
            raise DeepSpeedConfigError(
                f"serving.observability.ttft_slo_ms must be > 0, got "
                f"{self.ttft_slo_ms}")


class DeepSpeedServingPrefixCacheConfig(DeepSpeedConfigObject):
    """``serving.prefix_cache`` sub-block (serving/kv_cache.py
    ``PrefixCache``): content-addressed LRU index of FULL KV blocks,
    mapped read-only at admission with copy-on-write forks on divergent
    writes. ``capacity_blocks`` 0 leaves the index uncapped (it is still
    bounded by the block pool — every resident entry holds exactly one
    allocator reference, and refcount-1 entries are reclaimed before any
    preemption fires).

    Env override (sweep ergonomics): ``DS_SERVING_PREFIX_CACHE`` = 1/0
    force-toggles ``enabled``."""

    def __init__(self, serving_dict):
        p = serving_dict.get(C.SERVING_PREFIX_CACHE, {}) or {}
        self.enabled = p.get(C.SERVING_PREFIX_ENABLED,
                             C.SERVING_PREFIX_ENABLED_DEFAULT)
        self.capacity_blocks = int(
            p.get(C.SERVING_PREFIX_CAPACITY_BLOCKS,
                  C.SERVING_PREFIX_CAPACITY_BLOCKS_DEFAULT))
        env = os.environ.get("DS_SERVING_PREFIX_CACHE")
        if env is not None:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        if self.capacity_blocks < 0:
            raise DeepSpeedConfigError(
                f"serving.prefix_cache.capacity_blocks must be >= 0 "
                f"(0 = uncapped), got {self.capacity_blocks}")


class DeepSpeedServingSpeculativeConfig(DeepSpeedConfigObject):
    """``serving.speculative`` sub-block (serving/speculative.py):
    draft/verify speculative decoding over the paged KV. The default
    draft is the truncated-layer self-draft — ``draft_layers`` 0 picks
    ``n_layer // 4`` (floor 1) at engine construction; ``draft_model``
    null means self-draft (an explicit small model is handed to the
    engine programmatically as ``draft_params``). ``acceptance``
    "exact" keeps outputs bit-exact vs the non-speculative engine;
    "typical" trades parity on sampled slots for acceptance.
    ``acceptance_floor`` arms the observatory's ``speculation_waste``
    rule.

    Env override (sweep ergonomics): ``DS_SERVING_SPEC`` = 1/0
    force-toggles ``enabled``."""

    def __init__(self, serving_dict):
        sp = serving_dict.get(C.SERVING_SPECULATIVE, {}) or {}
        self.enabled = sp.get(C.SERVING_SPEC_ENABLED,
                              C.SERVING_SPEC_ENABLED_DEFAULT)
        self.k = int(sp.get(C.SERVING_SPEC_K, C.SERVING_SPEC_K_DEFAULT))
        self.draft_layers = int(sp.get(C.SERVING_SPEC_DRAFT_LAYERS,
                                       C.SERVING_SPEC_DRAFT_LAYERS_DEFAULT))
        self.draft_model = sp.get(C.SERVING_SPEC_DRAFT_MODEL,
                                  C.SERVING_SPEC_DRAFT_MODEL_DEFAULT)
        self.acceptance = sp.get(C.SERVING_SPEC_ACCEPTANCE,
                                 C.SERVING_SPEC_ACCEPTANCE_DEFAULT)
        self.typical_threshold = float(
            sp.get(C.SERVING_SPEC_TYPICAL_THRESHOLD,
                   C.SERVING_SPEC_TYPICAL_THRESHOLD_DEFAULT))
        self.acceptance_floor = float(
            sp.get(C.SERVING_SPEC_ACCEPTANCE_FLOOR,
                   C.SERVING_SPEC_ACCEPTANCE_FLOOR_DEFAULT))
        env = os.environ.get("DS_SERVING_SPEC")
        if env is not None:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        if self.k < 1:
            raise DeepSpeedConfigError(
                f"serving.speculative.k must be >= 1, got {self.k}")
        if self.draft_layers < 0:
            raise DeepSpeedConfigError(
                f"serving.speculative.draft_layers must be >= 0 "
                f"(0 = auto), got {self.draft_layers}")
        if self.acceptance not in ("exact", "typical"):
            raise DeepSpeedConfigError(
                f"serving.speculative.acceptance must be 'exact' or "
                f"'typical', got {self.acceptance!r}")
        if not 0.0 < self.typical_threshold <= 1.0:
            raise DeepSpeedConfigError(
                f"serving.speculative.typical_threshold must be in "
                f"(0, 1], got {self.typical_threshold}")
        if not 0.0 <= self.acceptance_floor <= 1.0:
            raise DeepSpeedConfigError(
                f"serving.speculative.acceptance_floor must be in "
                f"[0, 1], got {self.acceptance_floor}")
        if self.draft_model is not None and not isinstance(
                self.draft_model, str):
            raise DeepSpeedConfigError(
                f"serving.speculative.draft_model must be null "
                f"(self-draft) or a string tag, got "
                f"{type(self.draft_model).__name__}")


class DeepSpeedServingRouterConfig(DeepSpeedConfigObject):
    """``serving.router`` sub-block (serving/router.py
    ``ServingRouter``): admission scoring weights over per-replica
    signals (queue depth, KV occupancy, recent SLO breaches) plus
    prefix-affinity. ``breach_penalty`` dominates the load terms by
    design — a breaching replica only receives work when every replica
    is breaching (failover, not permanent blacklist)."""

    def __init__(self, serving_dict):
        r = serving_dict.get(C.SERVING_ROUTER, {}) or {}
        self.replicas = int(r.get(C.SERVING_ROUTER_REPLICAS,
                                  C.SERVING_ROUTER_REPLICAS_DEFAULT))
        self.affinity_weight = float(
            r.get(C.SERVING_ROUTER_AFFINITY_WEIGHT,
                  C.SERVING_ROUTER_AFFINITY_WEIGHT_DEFAULT))
        self.queue_weight = float(
            r.get(C.SERVING_ROUTER_QUEUE_WEIGHT,
                  C.SERVING_ROUTER_QUEUE_WEIGHT_DEFAULT))
        self.occupancy_weight = float(
            r.get(C.SERVING_ROUTER_OCCUPANCY_WEIGHT,
                  C.SERVING_ROUTER_OCCUPANCY_WEIGHT_DEFAULT))
        self.breach_penalty = float(
            r.get(C.SERVING_ROUTER_BREACH_PENALTY,
                  C.SERVING_ROUTER_BREACH_PENALTY_DEFAULT))
        if self.replicas < 1:
            raise DeepSpeedConfigError(
                f"serving.router.replicas must be >= 1, got "
                f"{self.replicas}")
        for name in ("affinity_weight", "queue_weight",
                     "occupancy_weight", "breach_penalty"):
            if getattr(self, name) < 0:
                raise DeepSpeedConfigError(
                    f"serving.router.{name} must be >= 0, got "
                    f"{getattr(self, name)}")


class DeepSpeedServingConfig(DeepSpeedConfigObject):
    """``serving`` block (serving/): continuous-batching inference server
    over a paged KV cache. ``num_blocks`` 0 auto-sizes the pool so the
    full batch at full length fits (preemption-free); a smaller explicit
    pool trades HBM for preemption-by-eviction under pressure.
    ``max_model_len`` 0 defers to the served model's ``n_positions``.

    Env overrides (sweep ergonomics): ``DS_SERVING_MAX_BATCH`` /
    ``DS_SERVING_BLOCK_SIZE`` / ``DS_SERVING_PREFILL_CHUNK``."""

    def __init__(self, param_dict):
        s = param_dict.get(C.SERVING, {}) or {}
        self.block_size = int(s.get(C.SERVING_BLOCK_SIZE,
                                    C.SERVING_BLOCK_SIZE_DEFAULT))
        self.num_blocks = int(s.get(C.SERVING_NUM_BLOCKS,
                                    C.SERVING_NUM_BLOCKS_DEFAULT))
        self.max_batch = int(s.get(C.SERVING_MAX_BATCH,
                                   C.SERVING_MAX_BATCH_DEFAULT))
        self.prefill_chunk = int(s.get(C.SERVING_PREFILL_CHUNK,
                                       C.SERVING_PREFILL_CHUNK_DEFAULT))
        self.max_model_len = int(s.get(C.SERVING_MAX_MODEL_LEN,
                                       C.SERVING_MAX_MODEL_LEN_DEFAULT))
        self.attention_impl = s.get(C.SERVING_ATTENTION_IMPL,
                                    C.SERVING_ATTENTION_IMPL_DEFAULT)
        self.decode_steps = int(s.get(C.SERVING_DECODE_STEPS,
                                      C.SERVING_DECODE_STEPS_DEFAULT))
        self.observability = DeepSpeedServingObservabilityConfig(s)
        self.prefix_cache = DeepSpeedServingPrefixCacheConfig(s)
        self.router = DeepSpeedServingRouterConfig(s)
        self.speculative = DeepSpeedServingSpeculativeConfig(s)
        for env, attr in (("DS_SERVING_MAX_BATCH", "max_batch"),
                          ("DS_SERVING_BLOCK_SIZE", "block_size"),
                          ("DS_SERVING_PREFILL_CHUNK", "prefill_chunk")):
            val = os.environ.get(env)
            if val is not None:
                setattr(self, attr, int(val))
        if self.block_size < 1:
            raise DeepSpeedConfigError(
                f"serving.block_size must be >= 1, got {self.block_size}")
        if self.max_batch < 1:
            raise DeepSpeedConfigError(
                f"serving.max_batch must be >= 1, got {self.max_batch}")
        if self.prefill_chunk < 1:
            raise DeepSpeedConfigError(
                f"serving.prefill_chunk must be >= 1, got "
                f"{self.prefill_chunk}")
        if self.num_blocks < 0 or self.num_blocks == 1:
            raise DeepSpeedConfigError(
                f"serving.num_blocks must be 0 (auto) or >= 2 (1 usable "
                f"+ the reserved null block), got {self.num_blocks}")
        if self.attention_impl not in ("paged", "gather"):
            raise DeepSpeedConfigError(
                f"serving.attention_impl must be 'paged' or 'gather', "
                f"got {self.attention_impl!r}")
        if self.decode_steps < 1:
            raise DeepSpeedConfigError(
                f"serving.decode_steps must be >= 1, got "
                f"{self.decode_steps}")


class DeepSpeedAutotuningConfig(DeepSpeedConfigObject):
    """``autotuning`` block (autotuning/tune.py): goodput-driven
    two-stage config search — compile-time pruning of the declared
    space, then measured probes of the top-K survivors scored by the
    goodput ledger. The block carries the tuner's defaults;
    ``GoodputTuner.from_config`` / the ``python -m
    deepspeed_tpu.autotuning.tune`` CLI consume it (the engine itself
    never autotunes mid-run).

    Env overrides (sweep ergonomics): ``DS_AUTOTUNING`` = 1/0
    force-toggles ``enabled``; ``DS_AUTOTUNING_TOP_K`` overrides
    ``top_k``; ``DS_AUTOTUNING_REPORT`` overrides ``report_file``."""

    def __init__(self, param_dict):
        a = param_dict.get(C.AUTOTUNING, {}) or {}
        self.enabled = a.get(C.AUTOTUNING_ENABLED,
                             C.AUTOTUNING_ENABLED_DEFAULT)
        self.metric = a.get(C.AUTOTUNING_METRIC, C.AUTOTUNING_METRIC_DEFAULT)
        self.top_k = int(a.get(C.AUTOTUNING_TOP_K,
                               C.AUTOTUNING_TOP_K_DEFAULT))
        self.probe_steps = int(a.get(C.AUTOTUNING_PROBE_STEPS,
                                     C.AUTOTUNING_PROBE_STEPS_DEFAULT))
        self.probe_warmup_steps = int(a.get(
            C.AUTOTUNING_PROBE_WARMUP, C.AUTOTUNING_PROBE_WARMUP_DEFAULT))
        self.memory_headroom = float(a.get(
            C.AUTOTUNING_MEMORY_HEADROOM,
            C.AUTOTUNING_MEMORY_HEADROOM_DEFAULT))
        self.hbm_budget_gb = float(a.get(C.AUTOTUNING_HBM_BUDGET_GB,
                                         C.AUTOTUNING_HBM_BUDGET_GB_DEFAULT))
        self.report_file = a.get(C.AUTOTUNING_REPORT_FILE,
                                 C.AUTOTUNING_REPORT_FILE_DEFAULT)
        self.results_dir = a.get(C.AUTOTUNING_RESULTS_DIR,
                                 C.AUTOTUNING_RESULTS_DIR_DEFAULT)
        self.seed = int(a.get(C.AUTOTUNING_SEED, C.AUTOTUNING_SEED_DEFAULT))
        self.space = a.get(C.AUTOTUNING_SPACE, C.AUTOTUNING_SPACE_DEFAULT)
        env = os.environ.get("DS_AUTOTUNING")
        if env is not None:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        env_k = os.environ.get("DS_AUTOTUNING_TOP_K")
        if env_k:
            self.top_k = int(env_k)
        env_r = os.environ.get("DS_AUTOTUNING_REPORT")
        if env_r:
            self.report_file = env_r
        if self.metric not in ("goodput", "step_time"):
            raise DeepSpeedConfigError(
                f"autotuning.metric must be 'goodput' or 'step_time', "
                f"got {self.metric!r}")
        if self.top_k < 1:
            raise DeepSpeedConfigError(
                f"autotuning.top_k must be >= 1, got {self.top_k}")
        if self.probe_steps < 1:
            raise DeepSpeedConfigError(
                f"autotuning.probe_steps must be >= 1, got "
                f"{self.probe_steps}")
        if self.probe_warmup_steps < 0:
            raise DeepSpeedConfigError(
                f"autotuning.probe_warmup_steps must be >= 0, got "
                f"{self.probe_warmup_steps}")
        if not 0.0 < self.memory_headroom <= 1.0:
            raise DeepSpeedConfigError(
                f"autotuning.memory_headroom must be in (0, 1], got "
                f"{self.memory_headroom}")
        if self.hbm_budget_gb < 0:
            raise DeepSpeedConfigError(
                f"autotuning.hbm_budget_gb must be >= 0 (0 = detect), "
                f"got {self.hbm_budget_gb}")
        if self.space is not None and (
                not isinstance(self.space, dict)
                or not all(isinstance(v, list) and v
                           for v in self.space.values())):
            raise DeepSpeedConfigError(
                "autotuning.space must map each dimension name to a "
                "non-empty list of values")


class DeepSpeedFlopsProfilerConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        fp = param_dict.get(C.FLOPS_PROFILER, {}) or {}
        self.enabled = fp.get(C.FLOPS_PROFILER_ENABLED, C.FLOPS_PROFILER_ENABLED_DEFAULT)
        self.profile_step = fp.get(C.FLOPS_PROFILER_PROFILE_STEP,
                                   C.FLOPS_PROFILER_PROFILE_STEP_DEFAULT)
        self.module_depth = fp.get(C.FLOPS_PROFILER_MODULE_DEPTH,
                                   C.FLOPS_PROFILER_MODULE_DEPTH_DEFAULT)
        self.top_modules = fp.get(C.FLOPS_PROFILER_TOP_MODULES,
                                  C.FLOPS_PROFILER_TOP_MODULES_DEFAULT)
        self.detailed = fp.get(C.FLOPS_PROFILER_DETAILED, C.FLOPS_PROFILER_DETAILED_DEFAULT)
        self.output_file = fp.get(C.FLOPS_PROFILER_OUTPUT_FILE,
                                  C.FLOPS_PROFILER_OUTPUT_FILE_DEFAULT)


class DeepSpeedActivationCheckpointingConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        ac = param_dict.get(C.ACTIVATION_CHECKPOINTING, {}) or {}
        self.partition_activations = ac.get(C.ACT_CHKPT_PARTITION_ACTIVATIONS,
                                            C.ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT)
        self.number_checkpoints = ac.get(C.ACT_CHKPT_NUMBER_CHECKPOINTS,
                                         C.ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT)
        self.contiguous_memory_optimization = ac.get(
            C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION,
            C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)
        self.synchronize_checkpoint_boundary = ac.get(
            C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY,
            C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)
        self.profile = ac.get(C.ACT_CHKPT_PROFILE, C.ACT_CHKPT_PROFILE_DEFAULT)
        self.cpu_checkpointing = ac.get(C.ACT_CHKPT_CPU_CHECKPOINTING,
                                        C.ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT)


class DeepSpeedAIOConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        aio = param_dict.get(C.AIO, {}) or {}
        self.block_size = aio.get(C.AIO_BLOCK_SIZE, C.AIO_BLOCK_SIZE_DEFAULT)
        self.queue_depth = aio.get(C.AIO_QUEUE_DEPTH, C.AIO_QUEUE_DEPTH_DEFAULT)
        self.thread_count = aio.get(C.AIO_THREAD_COUNT, C.AIO_THREAD_COUNT_DEFAULT)
        self.single_submit = aio.get(C.AIO_SINGLE_SUBMIT, C.AIO_SINGLE_SUBMIT_DEFAULT)
        self.overlap_events = aio.get(C.AIO_OVERLAP_EVENTS, C.AIO_OVERLAP_EVENTS_DEFAULT)


class DeepSpeedEigenvalueConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        ev = param_dict.get(C.EIGENVALUE, {}) or {}
        self.enabled = ev.get(C.EIGENVALUE_ENABLED, C.EIGENVALUE_ENABLED_DEFAULT)
        self.verbose = ev.get(C.EIGENVALUE_VERBOSE, C.EIGENVALUE_VERBOSE_DEFAULT)
        self.max_iter = ev.get(C.EIGENVALUE_MAX_ITER, C.EIGENVALUE_MAX_ITER_DEFAULT)
        self.tol = ev.get(C.EIGENVALUE_TOL, C.EIGENVALUE_TOL_DEFAULT)
        self.stability = ev.get(C.EIGENVALUE_STABILITY, C.EIGENVALUE_STABILITY_DEFAULT)
        self.gas_boundary_resolution = ev.get(
            C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION,
            C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT)
        self.layer_name = ev.get(C.EIGENVALUE_LAYER_NAME, C.EIGENVALUE_LAYER_NAME_DEFAULT)
        self.layer_num = ev.get(C.EIGENVALUE_LAYER_NUM, C.EIGENVALUE_LAYER_NUM_DEFAULT)


class DeepSpeedPLDConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        pld = param_dict.get(C.PROGRESSIVE_LAYER_DROP, {}) or {}
        self.enabled = pld.get(C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT)
        self.theta = pld.get(C.PLD_THETA, C.PLD_THETA_DEFAULT)
        self.gamma = pld.get(C.PLD_GAMMA, C.PLD_GAMMA_DEFAULT)


class DeepSpeedCurriculumConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        cl = param_dict.get(C.CURRICULUM_LEARNING, {}) or {}
        self.enabled = cl.get(C.CURRICULUM_ENABLED, C.CURRICULUM_ENABLED_DEFAULT)
        self.params = {k: v for k, v in cl.items() if k != C.CURRICULUM_ENABLED}


class DeepSpeedQuantizeTrainingConfig(DeepSpeedConfigObject):
    """MoQ quantize-aware-training block (reference config.py:231-344)."""

    def __init__(self, param_dict):
        qt = param_dict.get(C.QUANTIZE_TRAINING, {}) or {}
        self.enabled = qt.get(C.QUANTIZE_TRAINING_ENABLED,
                              C.QUANTIZE_TRAINING_ENABLED_DEFAULT)
        bits = qt.get(C.QUANTIZE_BITS, {}) or {}
        self.start_bits = bits.get(C.START_BITS, C.START_BITS_DEFAULT)
        self.target_bits = bits.get(C.TARGET_BITS, C.TARGET_BITS_DEFAULT)
        sched = qt.get(C.QUANTIZE_SCHEDULE, {}) or {}
        self.quantize_period = sched.get(C.QUANTIZE_PERIOD, C.QUANTIZE_PERIOD_DEFAULT)
        self.schedule_offset = sched.get(C.SCHEDULE_OFFSET, C.SCHEDULE_OFFSET_DEFAULT)
        self.quantize_groups = qt.get(C.QUANTIZE_GROUPS, C.QUANTIZE_GROUPS_DEFAULT)
        self.quantize_verbose = qt.get(C.QUANTIZE_VERBOSE, C.QUANTIZE_VERBOSE_DEFAULT)
        self.quantizer_kernel = qt.get(C.QUANTIZER_KERNEL, C.QUANTIZER_KERNEL_DEFAULT)
        self.quantize_change_ratio = qt.get(C.QUANTIZE_CHANGE_RATIO,
                                            C.QUANTIZE_CHANGE_RATIO_DEFAULT)
        qtype = qt.get(C.QUANTIZE_TYPE, C.QUANTIZE_SYMMETRIC)
        self.quantize_type = qtype
        algo = qt.get(C.QUANTIZE_ALGO, {}) or {}
        self.rounding = algo.get(C.QUANTIZE_ROUNDING, "nearest")
        self.stochastic_rounding = self.rounding == "stochastic"
        mixed = qt.get(C.FP16_MIXED_QUANTIZE, {}) or {}
        self.fp16_mixed_quantize = mixed.get("enabled", False)
        self.quantize_offset = mixed.get(C.QUANTIZE_OFFSET, C.QUANTIZE_OFFSET_DEFAULT)


class DeepSpeedPipelineConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        p = param_dict.get(C.PIPELINE, {}) or {}
        self.stages = p.get(C.PIPELINE_STAGES, C.PIPELINE_STAGES_DEFAULT)
        self.partition = p.get(C.PIPELINE_PARTITION, C.PIPELINE_PARTITION_DEFAULT)
        self.seed_layers = p.get(C.PIPELINE_SEED_LAYERS, C.PIPELINE_SEED_LAYERS_DEFAULT)
        self.activation_checkpoint_interval = p.get(
            C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL,
            C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT)


class DeepSpeedConfig:
    """Top-level parsed config (reference DeepSpeedConfig, config.py:789)."""

    def __init__(self, config, mpu=None, data_parallel_size=None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(
                    f"DeepSpeed config file not found: {config}")
            with open(config) as f:
                self._param_dict = json.load(f)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise DeepSpeedConfigError(
                f"Expected a path or dict for the DeepSpeed config, got {type(config)}")

        # Data-parallel world for batch triangulation. Callers pass the real
        # dp degree; default 1 (single device).
        if data_parallel_size is None:
            if mpu is not None:
                data_parallel_size = mpu.get_data_parallel_world_size()
            else:
                data_parallel_size = 1
        self.world_size = data_parallel_size

        self._apply_elasticity(self._param_dict)
        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    def _apply_elasticity(self, pd):
        """When elasticity is enabled, take control of the batch parameters
        before triangulation (reference config.py:813-872): compute the
        elastic (final_batch_size, micro_batch) for this world size and
        override train_batch_size / micro_batch / gas in the param dict."""
        from deepspeed_tpu.elasticity import (compute_elastic_config,
                                              elasticity_enabled,
                                              ensure_immutable_elastic_config)
        from deepspeed_tpu.elasticity.elasticity import (
            ELASTICITY, IGNORE_NON_ELASTIC_BATCH_INFO,
            IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

        if not elasticity_enabled(pd):
            return
        logger.info("DeepSpeed elasticity support enabled")
        final_batch_size, valid_gpus, micro_batch_size = \
            compute_elastic_config(ds_config=pd, world_size=self.world_size)
        elastic_dict = pd[ELASTICITY]

        ensure_immutable_elastic_config(elastic_dict)

        if not elastic_dict.get(IGNORE_NON_ELASTIC_BATCH_INFO,
                                IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT):
            batch_params = [C.TRAIN_BATCH_SIZE,
                            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                            C.GRADIENT_ACCUMULATION_STEPS]
            if any(t in pd for t in batch_params):
                from deepspeed_tpu.elasticity import ElasticityConfigError
                raise ElasticityConfigError(
                    "One or more batch related parameters were found in your "
                    f"ds_config ({C.TRAIN_BATCH_SIZE}, "
                    f"{C.TRAIN_MICRO_BATCH_SIZE_PER_GPU}, and/or "
                    f"{C.GRADIENT_ACCUMULATION_STEPS}). These parameters "
                    "*will not be used* since elastic training is enabled, "
                    "which takes control of these parameters. If you want to "
                    "suppress this error (the parameters will be silently "
                    f"ignored) please set '{IGNORE_NON_ELASTIC_BATCH_INFO}'"
                    ":true in your elasticity config.")

        gradient_accu_steps = final_batch_size // (micro_batch_size *
                                                   self.world_size)
        for key, new in ((C.TRAIN_BATCH_SIZE, final_batch_size),
                         (C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, micro_batch_size),
                         (C.GRADIENT_ACCUMULATION_STEPS, gradient_accu_steps)):
            if key in pd:
                logger.warning(
                    f"[Elasticity] overriding {key}: {pd[key]} -> {new}")
            pd[key] = new
        logger.info(f"[Elasticity] valid chip counts: {valid_gpus}")
        self.elastic_valid_world_sizes = valid_gpus

    # -- parsing ------------------------------------------------------------

    def _initialize_params(self, pd):
        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE, C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = pd.get(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = pd.get(
            C.GRADIENT_ACCUMULATION_STEPS, C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = pd.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)

        self.disable_allgather = pd.get(C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)
        self.communication_data_type = pd.get(C.COMMUNICATION_DATA_TYPE,
                                              C.COMMUNICATION_DATA_TYPE_DEFAULT)
        self.prescale_gradients = pd.get(C.PRESCALE_GRADIENTS,
                                         C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = pd.get(C.GRADIENT_PREDIVIDE_FACTOR,
                                                C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = pd.get(C.SPARSE_GRADIENTS,
                                               C.SPARSE_GRADIENTS_DEFAULT)

        self.zero_config = DeepSpeedZeroConfig.from_dict(pd)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.fp16 = DeepSpeedFP16Config(pd)
        self.fp16_enabled = self.fp16.enabled
        self.bf16 = DeepSpeedBF16Config(pd)
        self.bfloat16_enabled = self.bf16.enabled
        self.fp16_master_weights_and_gradients = self.fp16.master_weights_and_grads
        self.amp_enabled = (pd.get(C.AMP, {}) or {}).get(C.AMP_ENABLED,
                                                         C.AMP_ENABLED_DEFAULT)
        self.amp_params = {k: v for k, v in (pd.get(C.AMP, {}) or {}).items()
                           if k != C.AMP_ENABLED}
        self.loss_scale = self.fp16.loss_scale
        self.initial_dynamic_scale = 2 ** self.fp16.initial_scale_power
        self.dynamic_loss_scale_args = {
            "init_scale": 2 ** self.fp16.initial_scale_power,
            "scale_window": self.fp16.loss_scale_window,
            "min_scale": self.fp16.min_loss_scale,
            "delayed_shift": self.fp16.hysteresis,
        }

        self.gradient_clipping = pd.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)

        optimizer = pd.get(C.OPTIMIZER, {}) or {}
        self.optimizer_name = optimizer.get(C.TYPE, C.OPTIMIZER_TYPE_DEFAULT)
        if self.optimizer_name is not None and \
                self.optimizer_name.lower() in DEEPSPEED_OPTIMIZERS:
            self.optimizer_name = self.optimizer_name.lower()
        self.optimizer_params = optimizer.get(C.OPTIMIZER_PARAMS, None)
        self.optimizer_legacy_fusion = optimizer.get(C.LEGACY_FUSION,
                                                     C.LEGACY_FUSION_DEFAULT)
        self.zero_allow_untested_optimizer = pd.get(
            C.ZERO_ALLOW_UNTESTED_OPTIMIZER, C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)

        scheduler = pd.get(C.SCHEDULER, {}) or {}
        self.scheduler_name = scheduler.get(C.TYPE, C.SCHEDULER_TYPE_DEFAULT)
        self.scheduler_params = scheduler.get(C.SCHEDULER_PARAMS, None)

        self.wall_clock_breakdown = pd.get(C.WALL_CLOCK_BREAKDOWN,
                                           C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = pd.get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)
        self.tensorboard = DeepSpeedTensorboardConfig(pd)
        self.tensorboard_enabled = self.tensorboard.enabled
        self.tensorboard_output_path = self.tensorboard.output_path
        self.tensorboard_job_name = self.tensorboard.job_name
        self.telemetry = DeepSpeedTelemetryConfig(pd)
        self.telemetry_enabled = self.telemetry.enabled

        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig(pd)
        self.activation_checkpointing_config = DeepSpeedActivationCheckpointingConfig(pd)
        self.aio_config = DeepSpeedAIOConfig(pd)
        self.eigenvalue_config = DeepSpeedEigenvalueConfig(pd)
        self.eigenvalue_enabled = self.eigenvalue_config.enabled
        self.pld_config = DeepSpeedPLDConfig(pd)
        self.pld_enabled = self.pld_config.enabled
        self.curriculum_config = DeepSpeedCurriculumConfig(pd)
        self.curriculum_enabled = self.curriculum_config.enabled
        self.quantize_training_config = DeepSpeedQuantizeTrainingConfig(pd)
        self.quantize_training_enabled = self.quantize_training_config.enabled
        self.pipeline_config = DeepSpeedPipelineConfig(pd)
        self.pipeline = pd.get(C.PIPELINE, {}) or {}

        self.sparse_attention = pd.get(C.SPARSE_ATTENTION, None)

        ckpt = pd.get(C.CHECKPOINT, {}) or {}
        self.checkpoint_tag_validation_mode = ckpt.get(
            C.CHECKPOINT_TAG_VALIDATION, C.CHECKPOINT_TAG_VALIDATION_DEFAULT)
        self.checkpoint_tag_validation_enabled = \
            self.checkpoint_tag_validation_mode != "Ignore"
        self.checkpoint_tag_validation_fail = \
            self.checkpoint_tag_validation_mode == "Fail"
        self.load_universal_checkpoint = ckpt.get(C.LOAD_UNIVERSAL_CHECKPOINT,
                                                  C.LOAD_UNIVERSAL_CHECKPOINT_DEFAULT)
        self.checkpoint_async_save = bool(ckpt.get(
            C.CHECKPOINT_ASYNC_SAVE, C.CHECKPOINT_ASYNC_SAVE_DEFAULT))
        self.checkpoint_fallback = bool(ckpt.get(
            C.CHECKPOINT_FALLBACK, C.CHECKPOINT_FALLBACK_DEFAULT))
        self.checkpoint_wait_timeout_s = float(ckpt.get(
            C.CHECKPOINT_WAIT_TIMEOUT, C.CHECKPOINT_WAIT_TIMEOUT_DEFAULT))
        self.checkpoint_persist_retries = int(ckpt.get(
            C.CHECKPOINT_PERSIST_RETRIES,
            C.CHECKPOINT_PERSIST_RETRIES_DEFAULT))
        self.checkpoint_persist_backoff_s = float(ckpt.get(
            C.CHECKPOINT_PERSIST_BACKOFF_S,
            C.CHECKPOINT_PERSIST_BACKOFF_S_DEFAULT))
        env_retries = os.environ.get("DS_CHECKPOINT_PERSIST_RETRIES")
        if env_retries is not None:
            self.checkpoint_persist_retries = int(env_retries)
        env_async = os.environ.get("DS_CHECKPOINT_ASYNC_SAVE")
        if env_async is not None:
            self.checkpoint_async_save = env_async.lower() in (
                "1", "true", "yes", "on")
        env_fb = os.environ.get("DS_CHECKPOINT_FALLBACK")
        if env_fb is not None:
            self.checkpoint_fallback = env_fb.lower() in (
                "1", "true", "yes", "on")
        if self.checkpoint_wait_timeout_s <= 0:
            raise DeepSpeedConfigError(
                f"checkpoint.{C.CHECKPOINT_WAIT_TIMEOUT} must be > 0, got "
                f"{self.checkpoint_wait_timeout_s}")
        if self.checkpoint_persist_retries < 0:
            raise DeepSpeedConfigError(
                f"checkpoint.{C.CHECKPOINT_PERSIST_RETRIES} must be >= 0, "
                f"got {self.checkpoint_persist_retries}")
        if self.checkpoint_persist_backoff_s < 0:
            raise DeepSpeedConfigError(
                f"checkpoint.{C.CHECKPOINT_PERSIST_BACKOFF_S} must be "
                f">= 0, got {self.checkpoint_persist_backoff_s}")

        self.elasticity_enabled = bool((pd.get("elasticity", {}) or {}).get(
            "enabled", False))
        self.elasticity_params = pd.get("elasticity", {}) or {}

        # None = not configured. The engine's loader then defaults to
        # drop_last=True (a ragged final batch is a new shape, and under
        # jit a new shape is a recompile) — the reference's False default
        # is an eager-mode luxury; an EXPLICIT false is still honored.
        self.dataloader_drop_last = pd.get(C.DATALOADER_DROP_LAST, None)
        self.data_prefetch = DeepSpeedDataPrefetchConfig(pd)
        self.comm_overlap = DeepSpeedCommOverlapConfig(pd)
        self.guardian = DeepSpeedGuardianConfig(pd)
        self.serving = DeepSpeedServingConfig(pd)
        self.autotuning = DeepSpeedAutotuningConfig(pd)
        self.autotuning_enabled = self.autotuning.enabled
        self.gradient_accumulation_dtype = pd.get(C.GRADIENT_ACCUMULATION_FORMAT, None)

    # -- batch triangulation (reference config.py:926-1004) -----------------

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        if train_batch <= 0:
            raise DeepSpeedConfigError(f"Train batch size: {train_batch} has to be greater than 0")
        if micro_batch <= 0:
            raise DeepSpeedConfigError(f"Micro batch size per gpu: {micro_batch} has to be greater than 0")
        if grad_acc <= 0:
            raise DeepSpeedConfigError(f"Gradient accumulation steps: {grad_acc} has to be greater than 0")
        if train_batch != micro_batch * grad_acc * self.world_size:
            raise DeepSpeedConfigError(
                f"Check batch related parameters. train_batch_size is not equal "
                f"to micro_batch_per_gpu * gradient_acc_step * world_size "
                f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _set_batch_related_parameters(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        # All three provided: verify below. Otherwise derive missing ones.
        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * self.world_size
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

    def _configure_train_batch_size(self):
        self._set_batch_related_parameters()
        self._batch_assertion()

    # -- sanity checks (reference config.py:1033-1090) -----------------------

    def _do_sanity_check(self):
        if self.optimizer_name is not None and self.zero_enabled:
            if (self.optimizer_name not in DEEPSPEED_OPTIMIZERS
                    and not self.zero_allow_untested_optimizer):
                raise DeepSpeedConfigError(
                    f"ZeRO is only supported with DeepSpeed optimizers "
                    f"{DEEPSPEED_OPTIMIZERS}; set zero_allow_untested_optimizer "
                    f"to force-enable '{self.optimizer_name}'")
        if self.fp16_enabled and self.bfloat16_enabled:
            raise DeepSpeedConfigError("fp16 and bf16 modes are mutually exclusive")
        if self.fp16_master_weights_and_gradients:
            raise DeepSpeedConfigError(
                "fp16_master_weights_and_grads halves HOST memory for the "
                "cpu-offload masters; the TPU offload engine keeps fp32 "
                "masters (host RAM is not the binding constraint on TPU "
                "hosts, and the AVX CPU-Adam operates on fp32 buffers) — "
                "remove the key")
        # -- no-silent-no-op rule (same as the pipeline/offload dispatch in
        # deepspeed_tpu/__init__.py): keys whose reference mechanism has no
        # TPU/XLA counterpart are REJECTED when set off-default, never
        # silently accepted.
        if self.amp_enabled:
            raise DeepSpeedConfigError(
                "amp.enabled: NVIDIA apex AMP has no TPU counterpart; use "
                "the native mixed-precision blocks instead — bf16 "
                "{enabled: true} (preferred on TPU) or fp16 {enabled: true}")
        if self.prescale_gradients or self.gradient_predivide_factor != 1.0:
            raise DeepSpeedConfigError(
                "prescale_gradients/gradient_predivide_factor rescale "
                "gradients around an explicit NCCL allreduce to dodge fp16 "
                "overflow; under XLA the data-parallel reduction is fused "
                "into the compiled step with fp32 accumulation, so there "
                "is no allreduce boundary to pre-scale — remove the key "
                "(fp16 overflow is handled by the dynamic loss scaler)")
        if self.disable_allgather:
            raise DeepSpeedConfigError(
                "disable_allgather selects allreduce over allgather for "
                "the ZeRO-1 parameter update; XLA chooses the collective "
                "implementation from the sharding layout — remove the key")
        if self.communication_data_type is not None:
            raise DeepSpeedConfigError(
                "communication_data_type casts gradients for an explicit "
                "allreduce; XLA's fused reduction accumulates in fp32 and "
                "there is no user-visible collective to cast — remove the "
                "key (for bandwidth compression use the 1-bit optimizers)")
        if self.optimizer_legacy_fusion:
            raise DeepSpeedConfigError(
                "optimizer.legacy_fusion toggles a CUDA kernel-fusion "
                "fallback; TPU optimizers are XLA/Pallas-fused uncondition"
                "ally — remove the key")
        if self.gradient_accumulation_dtype not in (
                None, "fp32", "bf16", "fp16"):
            raise DeepSpeedConfigError(
                "data_types.grad_accum_dtype must be one of "
                "fp32|bf16|fp16, got "
                f"{self.gradient_accumulation_dtype!r}")

    def print(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:")
        logger.info(json.dumps(self._param_dict, sort_keys=True, indent=4))
