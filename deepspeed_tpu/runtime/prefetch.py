"""Asynchronous input pipeline: background prefetch + device double-buffering.

PR 4's goodput ledger made input stalls *visible* (``input_wait``); this
module makes them *removable*. Today the engine's step loop runs
``next(data_iter)`` -> collate -> ``_globalize_batch`` ``device_put`` ->
dispatch fully serialized on the critical path, so every millisecond of
host-side batch work and H2D transfer is dead device time. The
:class:`PrefetchLoader` wraps any engine data source with a bounded
two-stage background pipeline (the tf.data / Flax ``prefetch_to_device``
idiom, and the reference DeepSpeed's implicit contract via its
worker-backed dataloaders):

* **host stage** — worker thread(s) pull + collate the next ``depth``
  batches. A :class:`~deepspeed_tpu.runtime.dataloader.DeepSpeedDataLoader`
  exposes its index plan / materialize split, so ``num_local_io_workers``
  workers collate *concurrently* while a filler thread preserves batch
  order; a generic iterator gets one puller thread (generators are not
  concurrently re-entrant).
* **device stage** — a placement thread runs the engine's
  ``_globalize_batch`` (``device_put``) for batch N+1 while step N
  computes, so the H2D copy overlaps device execution. The yielded batch
  is the SAME pytree with device-placed global leaves — not a wrapper —
  so user code that inspects batches keeps working, and the engine's own
  ``device_put`` against the identical sharding is a no-transfer no-op
  (verified same-buffer in jax 0.4.37). The stage runs on multi-process
  meshes too: the engine passes ``verify=False`` placement, which is
  collective-free by construction — the broadcast-leaf checksum
  allgather and eval row-count agreement are deferred to the MAIN thread
  at consumption (``engine._verify_prefetched_batch``), so a
  background-thread collective can never race a main-thread one (the
  deadlock that made PR 5 restrict the stage to single-process runs).

Hard edges handled here, all unit-pinned (``tests/unit/test_prefetch.py``):

* a worker exception is re-raised at the consumer's ``next()``, in
  sequence position (batches before it are delivered first);
* ``StopIteration`` / epoch semantics are identical to the unwrapped
  loader — each ``iter()`` drains exactly one epoch, so a wrapping
  ``RepeatingLoader`` still fires ``set_epoch`` in order on wrap-around
  before the next epoch's first pull;
* at most ``depth`` batches are materialized inside the pipeline (a
  semaphore gates the filler; the consumer returns permits);
* shutdown is leak-free: ``close()`` (idempotent), context manager and
  engine teardown stop + join the (daemon) threads with sentinel
  wake-ups; an iterator ABANDONED mid-epoch is reclaimed by GC — the
  threads hold only the shared :class:`_PipelineState`, never the
  iterator, so ``weakref.finalize`` fires, stops the pipeline, and also
  covers interpreter exit;
* background threads run under the goodput ledger's
  ``suppress_attribution`` so overlapped input work books ZERO
  ``input_wait`` — the consumer's near-zero ``next()`` wait is the real
  number, which is exactly what drives the PR-4 ``input_stall`` rule
  quiet on a prefetched run.

Telemetry: ``prefetch_hits_total`` / ``prefetch_misses_total`` counters
(was the next batch ready when the consumer asked?) and a
``prefetch_depth_occupancy`` gauge flow through whatever metrics registry
is installed (the engine's TelemetryManager installs its registry as the
process global, so JSONL/Prometheus sinks carry them for free).
"""

import queue
import threading
import weakref

from deepspeed_tpu.telemetry import metrics as _metrics
from deepspeed_tpu.telemetry.ledger import suppress_attribution
from deepspeed_tpu.utils.logging import logger

_END = "end"
_ERR = "err"
_OK = "ok"

# close()-join grace per thread; they are daemon threads, so a pathological
# hang in user collate/placement code degrades to a leaked daemon (and a
# warning), never a blocked interpreter exit
_JOIN_TIMEOUT_S = 5.0
_POLL_S = 0.2


class _Slot:
    """A minimal future: one materialized batch, or the exception its
    materialization raised. Custom instead of concurrent.futures because
    ThreadPoolExecutor threads are non-daemon and atexit-joined — a hung
    collate would block interpreter exit, the exact leak close() exists
    to prevent."""
    __slots__ = ("_ev", "_value", "_exc")

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._exc = None

    def set_result(self, value):
        self._value = value
        self._ev.set()

    def set_exception(self, exc):
        self._exc = exc
        self._ev.set()

    def done(self):
        return self._ev.is_set()

    def wait_ready(self, stop=None):
        """Block until the slot is filled; with *stop*, poll so a close()
        can interrupt the wait. Returns False iff stopped unfilled — a
        close() may leave queued slots no worker will ever fill, and an
        untimed Event.wait() there blocks its thread forever."""
        if stop is None:
            self._ev.wait()
            return True
        while not self._ev.wait(timeout=_POLL_S):
            if stop.is_set():
                return False
        return True

    def result(self):
        self._ev.wait()
        if self._exc is not None:
            raise self._exc
        return self._value


class _PipelineState:
    """Everything the pipeline threads share. Threads (and the GC
    finalizer) hold THIS object, never the iterator — so abandoning an
    iterator mid-epoch lets GC collect it, which fires the finalizer,
    which stops these threads. Holding ``self`` in a thread target would
    pin the iterator alive forever (the parked filler never exits)."""
    __slots__ = ("stop", "sem", "hostq", "outq", "workq", "threads")

    def __init__(self, depth, device_stage):
        self.stop = threading.Event()
        self.sem = threading.Semaphore(depth)
        self.hostq = queue.Queue()
        self.outq = queue.Queue() if device_stage else self.hostq
        self.workq = None
        self.threads = []


def _wake_and_stop(state):
    """Stop flag + one wake sentinel per blocked wait site, so no thread
    sleeps out a poll timeout (an epoch wrap-around rebuilds the
    pipeline — join latency here is train-loop latency)."""
    state.stop.set()
    n = max(1, len(state.threads))
    if state.workq is not None:
        for _ in range(n):
            state.workq.put(None)
    state.hostq.put(None)
    if state.outq is not state.hostq:
        # device stage armed: the hostq sentinel stops the device
        # thread but never reaches a consumer blocked in outq.get()
        state.outq.put(None)
    state.sem.release(n)              # filler parked on the depth gate


def _acquire_permit(state):
    """Depth-semaphore acquire that aborts on stop."""
    while not state.stop.is_set():
        if state.sem.acquire(timeout=_POLL_S):
            return True
    return False


def _fill_indexed(state, loader):
    try:
        for idx in loader._index_plan():
            if not _acquire_permit(state):
                return
            slot = _Slot()
            state.workq.put((idx, slot))
            state.hostq.put((_OK, slot))
        state.hostq.put((_END, None))
    except BaseException as e:                 # plan-time failure
        state.hostq.put((_ERR, e))


def _worker_loop(state, loader):
    while not state.stop.is_set():
        try:
            item = state.workq.get(timeout=_POLL_S)
        except queue.Empty:
            continue
        if item is None:              # close() wake sentinel
            return
        idx, slot = item
        try:
            with suppress_attribution():
                slot.set_result(loader.materialize(idx))
        except BaseException as e:
            slot.set_exception(e)


def _fill_generic(state, src):
    while not state.stop.is_set():
        if not _acquire_permit(state):
            return
        try:
            with suppress_attribution():
                batch = next(src)
        except StopIteration:
            state.hostq.put((_END, None))
            return
        except BaseException as e:
            state.hostq.put((_ERR, e))
            return
        state.hostq.put((_OK, batch))


def _device_loop(state, place_fn):
    while not state.stop.is_set():
        try:
            item = state.hostq.get(timeout=_POLL_S)
        except queue.Empty:
            continue
        if item is None:              # close() wake sentinel
            return
        kind, payload = item
        if kind != _OK:
            state.outq.put((kind, payload))
            return
        if isinstance(payload, _Slot) and \
                not payload.wait_ready(state.stop):
            return                    # closed with the slot never filled
        try:
            batch = payload.result() if isinstance(payload, _Slot) \
                else payload
            with suppress_attribution():
                placed = place_fn(batch)
        except BaseException as e:
            state.outq.put((_ERR, e))
            return
        state.outq.put((_OK, placed))


class PrefetchIterator:
    """One epoch's pipeline. Built by :class:`PrefetchLoader`; usable
    directly to wrap an arbitrary iterator (the engine does this for a
    user-supplied ``data_iter``)."""

    def __init__(self, source, depth=2, num_workers=1, place_fn=None,
                 loader=None, name="prefetch"):
        self.depth = max(1, int(depth))
        self._name = name
        self._finished = False
        self._closed = False
        self._error = None
        # indexed mode: the loader's index plan is cheap pure numpy, so the
        # filler computes it and N workers materialize (dataset fetch +
        # collate) concurrently; order is preserved because slots enter the
        # host queue in plan order. Generic mode: one puller owns the
        # iterator (generators cannot be entered from two threads).
        indexed = (loader is not None
                   and hasattr(loader, "_index_plan")
                   and hasattr(loader, "materialize"))
        workers = max(1, int(num_workers or 1))
        if not indexed and workers > 1:
            _warn_once(
                "generic_iter_workers",
                f"data_prefetch: source {type(source).__name__!r} is not an "
                f"indexable DeepSpeedDataLoader; the host stage runs ONE "
                f"puller thread (iterators are not concurrently "
                f"re-entrant), ignoring num_local_io_workers={workers}")
            workers = 1
        workers = min(workers, self.depth)
        reg = _metrics.get_registry()
        self._hits = reg.counter(
            "prefetch_hits_total",
            "next() calls served by an already-materialized batch")
        self._misses = reg.counter(
            "prefetch_misses_total",
            "next() calls that had to wait on the input pipeline")
        self._occupancy = reg.gauge(
            "prefetch_depth_occupancy",
            "batches ready in the prefetch output queue at next()")

        state = self._state = _PipelineState(
            self.depth, device_stage=place_fn is not None)
        if indexed:
            state.workq = queue.Queue()
            for i in range(workers):
                self._spawn(_worker_loop, (state, loader), f"w{i}")
            self._spawn(_fill_indexed, (state, loader), "fill")
        else:
            self._spawn(_fill_generic, (state, iter(source)), "fill")
        if place_fn is not None:
            self._spawn(_device_loop, (state, place_fn), "place")
        # abandoned-iterator backstop: fires at GC (threads hold only
        # `state`, so dropping the iterator really does free it) and at
        # interpreter exit; stops the pipeline without joining (the
        # daemon threads drain themselves within a poll interval)
        self._finalizer = weakref.finalize(self, _wake_and_stop, state)

    def _spawn(self, fn, args, tag):
        t = threading.Thread(target=fn, args=args,
                             name=f"ds-{self._name}-{tag}", daemon=True)
        self._state.threads.append(t)
        t.start()

    # ------------------------------------------------------------ consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._error is not None:
            # a failed pipeline stays failed: repeating the exception is
            # honest; StopIteration here would silently truncate the epoch
            raise self._error
        if self._finished:
            raise StopIteration
        outq = self._state.outq
        try:
            item = outq.get_nowait()
            ready = True
        except queue.Empty:
            ready = False
            item = outq.get()
        if item is None:              # closed under a blocked consumer
            raise StopIteration
        kind, payload = item
        if kind == _END:
            self._finish()
            raise StopIteration
        if kind == _ERR:
            self._error = payload
            self._finish()
            raise payload
        if isinstance(payload, _Slot):
            # host future: a "hit" means the materialization had finished
            # by the time the consumer asked
            ready = ready and payload.done()
            if not payload.wait_ready(self._state.stop):
                raise StopIteration   # closed with the slot never filled
            try:
                payload = payload.result()
            except BaseException as e:
                self._error = e
                self._finish()
                raise
        (self._hits if ready else self._misses).inc()
        self._occupancy.set(outq.qsize())
        self._state.sem.release()
        return payload

    # ------------------------------------------------------------ shutdown
    def _finish(self):
        """Natural end (or error): stop + join the pipeline threads."""
        self._finished = True
        self.close()

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._finished = True
        _wake_and_stop(self._state)
        for t in self._state.threads:
            t.join(timeout=_JOIN_TIMEOUT_S)
            if t.is_alive():
                logger.warning(
                    f"data_prefetch: thread {t.name} did not stop within "
                    f"{_JOIN_TIMEOUT_S}s (daemon; it cannot block exit)")
        self._finalizer.detach()      # already shut down; nothing for GC

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class PrefetchLoader:
    """Loader-shaped wrapper: each ``iter()`` spawns one
    :class:`PrefetchIterator` epoch pipeline over ``iter(loader)``.

    Delegates ``__len__`` / ``set_epoch`` / ``.epoch`` to the wrapped
    loader so a surrounding ``RepeatingLoader`` (or a resume path) sees
    the ordinary loader surface. ``close()`` stops every live iterator's
    pipeline; the loader is also a context manager."""

    def __init__(self, loader, depth=2, num_workers=1, place_fn=None,
                 name="prefetch"):
        self.loader = loader
        self.depth = depth
        self.num_workers = num_workers
        self.place_fn = place_fn
        self._name = name
        self._iters = []                      # weakrefs to live pipelines

    def __len__(self):
        return len(self.loader)

    def set_epoch(self, epoch):
        set_epoch = getattr(self.loader, "set_epoch", None)
        if set_epoch is not None:
            set_epoch(epoch)

    def set_resume(self, batch_in_epoch):
        """Mid-epoch resume passthrough (RepeatingLoader.load_state_dict):
        the skip lives in the wrapped loader's index plan, so the next
        ``iter()``'s pipeline simply never schedules the skipped
        batches."""
        set_resume = getattr(self.loader, "set_resume", None)
        if set_resume is not None:
            set_resume(batch_in_epoch)
        else:
            raise AttributeError(
                f"wrapped loader {type(self.loader).__name__!r} has no "
                f"set_resume; mid-epoch resume needs a "
                f"DeepSpeedDataLoader-style index plan")

    @property
    def epoch(self):
        return getattr(self.loader, "epoch", 0)

    def __iter__(self):
        # DeepSpeedDataLoader: hand the loader itself over so the host
        # stage can use its index-plan/materialize split (N workers);
        # anything else is pulled through its ordinary iterator protocol
        indexed = (hasattr(self.loader, "_index_plan")
                   and hasattr(self.loader, "materialize"))
        it = PrefetchIterator(
            self.loader, depth=self.depth, num_workers=self.num_workers,
            place_fn=self.place_fn,
            loader=self.loader if indexed else None, name=self._name)
        self._iters = [r for r in self._iters if r() is not None]
        self._iters.append(weakref.ref(it))
        return it

    def close(self):
        for ref in self._iters:
            it = ref()
            if it is not None:
                it.close()
        self._iters = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


_WARNED = set()


def _warn_once(key, msg):
    if key not in _WARNED:
        _WARNED.add(key)
        logger.warning(msg)
