"""Progressive layer drop (PLD).

Port of deepspeed/runtime/progressive_layer_drop.py:5 — the θ(t)
stochastic-depth schedule. Identical math; the model consumes
``progressive_layer_drop`` kwargs exactly like the reference injects them
in engine.forward (engine.py:1571)."""

import numpy as np


class ProgressiveLayerDrop:
    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        def _prob(x, g, p):
            return (1.0 - p) * np.exp(-g * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
