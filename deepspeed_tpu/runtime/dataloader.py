"""Data loading.

Parity with ``deepspeed/runtime/dataloader.py`` (``DeepSpeedDataLoader``
:33, ``RepeatingLoader`` :10). TPU-native differences: batches are numpy /
jax arrays (no torch dependency required, though torch datasets work), and
instead of a per-rank ``DistributedSampler`` the loader yields the GLOBAL
batch — the engine shards it over the mesh's data axis with
``jax.device_put``; XLA then keeps each shard on its own chip. In a
multi-host setup each process loads only its host's slice
(``process_index``-strided sampling), matching DistributedSampler
semantics.

Both loaders are instrumented for the goodput ledger
(``telemetry/ledger.py``): time a consumer spends blocked in ``next()``
is attributed to the ``input_wait`` wall-clock category. Without an
installed ledger the instrumentation is a shared no-op context manager.
"""

import numpy as np

from deepspeed_tpu.telemetry.ledger import GoodputIterator, get_ledger


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference :10).

    On wrap-around the underlying loader's epoch is ADVANCED first (via
    ``set_epoch`` when it has one) — re-iterating a shuffling
    ``DeepSpeedDataLoader`` without it would replay the identical
    permutation every epoch (the reference relies on the training script
    calling ``DistributedSampler.set_epoch``; a repeating wrapper is
    exactly the place no script can do it)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        # continue from the wrapped loader's own epoch counter when it
        # has one (a resumed loader must not restart the shuffle stream)
        self.epoch = int(getattr(loader, "epoch", 0))
        # batches already yielded from the CURRENT epoch — with the epoch
        # it pins the exact position in the (epoch-seeded) shuffle stream,
        # which is what a preempted run must resume from
        self.batch_in_epoch = 0

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        with get_ledger().attribute("input_wait"):
            try:
                batch = next(self.data_iter)
            except StopIteration:
                self.epoch += 1
                self.batch_in_epoch = 0
                set_epoch = getattr(self.loader, "set_epoch", None)
                if set_epoch is not None:
                    set_epoch(self.epoch)
                self.data_iter = iter(self.loader)
                batch = next(self.data_iter)
            self.batch_in_epoch += 1
        return batch

    # ------------------------------------------------- preemption resume
    def state_dict(self):
        """The (epoch, offset) pair that pins the data stream position.
        Both counters are world-size invariant: an epoch holds
        ``dataset/global_batch`` batches per process regardless of how
        many processes stride it, so a checkpoint saved at dp=N resumes
        correctly at any other dp (``engine.save_checkpoint(...,
        data_iter=loader)`` carries this in the checkpoint)."""
        return {"epoch": int(self.epoch),
                "batch_in_epoch": int(self.batch_in_epoch)}

    def load_state_dict(self, sd):
        """Rewind/advance the stream to ``sd``'s position: re-seed the
        shuffle at the saved epoch, then skip the already-consumed
        batches. A loader exposing ``set_resume`` (DeepSpeedDataLoader,
        PrefetchLoader) skips inside its index plan — nothing is
        materialized; a generic iterator pulls and discards."""
        epoch = int(sd.get("epoch", 0))
        offset = int(sd.get("batch_in_epoch", 0))
        self.epoch = epoch
        set_epoch = getattr(self.loader, "set_epoch", None)
        if set_epoch is not None:
            set_epoch(epoch)
        set_resume = getattr(self.loader, "set_resume", None)
        if set_resume is not None:
            set_resume(offset)
            self.data_iter = iter(self.loader)
        else:
            self.data_iter = iter(self.loader)
            for _ in range(offset):
                next(self.data_iter)
        self.batch_in_epoch = offset


class DeepSpeedDataLoader:
    """Batched, optionally shuffled, epoch-aware loader over an indexable
    dataset of (x, y) pairs or dicts; built by ``engine.deepspeed_io``
    (reference engine.py:1474)."""

    def __init__(self, dataset, batch_size, shuffle=False, seed=0,
                 drop_last=True, collate_fn=None, num_local_io_workers=None,
                 data_sampler=None, process_index=0, process_count=1):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.process_index = process_index
        self.process_count = process_count
        self.epoch = 0
        self.data_sampler = data_sampler
        # honored by the prefetch host stage (runtime/prefetch.py) as its
        # worker count; without prefetch the loader is synchronous and the
        # engine warns once that the knob has no effect
        self.num_local_io_workers = num_local_io_workers
        # one-shot mid-epoch resume offset (set_resume): consumed by the
        # next _index_plan, which drops the first k slices un-materialized
        self._resume_batches = 0
        n = len(dataset)
        per_proc = n // process_count if drop_last else -(-n // process_count)
        if drop_last:
            self.len = per_proc // batch_size
        else:
            self.len = -(-per_proc // batch_size)

    def set_epoch(self, epoch):
        self.epoch = epoch

    def set_resume(self, batch_in_epoch):
        """Skip the first *batch_in_epoch* batches of the NEXT iteration
        (one-shot). Deterministic mid-epoch resume: the epoch's index
        plan is a pure function of (seed, epoch), so dropping its first
        slices reproduces the preempted run's exact remaining stream —
        and skipped batches are never fetched or collated."""
        self._resume_batches = max(0, int(batch_in_epoch))

    def __len__(self):
        return self.len

    def __iter__(self):
        # GoodputIterator times only the consumer's next() calls; timing
        # inside the generator would also count the consumer's own work
        # between batches (the generator is suspended across it)
        return GoodputIterator(self._iter_batches())

    def _index_plan(self):
        """Yield this epoch's batch index slices, in order. The plan is
        cheap pure-numpy work split from :meth:`materialize` so the
        prefetcher's host stage can fan the (expensive) dataset fetch +
        collate out over ``num_local_io_workers`` while one filler thread
        preserves the batch order."""
        n = len(self.dataset)
        if self.data_sampler is not None:
            # a user sampler already yields THIS process's indices
            # (DistributedSampler semantics) — no further striding
            order = np.fromiter(iter(self.data_sampler), dtype=np.int64)
        else:
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self.epoch)
                order = rng.permutation(n)
            else:
                order = np.arange(n)
            # host slice (DistributedSampler analogue): strided by process
            order = order[self.process_index::self.process_count]
        skip, self._resume_batches = self._resume_batches, 0
        limit = self.len * self.batch_size
        for bnum, start in enumerate(
                range(0, min(len(order), limit), self.batch_size)):
            idx = order[start:start + self.batch_size]
            if self.drop_last and len(idx) < self.batch_size:
                break
            if bnum < skip:       # mid-epoch resume: already consumed
                continue
            yield idx

    def materialize(self, idx):
        """Fetch + collate one batch by index slice (thread-safe for the
        usual indexable datasets; the prefetch workers call this off the
        consumer thread)."""
        return self.collate_fn([self.dataset[int(i)] for i in idx])

    def _iter_batches(self):
        for idx in self._index_plan():
            yield self.materialize(idx)


def _default_collate(samples):
    """Stack a list of samples into batched numpy arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([np.asarray(s[i]) for s in samples])
                     for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])
