"""Sparse gradients — the embedding-gradient allreduce path.

Rebuild of deepspeed/runtime/sparse_tensor.py:11 (``SparseTensor``) and
the engine's ``sparse_allreduce*`` (engine.py:2196-2268): embedding-layer
gradients touch only the rows of the tokens in the batch, so DP reduction
ships (indices, values) instead of the dense [V, D] tensor. The
reference's "allreduce" for sparse grads is an all_gather of every rank's
(indices, values) followed by a local scatter-add — exactly reproducible
with XLA collectives:

* :class:`SparseTensor` — (indices [k], values [k, ...]) + dense_size,
  with to_dense / from_dense conversions (torch coalescing becomes a
  segment-sum);
* :func:`sparse_all_reduce` — in-jit (shard_map/pjit) collective:
  all_gather indices+values over the axis, scatter-add into dense. Use it
  for vocab-sized embedding grads where k*D << V*D.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class SparseTensor(NamedTuple):
    """Compressed sparse representation (reference sparse_tensor.py:11)."""
    indices: Any          # [k] int32 row ids
    values: Any           # [k, ...] row payloads
    dense_shape: tuple    # full dense shape

    @staticmethod
    def from_dense(dense, indices):
        """Rows of ``dense`` at ``indices`` (the embedding-grad case:
        indices = the batch's token ids)."""
        return SparseTensor(indices=jnp.asarray(indices, jnp.int32),
                            values=jnp.take(dense, indices, axis=0),
                            dense_shape=tuple(dense.shape))

    def to_dense(self):
        """Scatter-add values into the dense shape (duplicate indices
        accumulate — torch sparse coalescing semantics)."""
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self):
        """(compressed elements, dense elements) — reference
        sparse_size()."""
        import numpy as np
        dense = int(np.prod(self.dense_shape))
        comp = self.indices.size + self.values.size
        return comp, dense


def sparse_all_reduce(indices, values, dense_shape, axis_name, op="mean"):
    """DP reduction of per-rank sparse gradients, inside shard_map/pjit.

    indices: [k] int32 (k static, same on every rank — the batch's token
    count); values: [k, D]. Returns the DENSE reduced [V, D] gradient.
    Wire cost: world*k*(D+1) elements vs world*V*D for a dense allreduce —
    the reference's bandwidth argument (engine.sparse_allreduce_bucket).
    """
    world = lax.psum(1, axis_name)
    all_idx = lax.all_gather(indices, axis_name)     # [world, k]
    all_val = lax.all_gather(values, axis_name)      # [world, k, D]
    dense = jnp.zeros(dense_shape, values.dtype)
    # mode="drop": callers may pad indices with dense_shape[0] (out of
    # bounds) to keep the nnz count static under jit
    dense = dense.at[all_idx.reshape(-1)].add(
        all_val.reshape((-1,) + all_val.shape[2:]), mode="drop")
    if op == "mean":
        dense = dense / world
    return dense
