"""Power-iteration curvature (eigenvalue) estimation.

Rebuild of deepspeed/runtime/eigenvalue.py:7, which drives the MoQ
quantization schedule (engine.step hook, engine.py:1891). The reference
power-iterates on each layer-block's gradients via autograd retain_graph;
here the same estimate is a Hessian-vector-product power iteration using
``jax.jvp`` over ``jax.grad`` — functionally identical, and jit-compiled.
"""

from typing import Callable

import jax
import jax.numpy as jnp


class Eigenvalue:
    def __init__(self, verbose=False, max_iter=100, tol=1e-2,
                 stability=1e-6, gas_boundary_resolution=1,
                 layer_name="", layer_num=0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def normalize(self, v):
        norm = jnp.sqrt(sum(jnp.vdot(x, x) for x in jax.tree.leaves(v)))
        norm = jnp.maximum(norm, self.stability)
        return jax.tree.map(lambda x: x / norm, v)

    def compute_eigenvalue(self, loss_fn: Callable, params, rng=None):
        """Largest |eigenvalue| of the loss Hessian at params.

        loss_fn(params) -> scalar. Returns a python float (the reference
        returns per-block ratios consumed by the MoQ scheduler)."""
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        key = rng if rng is not None else jax.random.PRNGKey(0)
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(key, len(leaves))
        v = treedef.unflatten([
            jax.random.normal(k, x.shape, jnp.float32)
            for k, x in zip(keys, leaves)])
        v = self.normalize(v)

        eig = 0.0
        for _ in range(self.max_iter):
            Hv = hvp(v)
            new_eig = float(sum(jnp.vdot(a, b).real for a, b in zip(
                jax.tree.leaves(v), jax.tree.leaves(Hv))))
            v = self.normalize(Hv)
            if abs(new_eig) < self.stability:
                return 0.0
            if eig != 0.0 and abs(new_eig - eig) / abs(new_eig) < self.tol:
                eig = new_eig
                break
            eig = new_eig
        return eig
