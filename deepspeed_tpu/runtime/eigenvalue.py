"""Power-iteration curvature (eigenvalue) estimation.

Rebuild of deepspeed/runtime/eigenvalue.py:7, which drives the MoQ
quantization schedule (engine.step hook, engine.py:1891). The reference
power-iterates on each layer-block's gradients via autograd retain_graph;
here the same estimate is a Hessian-vector-product power iteration using
``jax.jvp`` over ``jax.grad`` — functionally identical, and jit-compiled.
"""

import re
from typing import Callable

import jax
import jax.numpy as jnp


def path_str(path):
    """Join a jax key-path into 'a/b/0/c' (shared with the MoQ quantizer
    so block_eigenvalue keys match its tree_map_with_path lookups)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


class Eigenvalue:
    def __init__(self, verbose=False, max_iter=100, tol=1e-2,
                 stability=1e-6, gas_boundary_resolution=1,
                 layer_name="", layer_num=0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def normalize(self, v):
        norm = jnp.sqrt(sum(jnp.vdot(x, x) for x in jax.tree.leaves(v)))
        norm = jnp.maximum(norm, self.stability)
        return jax.tree.map(lambda x: x / norm, v)

    def compute_eigenvalue(self, loss_fn: Callable, params, rng=None):
        """Largest |eigenvalue| of the loss Hessian at params.

        loss_fn(params) -> scalar. Returns a python float (the reference
        returns per-block ratios consumed by the MoQ scheduler)."""
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        key = rng if rng is not None else jax.random.PRNGKey(0)
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(key, len(leaves))
        v = treedef.unflatten([
            jax.random.normal(k, x.shape, jnp.float32)
            for k, x in zip(keys, leaves)])
        v = self.normalize(v)

        eig = 0.0
        for _ in range(self.max_iter):
            Hv = hvp(v)
            new_eig = float(sum(jnp.vdot(a, b).real for a, b in zip(
                jax.tree.leaves(v), jax.tree.leaves(Hv))))
            v = self.normalize(Hv)
            if abs(new_eig) < self.stability:
                return 0.0
            if eig != 0.0 and abs(new_eig - eig) / abs(new_eig) < self.tol:
                eig = new_eig
                break
            eig = new_eig
        return eig

    def _block_index(self, joined_path):
        """Block id of a param path, or None.

        ``layer_name`` names the repeated-layer module ('h', 'layers',
        'bert.encoder.layer', ...); the block id is the integer that
        follows it in the path ('h_3/attn/...', 'layers/3/...')."""
        if self.layer_name:
            tail = self.layer_name.replace(".", "/").split("/")[-1]
            pat = rf"(?:^|/){re.escape(tail)}s?[_/]?(\d+)(?:/|$)"
        else:
            pat = r"_(\d+)(?:/|$)"
        m = re.search(pat, joined_path)
        if m is None:
            return None
        idx = int(m.group(1))
        if self.layer_num and idx >= self.layer_num:
            return None
        return idx

    def compute_block_eigenvalues(self, loss_fn: Callable, params, rng=None):
        """Per-layer-block curvature for the MoQ schedule.

        Power-iterates the DIAGONAL Hessian block of each repeated layer
        (tangent zero outside the block — the jax form of the reference's
        per-block ``torch.autograd.grad(grads, params, grad_outputs=v)``,
        eigenvalue.py:61-145). Returns ``{leaf_path: (ratio, layer_id)}``
        with ratios post-processed to [0, 1] of the max block, 1.0 for
        blocks whose estimate is 0 — exactly the reference's
        ``post_process`` (eigenvalue.py:148-151)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        paths = [path_str(p) for p, _ in flat]
        block_of = {i: b for i, p in enumerate(paths)
                    if (b := self._block_index(p)) is not None}
        if not block_of:
            return {}
        n_blocks = max(block_of.values()) + 1
        grad_fn = jax.grad(loss_fn)

        def hvp(v_tree):
            return jax.jvp(grad_fn, (params,), (v_tree,))[1]

        hvp = jax.jit(hvp)
        key = rng if rng is not None else jax.random.PRNGKey(17)
        leaves = [x for _, x in flat]
        block_evs = []
        for b in range(n_blocks):
            idxs = {i for i, blk in block_of.items() if blk == b}
            if not idxs:
                block_evs.append(0.0)
                continue
            key, sub = jax.random.split(key)
            subkeys = jax.random.split(sub, len(idxs))
            v_leaves = [jnp.zeros_like(x, jnp.float32) for x in leaves]
            for k, i in zip(subkeys, sorted(idxs)):
                v_leaves[i] = jax.random.normal(
                    k, leaves[i].shape, jnp.float32)

            def restrict_norm(lvs):
                norm = jnp.sqrt(sum(
                    jnp.vdot(lvs[i], lvs[i]).real for i in idxs))
                norm = jnp.maximum(norm, self.stability)
                return [lvs[i] / norm if i in idxs
                        else jnp.zeros_like(lvs[i])
                        for i in range(len(lvs))]

            v_leaves = restrict_norm(v_leaves)
            eig = 0.0
            for _ in range(self.max_iter):
                Hv = jax.tree.leaves(hvp(treedef.unflatten(v_leaves)))
                new_eig = float(sum(jnp.vdot(v_leaves[i], Hv[i]).real
                                    for i in idxs))
                v_leaves = restrict_norm(
                    [h.astype(jnp.float32) for h in Hv])
                if abs(new_eig) < self.stability:
                    eig = 0.0
                    break
                if eig != 0.0 and abs(new_eig - eig) / abs(new_eig) < self.tol:
                    eig = new_eig
                    break
                eig = new_eig
            block_evs.append(eig)
            if self.verbose:
                from deepspeed_tpu.utils.logging import log_dist
                log_dist(f"block {b} eigenvalue: {eig}", ranks=[0])

        max_ev = max((abs(v) for v in block_evs), default=0.0)
        ratios = [abs(v) / max_ev if (max_ev > 0.0 and v != 0.0) else 1.0
                  for v in block_evs]
        return {paths[i]: (ratios[blk], blk) for i, blk in block_of.items()}
