"""Loss scaling state machines.

Parity with ``deepspeed/runtime/fp16/loss_scaler.py`` (``LossScaler`` :56,
``DynamicLossScaler`` :79). TPU-native twist: the scaler state is a pytree
(:class:`LossScaleState`) threaded through the jitted train step, and the
update rule is a pure function built from ``lax`` ops so the
overflow-skip + scale-adjust logic compiles into the step instead of
requiring a host sync per iteration (the reference's ``_has_inf_or_nan``
forces a D2H copy each step).
"""

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    """Functional scaler state living inside the TrainState.

    Only the dynamic scalars live here (the static knobs — window, factor,
    hysteresis depth — are closed over by the jitted step so they never
    appear as traced values)."""
    loss_scale: jnp.ndarray       # f32 scalar
    good_steps: jnp.ndarray       # i32 scalar — consecutive overflow-free steps
    hysteresis: jnp.ndarray       # i32 scalar — remaining tolerated overflows


def make_scale_state(init_scale, delayed_shift=1):
    return LossScaleState(loss_scale=jnp.float32(init_scale),
                          good_steps=jnp.int32(0),
                          hysteresis=jnp.int32(delayed_shift))


def update_scale(state: LossScaleState, overflow, *, dynamic=True,
                 scale_factor=2.0, scale_window=1000, min_scale=1.0,
                 delayed_shift=1) -> LossScaleState:
    """Pure scale-update rule (reference DynamicLossScaler.update_scale).

    On overflow: consume hysteresis; once exhausted, halve the scale
    (clamped at min_scale) and reset the good-step counter. After
    ``scale_window`` consecutive good steps: double the scale and restore
    hysteresis.
    """
    if not dynamic:
        return state

    overflow = jnp.asarray(overflow)

    def on_overflow(s):
        new_hyst = s.hysteresis - 1
        must_shift = new_hyst <= 0
        new_scale = jnp.where(
            must_shift,
            jnp.maximum(s.loss_scale / scale_factor, min_scale),
            s.loss_scale)
        new_hyst = jnp.where(must_shift, jnp.int32(delayed_shift), new_hyst)
        return LossScaleState(loss_scale=new_scale, good_steps=jnp.int32(0),
                              hysteresis=new_hyst)

    def on_good(s):
        grown = (s.good_steps + 1) % scale_window == 0
        new_scale = jnp.where(grown, s.loss_scale * scale_factor, s.loss_scale)
        new_hyst = jnp.where(grown, jnp.int32(delayed_shift), s.hysteresis)
        return LossScaleState(loss_scale=new_scale, good_steps=s.good_steps + 1,
                              hysteresis=new_hyst)

    return lax.cond(overflow, on_overflow, on_good, state)


def scale_state_stats(state: LossScaleState):
    """The dynamic-scaler scalars as a flat dict — the health observatory's
    in-step view of the fp16 state machine. ``hysteresis`` is the REMAINING
    tolerated overflows: with the default delayed_shift=2, a value of 1
    means one overflow has already been absorbed silently (no scale change,
    no log line) and the next one will halve the scale — exactly the state
    a sampled host metric cannot otherwise see."""
    return {"loss_scale": state.loss_scale,
            "good_steps": state.good_steps,
            "hysteresis": state.hysteresis}


# ---------------------------------------------------------------------------
# Class API parity (reference LossScalerBase/LossScaler/DynamicLossScaler)
# ---------------------------------------------------------------------------


class LossScalerBase:
    def __init__(self, cur_scale):
        self.cur_scale = cur_scale
        self.dynamic = False

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, module, grad_in, grad_out):
        return tuple(self.loss_scale * g for g in grad_in)

    def update_scale(self, overflow):
        pass

    def backward(self, loss, retain_graph=False):
        # JAX has no .backward(); the engine scales loss inside its jitted
        # grad computation. Kept for signature parity.
        return loss * self.loss_scale


class LossScaler(LossScalerBase):
    """Static scaler (reference :56)."""

    def __init__(self, scale=1):
        super().__init__(scale)

    def has_overflow(self, params):
        return False

    def _has_inf_or_nan(self, x):
        return False


class DynamicLossScaler(LossScalerBase):
    """Host-side mirror of the dynamic state machine (reference :79)."""

    def __init__(self, init_scale=2 ** 32, scale_factor=2.0, scale_window=1000,
                 min_scale=1, delayed_shift=1, consecutive_hysteresis=False):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.cur_hysteresis = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis
        self.dynamic = True

    def _has_inf_or_nan(self, x):
        a = jnp.asarray(x)
        return bool(~jnp.isfinite(a).all())

    def has_overflow(self, grads):
        import jax
        return any(self._has_inf_or_nan(g) for g in jax.tree.leaves(grads))

    def update_scale(self, overflow):
        if overflow:
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                self.cur_scale = max(self.cur_scale / self.scale_factor, self.min_scale)
            else:
                self.cur_hysteresis -= 1
            self.last_overflow_iter = self.cur_iter
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1


CONFIG_MAPPING = {
    INITIAL_LOSS_SCALE: "init_scale",
    SCALE_WINDOW: "scale_window",
    DELAYED_SHIFT: "delayed_shift",
    MIN_LOSS_SCALE: "min_scale",
}


def CreateLossScaler(dtype, static_loss_scale, dynamic_scaling, dynamic_loss_args):
    """Factory mirroring the reference's engine wiring: fp16+dynamic →
    DynamicLossScaler; fp16+static → LossScaler(static); bf16/fp32 →
    LossScaler(1)."""
    if dynamic_scaling:
        kwargs = dynamic_loss_args or {}
        return DynamicLossScaler(**{CONFIG_MAPPING.get(k, k): v
                                    for k, v in kwargs.items()})
    return LossScaler(scale=static_loss_scale if static_loss_scale else 1)
