"""1-bit LAMB (reference deepspeed/runtime/fp16/onebit/lamb.py).

Same structure as onebit/adam.py: freeze_step warmup of exact LAMB, then
sign-compressed momentum with error feedback and a frozen variance; the
per-tensor trust ratio (scaled_lr = lr * clamp(||w||/||u||)) is computed
from the compressed update, matching the reference's fused lamb path. See
onebit/adam.py for the TPU comm note.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime import optim as optim_lib
from deepspeed_tpu.runtime.fp16.onebit.adam import _compress


class OnebitLambState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any
    error: Any


def onebit_lamb(b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.0,
                freeze_step=100, min_coeff=0.01, max_coeff=10.0,
                bias_correction=True):
    def init(params):
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return OnebitLambState(step=jnp.zeros([], jnp.int32),
                               mu=zeros(), nu=zeros(), error=zeros())

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        warm = step <= freeze_step

        def leaf_update(g, m, v, e, p):
            m_new = b1 * m + (1.0 - b1) * g
            v_warm = b2 * v + (1.0 - b2) * g * g
            m_comp, e_new = _compress(m_new, e)

            m_eff = jnp.where(warm, m_new, m_comp)
            v_eff = jnp.where(warm, v_warm, v)
            u = (m_eff / bc1) / (jnp.sqrt(v_eff / bc2) + eps)
            if weight_decay > 0.0:
                u = u + weight_decay * p
            w_norm = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
            u_norm = jnp.linalg.norm(u.astype(jnp.float32).reshape(-1))
            ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                              jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
                              jnp.float32(1.0))
            upd = -lr * ratio * u
            return (upd, m_eff, v_eff, jnp.where(warm, e, e_new))

        flat_g, treedef = jax.tree.flatten(grads)
        flat = zip(flat_g, treedef.flatten_up_to(state.mu),
                   treedef.flatten_up_to(state.nu),
                   treedef.flatten_up_to(state.error),
                   treedef.flatten_up_to(params))
        out = [leaf_update(*args) for args in flat]
        return (treedef.unflatten([o[0] for o in out]),
                OnebitLambState(
                    step=step,
                    mu=treedef.unflatten([o[1] for o in out]),
                    nu=treedef.unflatten([o[2] for o in out]),
                    error=treedef.unflatten([o[3] for o in out])))

    return optim_lib.Optimizer(init, update)


class OnebitLambDistState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any
    worker_error: Any   # per-leaf flat [P] (comm/nccl.py worker_error)
    server_error: Any   # per-leaf flat [P / world] (server_error)


def onebit_lamb_distributed(axis_name, world, b1=0.9, b2=0.999, eps=1e-6,
                            weight_decay=0.0, freeze_step=100,
                            min_coeff=0.01, max_coeff=10.0,
                            bias_correction=True):
    """1-bit LAMB with the REAL compressed collective in the loop
    (reference onebit/lamb.py:14 over comm/nccl.py:47).

    Same contract as :func:`onebit_adam_distributed`: ``update`` must run
    INSIDE shard_map/pjit with ``axis_name`` bound and rank-LOCAL grads;
    warmup steps use an exact fp32 pmean, post-freeze the momenta travel
    through the error-compensated 1-bit allreduce and the variance
    freezes. The per-tensor trust ratio is computed from the synchronized
    update, so every rank applies the same scaled step.
    """
    from deepspeed_tpu.comm.compressed import (compressed_allreduce,
                                               padded_numel)

    def init(params):
        zeros = lambda fn: jax.tree.map(fn, params)  # noqa: E731
        return OnebitLambDistState(
            step=jnp.zeros([], jnp.int32),
            mu=zeros(lambda p: jnp.zeros(p.shape, jnp.float32)),
            nu=zeros(lambda p: jnp.zeros(p.shape, jnp.float32)),
            worker_error=zeros(lambda p: jnp.zeros(
                (padded_numel(p.size, world),), jnp.float32)),
            server_error=zeros(lambda p: jnp.zeros(
                (padded_numel(p.size, world) // world,), jnp.float32)))

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        warm = step <= freeze_step

        def leaf(g, m, v, we, se, p):
            g = g.astype(jnp.float32)
            m_local = b1 * m + (1.0 - b1) * g

            def warm_branch(operands):
                m_local, v, we, se, g = operands
                m_exact = jax.lax.pmean(m_local, axis_name)
                v_new = b2 * v + (1.0 - b2) * \
                    jax.lax.pmean(g, axis_name) ** 2
                return m_exact, v_new, we, se

            def frozen_branch(operands):
                m_local, v, we, se, _ = operands
                m_flat, we_new, se_new = compressed_allreduce(
                    m_local.reshape(-1), we, se, axis_name)
                return m_flat.reshape(m_local.shape), v, we_new, se_new

            m_out, v_out, we_out, se_out = jax.lax.cond(
                warm, warm_branch, frozen_branch, (m_local, v, we, se, g))
            u = (m_out / bc1) / (jnp.sqrt(v_out / bc2) + eps)
            if weight_decay > 0.0:
                u = u + weight_decay * p.astype(jnp.float32)
            w_norm = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
            u_norm = jnp.linalg.norm(u.reshape(-1))
            ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                              jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
                              jnp.float32(1.0))
            upd = (-lr * ratio * u).astype(p.dtype)
            return upd, m_out, v_out, we_out, se_out

        flat_g, treedef = jax.tree.flatten(grads)
        out = [leaf(g, m, v, we, se, p) for g, m, v, we, se, p in zip(
            flat_g,
            treedef.flatten_up_to(state.mu),
            treedef.flatten_up_to(state.nu),
            treedef.flatten_up_to(state.worker_error),
            treedef.flatten_up_to(state.server_error),
            treedef.flatten_up_to(params))]
        updates = treedef.unflatten([o[0] for o in out])
        new_state = OnebitLambDistState(
            step=step,
            mu=treedef.unflatten([o[1] for o in out]),
            nu=treedef.unflatten([o[2] for o in out]),
            worker_error=treedef.unflatten([o[3] for o in out]),
            server_error=treedef.unflatten([o[4] for o in out]))
        return updates, new_state

    return optim_lib.Optimizer(init, update)


def onebit_lamb_engine(axis_name, world, **kw):
    """Engine-facing wrapper: GLOBAL flat error buffers sharded over
    ``axis_name`` (see onebit/adam.py make_global_dist_state)."""
    from deepspeed_tpu.runtime.fp16.onebit.adam import make_global_dist_state
    base = onebit_lamb_distributed(axis_name, world, **kw)
    return optim_lib.Optimizer(
        lambda params: make_global_dist_state(
            OnebitLambDistState, params, world),
        base.update)


class OnebitLamb:
    def __new__(cls, params=None, lr=1e-3, freeze_step=100,
                betas=(0.9, 0.999), eps=1e-6, weight_decay=0.0,
                min_coeff=0.01, max_coeff=10.0, **_):
        return onebit_lamb(b1=betas[0], b2=betas[1], eps=eps,
                           weight_decay=weight_decay, freeze_step=freeze_step,
                           min_coeff=min_coeff, max_coeff=max_coeff)
