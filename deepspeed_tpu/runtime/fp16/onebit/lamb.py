"""1-bit LAMB (reference deepspeed/runtime/fp16/onebit/lamb.py).

Same structure as onebit/adam.py: freeze_step warmup of exact LAMB, then
sign-compressed momentum with error feedback and a frozen variance; the
per-tensor trust ratio (scaled_lr = lr * clamp(||w||/||u||)) is computed
from the compressed update, matching the reference's fused lamb path. See
onebit/adam.py for the TPU comm note.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime import optim as optim_lib
from deepspeed_tpu.runtime.fp16.onebit.adam import _compress


class OnebitLambState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any
    error: Any


def onebit_lamb(b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.0,
                freeze_step=100, min_coeff=0.01, max_coeff=10.0,
                bias_correction=True):
    def init(params):
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return OnebitLambState(step=jnp.zeros([], jnp.int32),
                               mu=zeros(), nu=zeros(), error=zeros())

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        warm = step <= freeze_step

        def leaf_update(g, m, v, e, p):
            m_new = b1 * m + (1.0 - b1) * g
            v_warm = b2 * v + (1.0 - b2) * g * g
            m_comp, e_new = _compress(m_new, e)

            m_eff = jnp.where(warm, m_new, m_comp)
            v_eff = jnp.where(warm, v_warm, v)
            u = (m_eff / bc1) / (jnp.sqrt(v_eff / bc2) + eps)
            if weight_decay > 0.0:
                u = u + weight_decay * p
            w_norm = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
            u_norm = jnp.linalg.norm(u.astype(jnp.float32).reshape(-1))
            ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                              jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
                              jnp.float32(1.0))
            upd = -lr * ratio * u
            return (upd, m_eff, v_eff, jnp.where(warm, e, e_new))

        flat_g, treedef = jax.tree.flatten(grads)
        flat = zip(flat_g, treedef.flatten_up_to(state.mu),
                   treedef.flatten_up_to(state.nu),
                   treedef.flatten_up_to(state.error),
                   treedef.flatten_up_to(params))
        out = [leaf_update(*args) for args in flat]
        return (treedef.unflatten([o[0] for o in out]),
                OnebitLambState(
                    step=step,
                    mu=treedef.unflatten([o[1] for o in out]),
                    nu=treedef.unflatten([o[2] for o in out]),
                    error=treedef.unflatten([o[3] for o in out])))

    return optim_lib.Optimizer(init, update)


class OnebitLamb:
    def __new__(cls, params=None, lr=1e-3, freeze_step=100,
                betas=(0.9, 0.999), eps=1e-6, weight_decay=0.0,
                min_coeff=0.01, max_coeff=10.0, **_):
        return onebit_lamb(b1=betas[0], b2=betas[1], eps=eps,
                           weight_decay=weight_decay, freeze_step=freeze_step,
                           min_coeff=min_coeff, max_coeff=max_coeff)
