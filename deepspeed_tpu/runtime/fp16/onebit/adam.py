"""1-bit Adam — error-compensated sign-compressed momentum.

Rebuild of deepspeed/runtime/fp16/onebit/adam.py:14 (+ the compressed
allreduce backends comm/nccl.py:47, comm/mpi.py:170). Algorithm semantics
are identical: a ``freeze_step`` warmup of exact Adam, then the variance
term freezes and the momentum is communicated 1-bit (sign + per-tensor
scale) with worker-side error feedback.

TPU-native note: the reference compresses because its inter-node fabric is
slow Ethernet; XLA's grad psum over ICI doesn't expose a hook to compress
in-flight (and ICI rarely needs it — SURVEY.md §2.4). What this optimizer
preserves is the ALGORITHM: post-freeze updates use the same
sign(momentum+error)·scale quantity every rank would agree on after the
compressed allreduce, with the same error-feedback recursion — so loss
curves match the reference's, and the compression hook is a single
function (``_compress``) a DCN-scale deployment can move into a
shard_map collective.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime import optim as optim_lib


class OnebitAdamState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any
    error: Any       # worker error feedback (comm/nccl.py worker_error)


def _compress(x, error):
    """Error-compensated 1-bit compression (compressed_allreduce,
    comm/nccl.py:47): sign bits + one fp scale; the residual feeds back.
    Scale is the RMS — norm/sqrt(numel), the reference's worker_scale
    (nccl.py:66) — and sign(0) maps to +1 like the reference's bool trick.
    The wire-format collective lives in comm/compressed.py."""
    corrected = x + error
    scale = jnp.linalg.norm(corrected) / jnp.sqrt(corrected.size)
    compressed = jnp.where(corrected >= 0, scale, -scale)
    new_error = corrected - compressed
    return compressed, new_error


def _bias_corrections(step, b1, b2, bias_correction):
    """Shared Adam bias-correction terms (step already incremented)."""
    if bias_correction:
        return (1.0 - b1 ** step.astype(jnp.float32),
                1.0 - b2 ** step.astype(jnp.float32))
    return jnp.float32(1.0), jnp.float32(1.0)


def onebit_adam(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                freeze_step=100, adam_w_mode=True, bias_correction=True):
    """Optimizer pair (reference OnebitAdam :14)."""

    def init(params):
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return OnebitAdamState(step=jnp.zeros([], jnp.int32),
                               mu=zeros(), nu=zeros(), error=zeros())

    def update(grads, state, params, lr):
        step = state.step + 1
        bc1, bc2 = _bias_corrections(step, b1, b2, bias_correction)
        warm = step <= freeze_step

        def leaf_update(g, m, v, e, p):
            m_new = b1 * m + (1.0 - b1) * g
            # warmup: exact Adam, variance updates, no compression
            v_warm = b2 * v + (1.0 - b2) * g * g
            upd_warm = -lr * (m_new / bc1) / (jnp.sqrt(v_warm / bc2) + eps)
            # post-freeze: compressed momentum, frozen variance
            m_comp, e_new = _compress(m_new, e)
            upd_frozen = -lr * (m_comp / bc1) / (jnp.sqrt(v / bc2) + eps)

            m_out = jnp.where(warm, m_new, m_comp)  # ranks stay in sync
            v_out = jnp.where(warm, v_warm, v)
            e_out = jnp.where(warm, e, e_new)
            upd = jnp.where(warm, upd_warm, upd_frozen)
            if adam_w_mode and weight_decay > 0.0:
                upd = upd - lr * weight_decay * p
            return upd, m_out, v_out, e_out

        flat_g, treedef = jax.tree.flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_e = treedef.flatten_up_to(state.error)
        flat_p = treedef.flatten_up_to(params)
        out = [leaf_update(g, m, v, e, p) for g, m, v, e, p in
               zip(flat_g, flat_m, flat_v, flat_e, flat_p)]
        updates = treedef.unflatten([o[0] for o in out])
        new_state = OnebitAdamState(
            step=step,
            mu=treedef.unflatten([o[1] for o in out]),
            nu=treedef.unflatten([o[2] for o in out]),
            error=treedef.unflatten([o[3] for o in out]))
        return updates, new_state

    return optim_lib.Optimizer(init, update)


def make_global_dist_state(state_cls, params, world):
    """GLOBAL-layout init for the engine-facing 1-bit optimizers.

    The engine stores optimizer state as global jax.Arrays; the per-rank
    error-feedback buffers are laid out flat with the rank dim folded in
    (worker_error [world*P], server_error [world*(P/world)]) and sharded
    over the data axis, so that inside the engine's shard_map each rank's
    local block is exactly the [P] / [P/world] buffer the distributed
    ``update`` expects. Shared by the Adam and LAMB dist-state layouts
    (identical field structure)."""
    from deepspeed_tpu.comm.compressed import padded_numel
    zeros = lambda fn: jax.tree.map(fn, params)  # noqa: E731
    return state_cls(
        step=jnp.zeros([], jnp.int32),
        mu=zeros(lambda p: jnp.zeros(p.shape, jnp.float32)),
        nu=zeros(lambda p: jnp.zeros(p.shape, jnp.float32)),
        worker_error=zeros(lambda p: jnp.zeros(
            (world * padded_numel(p.size, world),), jnp.float32)),
        server_error=zeros(lambda p: jnp.zeros(
            (padded_numel(p.size, world),), jnp.float32)))


def onebit_adam_engine(axis_name, world, **kw):
    """Engine-facing wrapper over :func:`onebit_adam_distributed`:
    ``init`` builds the global layout (:func:`make_global_dist_state`);
    ``update`` IS the distributed update and must run inside shard_map
    with ``axis_name`` bound."""
    base = onebit_adam_distributed(axis_name, world, **kw)
    return optim_lib.Optimizer(
        lambda params: make_global_dist_state(
            OnebitAdamDistState, params, world),
        base.update)


class OnebitAdam:
    """API-parity shell (reference OnebitAdam ctor surface)."""

    def __new__(cls, params=None, lr=1e-3, freeze_step=100,
                betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                cuda_aware=False, comm_backend_name="xla", **_):
        return onebit_adam(b1=betas[0], b2=betas[1], eps=eps,
                           weight_decay=weight_decay, freeze_step=freeze_step)


class OnebitAdamDistState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any
    worker_error: Any   # per-leaf flat [P] (comm/nccl.py worker_error)
    server_error: Any   # per-leaf flat [P / world] (server_error)


def onebit_adam_distributed(axis_name, world, b1=0.9, b2=0.999, eps=1e-8,
                            weight_decay=0.0, freeze_step=100,
                            adam_w_mode=True, bias_correction=True):
    """1-bit Adam with the REAL compressed collective in the loop.

    The reference dataflow (onebit/adam.py:14 + comm/nccl.py:47): each dp
    rank updates momentum from its LOCAL gradient, then the momenta are
    averaged with the error-compensated 1-bit allreduce
    (comm/compressed.py). ``update(grads, state, params, lr)`` must run
    INSIDE shard_map/pjit with ``axis_name`` bound and ``grads`` being the
    rank-local (unreduced) gradients; warmup steps use an exact pmean.
    ``world`` is the static axis size (error-buffer layout).
    """
    from deepspeed_tpu.comm.compressed import (compressed_allreduce,
                                               padded_numel)

    def init(params):
        zeros = lambda fn: jax.tree.map(fn, params)  # noqa: E731
        return OnebitAdamDistState(
            step=jnp.zeros([], jnp.int32),
            mu=zeros(lambda p: jnp.zeros(p.shape, jnp.float32)),
            nu=zeros(lambda p: jnp.zeros(p.shape, jnp.float32)),
            worker_error=zeros(lambda p: jnp.zeros(
                (padded_numel(p.size, world),), jnp.float32)),
            server_error=zeros(lambda p: jnp.zeros(
                (padded_numel(p.size, world) // world,), jnp.float32)))

    def update(grads, state, params, lr):
        step = state.step + 1
        bc1, bc2 = _bias_corrections(step, b1, b2, bias_correction)
        warm = step <= freeze_step

        def leaf(g, m, v, we, se, p):
            g = g.astype(jnp.float32)
            m_local = b1 * m + (1.0 - b1) * g

            # the two phases run under lax.cond so only ONE collective set
            # executes per step (warm is replica-uniform): the warmup's
            # exact fp32 pmean, or the 1-bit wire format — running both
            # (jnp.where) would make total traffic WORSE than plain Adam
            def warm_branch(operands):
                m_local, v, we, se, g = operands
                m_exact = jax.lax.pmean(m_local, axis_name)
                v_new = b2 * v + (1.0 - b2) * \
                    jax.lax.pmean(g, axis_name) ** 2
                return m_exact, v_new, we, se

            def frozen_branch(operands):
                m_local, v, we, se, _ = operands
                m_flat, we_new, se_new = compressed_allreduce(
                    m_local.reshape(-1), we, se, axis_name)
                return m_flat.reshape(m_local.shape), v, we_new, se_new

            m_out, v_out, we_out, se_out = jax.lax.cond(
                warm, warm_branch, frozen_branch, (m_local, v, we, se, g))
            upd = -lr * (m_out / bc1) / (jnp.sqrt(v_out / bc2) + eps)
            if adam_w_mode and weight_decay > 0.0:
                upd = upd - lr * weight_decay * p
            return upd.astype(p.dtype), m_out, v_out, we_out, se_out

        flat_g, treedef = jax.tree.flatten(grads)
        out = [leaf(g, m, v, we, se, p) for g, m, v, we, se, p in zip(
            flat_g,
            treedef.flatten_up_to(state.mu),
            treedef.flatten_up_to(state.nu),
            treedef.flatten_up_to(state.worker_error),
            treedef.flatten_up_to(state.server_error),
            treedef.flatten_up_to(params))]
        updates = treedef.unflatten([o[0] for o in out])
        new_state = OnebitAdamDistState(
            step=step,
            mu=treedef.unflatten([o[1] for o in out]),
            nu=treedef.unflatten([o[2] for o in out]),
            worker_error=treedef.unflatten([o[3] for o in out]),
            server_error=treedef.unflatten([o[4] for o in out]))
        return updates, new_state

    return optim_lib.Optimizer(init, update)
