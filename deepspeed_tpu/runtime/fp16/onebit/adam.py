"""1-bit Adam — error-compensated sign-compressed momentum.

Rebuild of deepspeed/runtime/fp16/onebit/adam.py:14 (+ the compressed
allreduce backends comm/nccl.py:47, comm/mpi.py:170). Algorithm semantics
are identical: a ``freeze_step`` warmup of exact Adam, then the variance
term freezes and the momentum is communicated 1-bit (sign + per-tensor
scale) with worker-side error feedback.

TPU-native note: the reference compresses because its inter-node fabric is
slow Ethernet; XLA's grad psum over ICI doesn't expose a hook to compress
in-flight (and ICI rarely needs it — SURVEY.md §2.4). What this optimizer
preserves is the ALGORITHM: post-freeze updates use the same
sign(momentum+error)·scale quantity every rank would agree on after the
compressed allreduce, with the same error-feedback recursion — so loss
curves match the reference's, and the compression hook is a single
function (``_compress``) a DCN-scale deployment can move into a
shard_map collective.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime import optim as optim_lib


class OnebitAdamState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any
    error: Any       # worker error feedback (comm/nccl.py worker_error)


def _compress(x, error):
    """Error-compensated 1-bit compression (compressed_allreduce,
    comm/nccl.py:47): sign bits + one fp scale; the residual feeds back.
    Scale is the RMS — norm/sqrt(numel), the reference's worker_scale
    (nccl.py:66) — and sign(0) maps to +1 like the reference's bool trick.
    The wire-format collective lives in comm/compressed.py."""
    corrected = x + error
    scale = jnp.linalg.norm(corrected) / jnp.sqrt(corrected.size)
    compressed = jnp.where(corrected >= 0, scale, -scale)
    new_error = corrected - compressed
    return compressed, new_error


def onebit_adam(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                freeze_step=100, adam_w_mode=True, bias_correction=True):
    """Optimizer pair (reference OnebitAdam :14)."""

    def init(params):
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return OnebitAdamState(step=jnp.zeros([], jnp.int32),
                               mu=zeros(), nu=zeros(), error=zeros())

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)
        warm = step <= freeze_step

        def leaf_update(g, m, v, e, p):
            m_new = b1 * m + (1.0 - b1) * g
            # warmup: exact Adam, variance updates, no compression
            v_warm = b2 * v + (1.0 - b2) * g * g
            upd_warm = -lr * (m_new / bc1) / (jnp.sqrt(v_warm / bc2) + eps)
            # post-freeze: compressed momentum, frozen variance
            m_comp, e_new = _compress(m_new, e)
            upd_frozen = -lr * (m_comp / bc1) / (jnp.sqrt(v / bc2) + eps)

            m_out = jnp.where(warm, m_new, m_comp)  # ranks stay in sync
            v_out = jnp.where(warm, v_warm, v)
            e_out = jnp.where(warm, e, e_new)
            upd = jnp.where(warm, upd_warm, upd_frozen)
            if adam_w_mode and weight_decay > 0.0:
                upd = upd - lr * weight_decay * p
            return upd, m_out, v_out, e_out

        flat_g, treedef = jax.tree.flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_e = treedef.flatten_up_to(state.error)
        flat_p = treedef.flatten_up_to(params)
        out = [leaf_update(g, m, v, e, p) for g, m, v, e, p in
               zip(flat_g, flat_m, flat_v, flat_e, flat_p)]
        updates = treedef.unflatten([o[0] for o in out])
        new_state = OnebitAdamState(
            step=step,
            mu=treedef.unflatten([o[1] for o in out]),
            nu=treedef.unflatten([o[2] for o in out]),
            error=treedef.unflatten([o[3] for o in out]))
        return updates, new_state

    return optim_lib.Optimizer(init, update)


class OnebitAdam:
    """API-parity shell (reference OnebitAdam ctor surface)."""

    def __new__(cls, params=None, lr=1e-3, freeze_step=100,
                betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                cuda_aware=False, comm_backend_name="xla", **_):
        return onebit_adam(b1=betas[0], b2=betas[1], eps=eps,
                           weight_decay=weight_decay, freeze_step=freeze_step)
